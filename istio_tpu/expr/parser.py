"""Expression-language parser: Go-expression surface syntax → AST.

The reference reuses go/parser and post-processes its tree
(mixer/pkg/expr/expr.go:287-436). We have no Go parser to lean on, so this
is a small hand-rolled tokenizer + precedence-climbing parser for the same
grammar:

  expr    = or_expr
  binary operators, loosest to tightest (Go precedence levels):
      ||                      (LOR)
      &&                      (LAND)
      == != < <= > >=         (EQ NEQ LSS LEQ GTR GEQ)
      + - | ^                 (ADD SUB OR XOR)
      * / % << >> &           (MUL QUO REM SHL SHR AND)
  unary   = [!|-] postfix
  postfix = primary { "[" expr "]" | "." IDENT "(" args ")" }
  primary = literal | dotted-name [ "(" args ")" ] | "(" expr ")"

Notes preserved from the reference semantics:
  * a dotted name (``a.b.c``) is ONE flat attribute, not member access
    (generateVarName, expr.go:270-285);
  * in ``a.b.startsWith("x")`` the final component is the method name and
    the rest is the receiver attribute (flattenSelectors, expr.go:384);
  * ``true``/``false`` are constants, all other identifiers are attributes;
  * every string literal is first tried as a Go duration ("20ms" parses to
    a DURATION constant — newConstant, expr.go:143-146);
  * all operators become named functions; whether a function EXISTS is a
    type-check question, not a parse question (so ``x/y`` parses fine and
    later fails with "unknown function: QUO").
"""
from __future__ import annotations

import re

from istio_tpu.attribute.types import ValueType, parse_go_duration
from istio_tpu.expr.exprs import (Constant, Expression, FunctionCall,
                                  Variable)


class ParseError(ValueError):
    pass


_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<float>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+[eE][+-]?\d+)
  | (?P<int>0[xX][0-9a-fA-F]+|\d+)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)*)
  | (?P<str>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*'|`[^`]*`)
  | (?P<op>\|\||&&|==|!=|<=|>=|<<|>>|&\^|[-+*/%<>!|^&()\[\],.])
""", re.VERBOSE)

# operator token -> (function name, precedence); Go spec precedence levels
_BINARY = {
    "||": ("LOR", 1),
    "&&": ("LAND", 2),
    "==": ("EQ", 3), "!=": ("NEQ", 3), "<": ("LSS", 3), "<=": ("LEQ", 3),
    ">": ("GTR", 3), ">=": ("GEQ", 3),
    "+": ("ADD", 4), "-": ("SUB", 4), "|": ("OR", 4), "^": ("XOR", 4),
    "*": ("MUL", 5), "/": ("QUO", 5), "%": ("REM", 5),
    "<<": ("SHL", 5), ">>": ("SHR", 5), "&": ("AND", 5), "&^": ("ANDNOT", 5),
}
_UNARY = {"!": "NOT", "-": "SUB", "+": "ADD"}

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "\\": "\\", '"': '"',
            "'": "'", "a": "\a", "b": "\b", "f": "\f", "v": "\v", "0": "\0"}


def _unquote(text: str) -> str:
    if text.startswith("`"):
        return text[1:-1]
    body = text[1:-1]
    out: list[str] = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            nxt = body[i + 1]
            if nxt == "x" and i + 3 < len(body):
                out.append(chr(int(body[i + 2:i + 4], 16)))
                i += 4
                continue
            if nxt == "u" and i + 5 < len(body):
                out.append(chr(int(body[i + 2:i + 6], 16)))
                i += 6
                continue
            out.append(_ESCAPES.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


class _Token:
    __slots__ = ("kind", "text")

    def __init__(self, kind: str, text: str):
        self.kind = kind
        self.text = text

    def __repr__(self) -> str:
        return f"{self.kind}:{self.text}"


def _tokenize(src: str) -> list[_Token]:
    tokens: list[_Token] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise ParseError(f"unable to parse expression '{src}': "
                             f"bad character {src[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        tokens.append(_Token(kind, m.group()))
    tokens.append(_Token("eof", ""))
    return tokens


def _string_constant(raw: str) -> Constant:
    """String literal → DURATION if it parses as a Go duration, else
    STRING (reference: newConstant, expr.go:136-150)."""
    unq = _unquote(raw)
    # cheap prefilter before the full duration grammar: every Go
    # duration starts with a digit/sign/dot and ends with a unit
    # letter — the full parse on every literal was ~20% of a 10k-rule
    # snapshot compile
    # unit-less zeros ("0", "+0", "-0") are the only valid durations
    # not ending in a unit letter (time.ParseDuration)
    if unq in ("0", "+0", "-0") or (unq and unq[0] in "0123456789+-."
                                    and unq[-1] in "smh"):
        try:
            td = parse_go_duration(unq)
            return Constant(str_value=raw, vtype=ValueType.DURATION,
                            value=td)
        except ValueError:
            pass
    return Constant(str_value=raw, vtype=ValueType.STRING, value=unq)


class _Parser:
    def __init__(self, src: str):
        self.src = src
        self.toks = _tokenize(src)
        self.i = 0

    def peek(self) -> _Token:
        return self.toks[self.i]

    def next(self) -> _Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str) -> None:
        t = self.next()
        if t.text != text:
            raise ParseError(f"unable to parse expression '{self.src}': "
                             f"expected {text!r}, found {t.text!r}")

    # --- grammar ---

    def parse(self) -> Expression:
        e = self.binary(1)
        if self.peek().kind != "eof":
            raise ParseError(f"unable to parse expression '{self.src}': "
                             f"trailing tokens at {self.peek().text!r}")
        return e

    def binary(self, min_prec: int) -> Expression:
        left = self.unary()
        while True:
            t = self.peek()
            info = _BINARY.get(t.text) if t.kind == "op" else None
            if info is None or info[1] < min_prec:
                return left
            self.next()
            right = self.binary(info[1] + 1)  # left-associative
            left = Expression(fn=FunctionCall(name=info[0], args=[left, right]))

    def unary(self) -> Expression:
        t = self.peek()
        if t.kind == "op" and t.text in _UNARY:
            self.next()
            operand = self.unary()
            return Expression(fn=FunctionCall(name=_UNARY[t.text], args=[operand]))
        return self.postfix()

    def postfix(self) -> Expression:
        e = self.primary()
        while True:
            t = self.peek()
            if t.text == "[":
                self.next()
                idx = self.binary(1)
                self.expect("]")
                e = Expression(fn=FunctionCall(name="INDEX", args=[e, idx]))
            elif t.text == ".":
                # method call anchored on a non-identifier primary:
                # ("lit").startsWith(...), f(x).matches(...)
                self.next()
                name_tok = self.next()
                if name_tok.kind != "ident" or "." in name_tok.text:
                    raise ParseError(
                        f"unable to parse expression '{self.src}': "
                        f"expected method name after '.'")
                self.expect("(")
                args = self.call_args()
                e = Expression(fn=FunctionCall(name=name_tok.text, args=args,
                                               target=e))
            else:
                return e

    def call_args(self) -> list[Expression]:
        args: list[Expression] = []
        if self.peek().text == ")":
            self.next()
            return args
        while True:
            args.append(self.binary(1))
            t = self.next()
            if t.text == ")":
                return args
            if t.text != ",":
                raise ParseError(f"unable to parse expression '{self.src}': "
                                 f"expected ',' or ')', found {t.text!r}")

    def primary(self) -> Expression:
        t = self.next()
        if t.text == "(":
            e = self.binary(1)
            self.expect(")")
            return e
        if t.kind == "int":
            return Expression(const_=Constant(
                str_value=t.text, vtype=ValueType.INT64, value=int(t.text, 0)))
        if t.kind == "float":
            return Expression(const_=Constant(
                str_value=t.text, vtype=ValueType.DOUBLE, value=float(t.text)))
        if t.kind == "str":
            return Expression(const_=_string_constant(t.text))
        if t.kind == "ident":
            # case-insensitive like the reference: expr.go:344 lowercases
            # the identifier before comparing against true/false
            low = t.text.lower()
            if low in ("true", "false"):
                return Expression(const_=Constant(
                    str_value=low, vtype=ValueType.BOOL, value=(low == "true")))
            if self.peek().text == "(":
                # call: last dotted component is the function name,
                # the rest (if any) is the receiver attribute
                # (reference: flattenSelectors + process CallExpr branch)
                self.next()
                args = self.call_args()
                if "." in t.text:
                    recv, meth = t.text.rsplit(".", 1)
                    return Expression(fn=FunctionCall(
                        name=meth, args=args,
                        target=Expression(var=Variable(name=recv))))
                return Expression(fn=FunctionCall(name=t.text, args=args))
            return Expression(var=Variable(name=t.text))
        raise ParseError(f"unable to parse expression '{self.src}': "
                         f"unexpected token {t.text!r}")


def parse(src: str) -> Expression:
    """Parse expression source into the simplified AST
    (role of reference Parse, expr.go:424-436)."""
    return _Parser(src).parse()


def extract_eq_matches(src: str) -> dict[str, object]:
    """Hoistable `attr == literal` conjuncts of a match expression — used
    to index rules by destination/protocol (reference: ExtractEQMatches,
    expr.go:446-490: only recurses through LAND)."""
    ex = parse(src)
    out: dict[str, object] = {}

    def record(fn: FunctionCall) -> None:
        if fn.name != "EQ" or len(fn.args) != 2:
            return
        a, b = fn.args
        if a.var is not None and b.const_ is not None:
            out[a.var.name] = b.const_.value
        elif a.const_ is not None and b.var is not None:
            out[b.var.name] = a.const_.value

    def walk(e: Expression) -> None:
        if e.fn is None:
            return
        record(e.fn)
        if e.fn.name != "LAND":
            return
        for arg in e.fn.args:
            walk(arg)

    walk(ex)
    return out
