"""Policy expression language (reference: mixer/pkg/expr + mixer/pkg/il)."""

from istio_tpu.expr.exprs import Expression, Constant, Variable, FunctionCall
from istio_tpu.expr.parser import parse, extract_eq_matches, ParseError
from istio_tpu.expr.checker import (AttributeDescriptorFinder, FunctionMetadata,
                                    eval_type, func_map, TypeError_,
                                    DEFAULT_FUNCS)
from istio_tpu.expr.oracle import (OracleProgram, OracleEvaluator, EvalError,
                                   evaluate)

__all__ = [
    "Expression", "Constant", "Variable", "FunctionCall",
    "parse", "extract_eq_matches", "ParseError",
    "AttributeDescriptorFinder", "FunctionMetadata", "eval_type", "func_map",
    "TypeError_", "DEFAULT_FUNCS",
    "OracleProgram", "OracleEvaluator", "EvalError", "evaluate",
]
