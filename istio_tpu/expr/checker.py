"""Static type checking of expressions against an attribute manifest.

Role of the reference's EvalType walk (mixer/pkg/expr/expr.go:93-268) and
FuncMap (func.go:39-85): intrinsics EQ/NEQ/OR/LOR/LAND/INDEX plus extern
metadata; any other function name — including parsed-but-undefined
operators like QUO or NOT — is an "unknown function" error.
"""
from __future__ import annotations

import dataclasses

from istio_tpu.attribute.types import ValueType
from istio_tpu.expr.exprs import Expression, FunctionCall


class TypeError_(ValueError):
    """Expression type-check failure (named to avoid shadowing builtins)."""


@dataclasses.dataclass(frozen=True)
class FunctionMetadata:
    name: str
    return_type: ValueType
    argument_types: tuple[ValueType, ...]
    instance: bool = False
    target_type: ValueType = ValueType.UNSPECIFIED


INTRINSICS = [
    FunctionMetadata("EQ", ValueType.BOOL,
                     (ValueType.UNSPECIFIED, ValueType.UNSPECIFIED)),
    FunctionMetadata("NEQ", ValueType.BOOL,
                     (ValueType.UNSPECIFIED, ValueType.UNSPECIFIED)),
    # ordered comparisons (reference expr/func.go LT/LEQ/GT/GEQ): both
    # operands the same type; ordering defined for numeric/string/
    # time-like values (oracle enforces at eval time)
    FunctionMetadata("LSS", ValueType.BOOL,
                     (ValueType.UNSPECIFIED, ValueType.UNSPECIFIED)),
    FunctionMetadata("LEQ", ValueType.BOOL,
                     (ValueType.UNSPECIFIED, ValueType.UNSPECIFIED)),
    FunctionMetadata("GTR", ValueType.BOOL,
                     (ValueType.UNSPECIFIED, ValueType.UNSPECIFIED)),
    FunctionMetadata("GEQ", ValueType.BOOL,
                     (ValueType.UNSPECIFIED, ValueType.UNSPECIFIED)),
    FunctionMetadata("OR", ValueType.UNSPECIFIED,
                     (ValueType.UNSPECIFIED, ValueType.UNSPECIFIED)),
    FunctionMetadata("LOR", ValueType.BOOL, (ValueType.BOOL, ValueType.BOOL)),
    FunctionMetadata("LAND", ValueType.BOOL, (ValueType.BOOL, ValueType.BOOL)),
    FunctionMetadata("INDEX", ValueType.STRING,
                     (ValueType.STRING_MAP, ValueType.STRING)),
]

# Extern type metadata (reference: mixer/pkg/il/runtime/externs.go:42-79).
EXTERN_METADATA = [
    FunctionMetadata("ip", ValueType.IP_ADDRESS, (ValueType.STRING,)),
    FunctionMetadata("timestamp", ValueType.TIMESTAMP, (ValueType.STRING,)),
    FunctionMetadata("match", ValueType.BOOL,
                     (ValueType.STRING, ValueType.STRING)),
    FunctionMetadata("matches", ValueType.BOOL, (ValueType.STRING,),
                     instance=True, target_type=ValueType.STRING),
    FunctionMetadata("startsWith", ValueType.BOOL, (ValueType.STRING,),
                     instance=True, target_type=ValueType.STRING),
    FunctionMetadata("endsWith", ValueType.BOOL, (ValueType.STRING,),
                     instance=True, target_type=ValueType.STRING),
]


def func_map(extra: list[FunctionMetadata] | None = None) -> dict[str, FunctionMetadata]:
    m = {f.name: f for f in INTRINSICS}
    for f in EXTERN_METADATA:
        m[f.name] = f
    for f in extra or []:
        m[f.name] = f
    return m


DEFAULT_FUNCS = func_map()


class AttributeDescriptorFinder:
    """Attribute vocabulary: name → declared ValueType
    (role of reference expr/finder.go NewFinder)."""

    def __init__(self, manifest: dict[str, ValueType]):
        self._manifest = dict(manifest)

    def get_attribute(self, name: str) -> ValueType | None:
        return self._manifest.get(name)

    def names(self) -> list[str]:
        return list(self._manifest)

    def merged_with(self, other: "AttributeDescriptorFinder") -> "AttributeDescriptorFinder":
        merged = dict(self._manifest)
        merged.update(other._manifest)
        return AttributeDescriptorFinder(merged)


def eval_type(e: Expression, attrs: AttributeDescriptorFinder,
              funcs: dict[str, FunctionMetadata] | None = None) -> ValueType:
    """Infer the expression's static type; raises TypeError_ on unknown
    attributes/functions or argument type mismatches (reference:
    Expression.EvalType expr.go:93, Function.EvalType :202-268)."""
    fmap = DEFAULT_FUNCS if funcs is None else funcs
    if e.const_ is not None:
        return e.const_.vtype
    if e.var is not None:
        vt = attrs.get_attribute(e.var.name)
        if vt is None:
            raise TypeError_(f"unknown attribute {e.var.name}")
        return vt
    assert e.fn is not None
    return _fn_eval_type(e.fn, attrs, fmap)


def _fn_eval_type(f: FunctionCall, attrs: AttributeDescriptorFinder,
                  fmap: dict[str, FunctionMetadata]) -> ValueType:
    meta = fmap.get(f.name)
    if meta is None:
        raise TypeError_(f"unknown function: {f.name}")

    tmpl_type = ValueType.UNSPECIFIED

    if f.target is not None:
        if not meta.instance:
            raise TypeError_(
                f"invoking regular function on instance method: {f.name}")
        target_type = eval_type(f.target, attrs, fmap)
        if meta.target_type == ValueType.UNSPECIFIED:
            tmpl_type = target_type
        elif target_type != meta.target_type:
            raise TypeError_(
                f"{f} target typeError got {target_type}, "
                f"expected {meta.target_type}")
    elif meta.instance:
        raise TypeError_(f"invoking instance method without an instance: {f.name}")

    # The reference only rejects too-few args (expr.go:234, excess-arg
    # check is a TODO at :259 and crashes later in extern reflection);
    # rejecting excess here keeps the error typed instead of crashing.
    if len(f.args) != len(meta.argument_types):
        raise TypeError_(
            f"{f} arity mismatch. Got {len(f.args)} arg(s), "
            f"expected {len(meta.argument_types)} arg(s)")

    for idx in range(min(len(f.args), len(meta.argument_types))):
        arg_type = eval_type(f.args[idx], attrs, fmap)
        expected = meta.argument_types[idx]
        if expected == ValueType.UNSPECIFIED:
            if tmpl_type == ValueType.UNSPECIFIED:
                tmpl_type = arg_type
                continue
            expected = tmpl_type
        if arg_type != expected:
            raise TypeError_(
                f"{f} arg {idx + 1} ({f.args[idx]}) typeError got "
                f"{arg_type}, expected {expected}")

    if meta.return_type == ValueType.UNSPECIFIED:
        return tmpl_type
    return meta.return_type
