"""Oracle interpreter — the host-side semantics reference.

This is the behavioral twin of the reference's IL compiler + stack-VM
interpreter (mixer/pkg/il/compiler/compiler.go + interpreter/
interpreterRun.go), implemented as a direct AST walk. It is the contract
the TPU tensor compiler is conformance-tested against, and the fallback
engine for expressions the tensor compiler cannot lower.

Semantics reproduced exactly (see compiler.go codegen):
  * attribute resolution failure is a runtime error
    "lookup failed: '<name>'" (interpreterRun.got:396-463);
  * map-key miss is "member lookup failed: '<key>'" (:760-785);
  * `a | b` (OR) evaluates its left side in "soft" mode: attribute
    absence or map-key miss falls through to the right side
    (nilMode nmJmpOnValue, compiler.go:102-117, generateOr :459+);
    soft mode reaches only Var / INDEX / nested-OR positions — any other
    function produces a definite value or a hard error;
  * `&&` / `||` short-circuit (generateLand :373, generateLor :354) — a
    suppressed right side is never evaluated, so its errors never fire;
  * EQ on IP_ADDRESS uses net.IP-style equality and on TIMESTAMP uses
    instant equality (generateEq compiler.go:334-341 Interface case);
  * NEQ is !EQ (:347).
"""
from __future__ import annotations

import datetime
from typing import Any, Mapping

from istio_tpu.attribute.bag import Bag, DictBag, TrackingBag
from istio_tpu.attribute.types import ValueType
from istio_tpu.expr.checker import (AttributeDescriptorFinder, DEFAULT_FUNCS,
                                    FunctionMetadata, eval_type)
from istio_tpu.expr.exprs import Expression, FunctionCall
from istio_tpu.expr.externs import (EXTERNS, ExternError, extern_ip_equal,
                                    extern_timestamp_equal)
from istio_tpu.expr.parser import parse


class EvalError(ValueError):
    """Runtime evaluation error (lookup failure, extern failure)."""


class _Absent(Exception):
    """Internal signal: soft-mode resolution produced no value."""


class OracleProgram:
    """A parsed + type-checked expression bound to a manifest — the
    oracle analog of a compiled IL program."""

    def __init__(self, text: str, finder: AttributeDescriptorFinder,
                 funcs: dict[str, FunctionMetadata] | None = None):
        self.text = text
        self.finder = finder
        self.funcs = DEFAULT_FUNCS if funcs is None else funcs
        self.ast = parse(text)
        self.result_type = eval_type(self.ast, finder, self.funcs)

    @classmethod
    def from_ast(cls, ast, finder: AttributeDescriptorFinder
                 ) -> "OracleProgram":
        """Bind an already-parsed expression (e.g. a compiled ruleset's
        retained atom AST — the disassembler/stepper path)."""
        prog = cls.__new__(cls)
        prog.text = str(ast)
        prog.finder = finder
        prog.funcs = DEFAULT_FUNCS
        prog.ast = ast
        prog.result_type = eval_type(ast, finder, DEFAULT_FUNCS)
        return prog

    # --- public API (role of il/interpreter Interpreter.Eval) ---

    def evaluate(self, bag: Bag) -> Any:
        return self._eval(self.ast, bag)

    def evaluate_with_tracking(self, bag: Bag) -> tuple[Any, TrackingBag]:
        tb = TrackingBag(bag)
        return self._eval(self.ast, tb), tb

    # --- evaluation ---

    def _eval(self, e: Expression, bag: Bag) -> Any:
        if e.const_ is not None:
            return e.const_.value
        if e.var is not None:
            v, ok = bag.get(e.var.name)
            if not ok:
                raise EvalError(f"lookup failed: '{e.var.name}'")
            return v
        assert e.fn is not None
        return self._eval_fn(e.fn, bag)

    def _eval_soft(self, e: Expression, bag: Bag) -> Any:
        """nmJmpOnValue evaluation: raises _Absent instead of a lookup
        error, but only for Var / INDEX / OR shapes; everything else is
        evaluated hard (mirrors which codegen paths honor nilMode)."""
        if e.var is not None:
            v, ok = bag.get(e.var.name)
            if not ok:
                raise _Absent()
            return v
        if e.fn is not None and e.fn.name == "INDEX":
            return self._eval_index(e.fn, bag, soft=True)
        if e.fn is not None and e.fn.name == "OR":
            return self._eval_or(e.fn, bag, soft=True)
        return self._eval(e, bag)

    def _eval_fn(self, f: FunctionCall, bag: Bag) -> Any:
        name = f.name
        if name == "EQ":
            return self._equals(f, bag)
        if name == "NEQ":
            return not self._equals(f, bag)
        if name == "LAND":
            for arg in f.args:
                if not self._eval(arg, bag):
                    return False
            return True
        if name == "LOR":
            for arg in f.args:
                if self._eval(arg, bag):
                    return True
            return False
        if name == "OR":
            return self._eval_or(f, bag, soft=False)
        if name == "INDEX":
            return self._eval_index(f, bag, soft=False)
        if name == "NOT":
            return not self._eval(f.args[0], bag)
        if name in ("LSS", "LEQ", "GTR", "GEQ"):
            return self._ordered(name, f, bag)
        return self._eval_extern(f, bag)

    def _ordered(self, name: str, f: FunctionCall, bag: Bag) -> bool:
        a = self._eval(f.args[0], bag)
        b = self._eval(f.args[1], bag)
        for v in (a, b):
            if not isinstance(v, (int, float, str, datetime.datetime,
                                  datetime.timedelta)) or \
                    isinstance(v, bool):
                raise EvalError(
                    f"unordered operand for {name}: {type(v).__name__}")
        try:
            if name == "LSS":
                return a < b
            if name == "LEQ":
                return a <= b
            if name == "GTR":
                return a > b
            return a >= b
        except TypeError as exc:   # mixed runtime types (bags are untyped)
            raise EvalError(f"unordered operands for {name}: "
                            f"{type(a).__name__} vs {type(b).__name__}"
                            ) from exc

    def _eval_or(self, f: FunctionCall, bag: Bag, soft: bool) -> Any:
        try:
            return self._eval_soft(f.args[0], bag)
        except _Absent:
            pass
        if soft:
            return self._eval_soft(f.args[1], bag)
        return self._eval(f.args[1], bag)

    def _eval_index(self, f: FunctionCall, bag: Bag, soft: bool) -> Any:
        if soft:
            target = self._eval_soft(f.args[0], bag)  # _Absent propagates
            key = self._eval_soft(f.args[1], bag)
        else:
            target = self._eval(f.args[0], bag)
            key = self._eval(f.args[1], bag)
        if not isinstance(key, str):
            raise EvalError(f"error converting value to string: '{key}'")
        found = isinstance(target, Mapping) and key in target
        if isinstance(bag, TrackingBag) and f.args[0].var is not None:
            bag.track_map_key(f.args[0].var.name, key, found)
        if not found:
            if soft:
                raise _Absent()
            raise EvalError(f"member lookup failed: '{key}'")
        return target[key]

    def _equals(self, f: FunctionCall, bag: Bag) -> bool:
        a = self._eval(f.args[0], bag)
        b = self._eval(f.args[1], bag)
        if isinstance(a, bytes) and isinstance(b, bytes):
            return extern_ip_equal(a, b)
        if isinstance(a, datetime.datetime) and isinstance(b, datetime.datetime):
            return extern_timestamp_equal(a, b)
        return a == b

    def _eval_extern(self, f: FunctionCall, bag: Bag) -> Any:
        fn = EXTERNS.get(f.name)
        if fn is None:
            raise EvalError(f"unknown function: {f.name}")
        args: list[Any] = []
        if f.target is not None:
            args.append(self._eval(f.target, bag))
        for arg in f.args:
            args.append(self._eval(arg, bag))
        try:
            return fn(*args)
        except ExternError as exc:
            raise EvalError(str(exc)) from exc


class OracleEvaluator:
    """Caching expression evaluator — role of the reference's IL
    evaluator (mixer/pkg/il/evaluator/evaluator.go:53-185): an LRU of
    compiled programs keyed by expression text, invalidated when the
    attribute vocabulary changes."""

    def __init__(self, finder: AttributeDescriptorFinder, cache_size: int = 4096):
        from istio_tpu.utils.cache import LRUCache
        self._finder = finder
        self._cache = LRUCache(cache_size)

    def change_vocabulary(self, finder: AttributeDescriptorFinder) -> None:
        self._finder = finder
        self._cache.clear()

    def _program(self, text: str) -> OracleProgram:
        prog = self._cache.get(text)
        if prog is None:
            prog = OracleProgram(text, self._finder)
            self._cache.set(text, prog)
        return prog

    def eval(self, text: str, bag: Bag) -> Any:
        return self._program(text).evaluate(bag)

    def eval_string(self, text: str, bag: Bag) -> str:
        v = self.eval(text, bag)
        if not isinstance(v, str):
            raise EvalError(f"expression '{text}' evaluated to {type(v).__name__}, "
                            "expected string")
        return v

    def eval_predicate(self, text: str, bag: Bag) -> bool:
        v = self.eval(text, bag)
        if not isinstance(v, bool):
            raise EvalError(f"expression '{text}' evaluated to {type(v).__name__}, "
                            "expected boolean")
        return v


def evaluate(text: str, values: Mapping[str, Any],
             manifest: dict[str, ValueType]) -> Any:
    """One-shot convenience: parse, check, evaluate over a dict."""
    prog = OracleProgram(text, AttributeDescriptorFinder(manifest))
    return prog.evaluate(DictBag(values))
