"""Expression AST — the simplified Const/Var/Fn tree.

Role of the reference's mixer/pkg/expr Expression (expr.go:78-118): all
operators are normalized to named functions (== -> EQ, && -> LAND, | -> OR,
[] -> INDEX, unary ! -> NOT ...), selector chains like ``a.b.c`` flatten to
single attribute names, and instance-method syntax ``s.startsWith("x")``
becomes a Function with a Target.
"""
from __future__ import annotations

import dataclasses
import datetime
from typing import Optional, Union

from istio_tpu.attribute.types import ValueType, format_go_duration

ConstValue = Union[str, int, float, bool, datetime.timedelta]


@dataclasses.dataclass
class Constant:
    str_value: str          # source text, for round-tripping
    vtype: ValueType
    value: ConstValue

    def __str__(self) -> str:
        return self.str_value


@dataclasses.dataclass
class Variable:
    name: str

    def __str__(self) -> str:
        return "$" + self.name


@dataclasses.dataclass
class FunctionCall:
    name: str
    args: list["Expression"]
    target: Optional["Expression"] = None   # instance-method receiver

    def __str__(self) -> str:
        inner = ", ".join(str(a) for a in self.args)
        prefix = f"{self.target}:" if self.target is not None else ""
        return f"{prefix}{self.name}({inner})"


@dataclasses.dataclass
class Expression:
    """Exactly one of const_/var/fn is set."""
    const_: Optional[Constant] = None
    var: Optional[Variable] = None
    fn: Optional[FunctionCall] = None

    def __str__(self) -> str:
        if self.const_ is not None:
            return str(self.const_)
        if self.var is not None:
            return str(self.var)
        if self.fn is not None:
            return str(self.fn)
        return "<nil>"


def const_expr(value: ConstValue, vtype: ValueType, text: str | None = None) -> Expression:
    if text is None:
        if isinstance(value, datetime.timedelta):
            text = f'"{format_go_duration(value)}"'
        elif isinstance(value, str):
            text = f'"{value}"'
        elif isinstance(value, bool):
            text = "true" if value else "false"
        else:
            text = str(value)
    return Expression(const_=Constant(str_value=text, vtype=vtype, value=value))


def var_expr(name: str) -> Expression:
    return Expression(var=Variable(name=name))


def fn_expr(name: str, *args: Expression, target: Expression | None = None) -> Expression:
    return Expression(fn=FunctionCall(name=name, args=list(args), target=target))
