"""Extern functions callable from expressions.

Behavioral contract from mixer/pkg/il/runtime/externs.go:81-128:
  ip(s)                — parse textual IP to bytes; error on bad input
  ip_equal(a, b)       — net.IP-style equality (v4 == v4-in-v6)
  timestamp(s)         — RFC3339 parse; error on bad input
  timestamp_equal(a,b) — instant equality
  match(str, pattern)  — glob-ish: trailing '*' = prefix, leading '*' =
                         suffix, else exact
  matches(pattern,str) — RE2 regex (unanchored search)
  startsWith / endsWith
"""
from __future__ import annotations

import datetime
import re
from typing import Any, Callable

from istio_tpu.attribute.types import (ip_equal, parse_ip, parse_rfc3339)


class ExternError(ValueError):
    """Runtime error raised by an extern (e.g. unparseable IP)."""


def extern_ip(s: str) -> bytes:
    try:
        return parse_ip(s)
    except ValueError:
        raise ExternError(f"could not convert {s} to IP_ADDRESS")


def extern_ip_equal(a: bytes, b: bytes) -> bool:
    return ip_equal(a, b)


def extern_timestamp(s: str) -> datetime.datetime:
    try:
        return parse_rfc3339(s)
    except ValueError:
        raise ExternError(
            f"could not convert '{s}' to TIMESTAMP. expected format: RFC3339")


def extern_timestamp_equal(a: datetime.datetime, b: datetime.datetime) -> bool:
    return a == b


def extern_match(value: str, pattern: str) -> bool:
    if pattern.endswith("*"):
        return value.startswith(pattern[:-1])
    if pattern.startswith("*"):
        return value.endswith(pattern[1:])
    return value == pattern


def extern_matches(pattern: str, value: str) -> bool:
    try:
        return re.search(pattern, value) is not None
    except re.error as exc:
        raise ExternError(f"bad regex {pattern!r}: {exc}")


def extern_starts_with(value: str, prefix: str) -> bool:
    return value.startswith(prefix)


def extern_ends_with(value: str, suffix: str) -> bool:
    return value.endswith(suffix)


EXTERNS: dict[str, Callable[..., Any]] = {
    "ip": extern_ip,
    "ip_equal": extern_ip_equal,
    "timestamp": extern_timestamp,
    "timestamp_equal": extern_timestamp_equal,
    "match": extern_match,
    "matches": extern_matches,
    "startsWith": extern_starts_with,
    "endsWith": extern_ends_with,
}
