"""CLI entry points (reference: the cobra commands in mixer/cmd,
pilot/cmd, security/cmd, broker/cmd — SURVEY.md §1 L7):

    mixs            — mixer server (cmd/mixs)
    mixc            — mixer check/report client (cmd/mixc)
    pilot-discovery — discovery server (pilot/cmd/pilot-discovery)
    pilot-agent     — sidecar agent (pilot/cmd/pilot-agent)
    istioctl        — config CRUD + kube-inject (pilot/cmd/istioctl)
    istio_ca        — certificate authority (security/cmd/istio_ca)
    node_agent      — workload cert rotation (security/cmd/node_agent)
    brks            — OSB broker (broker/cmd/brks)

All are argparse subcommands of one `python -m istio_tpu.cmd` tool;
each also has a `main()` for setuptools console_scripts.
"""
