"""One multi-tool CLI: `python -m istio_tpu.cmd <command> ...`."""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def _serve_forever() -> None:
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass


def cmd_mixs(args: argparse.Namespace) -> int:
    """mixer server (cmd/mixs: server/server.go assembly)."""
    from istio_tpu.api import MixerGrpcServer
    from istio_tpu.runtime import FsStore, MemStore, RuntimeServer, \
        ServerArgs
    if args.trace_zipkin_url or args.trace_log_spans:
        # pkg/tracing/config.go:87 Configure — spans cover the serving
        # pipeline stages (batch/queue-wait/tensorize/device/overlay)
        from istio_tpu.utils import tracing
        tracing.configure("mixs", zipkin_url=args.trace_zipkin_url,
                          log_spans=args.trace_log_spans)
    store = FsStore(args.config_store) if args.config_store else MemStore()
    runtime = RuntimeServer(store, ServerArgs(
        batch_window_s=args.batch_window_us / 1e6,
        max_batch=args.max_batch,
        # overload resilience (runtime/resilience.py + batcher
        # admission control)
        default_check_deadline_ms=args.default_check_deadline_ms,
        check_queue_cap=args.check_queue_cap,
        report_queue_cap=args.report_queue_cap,
        brownout=args.brownout,
        check_fail_policy=args.check_fail_policy,
        breaker_failures=args.breaker_failures,
        breaker_reset_s=args.breaker_reset_ms / 1e3,
        # adapter-executor plane (runtime/executor.py): host actions
        # bulkheaded per handler, deadline-bounded, breaker-guarded
        host_fail_policy=args.host_fail_policy,
        executor_workers=args.executor_workers,
        executor_queue_cap=args.executor_queue_cap,
        host_action_timeout_ms=args.host_action_timeout_ms,
        host_executor=not args.no_host_executor,
        host_breaker_failures=args.host_breaker_failures,
        host_breaker_reset_s=args.host_breaker_reset_ms / 1e3,
        # config canary (istio_tpu/canary): record live traffic,
        # shadow-replay rebuilt snapshots, veto divergent swaps
        canary=args.canary,
        canary_max_divergence=args.canary_max_divergence,
        canary_capacity=args.canary_capacity,
        canary_sample_every=args.canary_sample_every,
        canary_replay_limit=args.canary_replay_limit,
        canary_waivers=tuple(args.canary_waive or ()),
        # sharded serving + delta compilation (istio_tpu/sharding,
        # compiler/cache.py)
        shards=args.shards,
        replicas=args.replicas,
        jax_compile_cache_dir=args.jax_compile_cache_dir,
        delta_compile=not args.no_delta_compile,
        shard_rebalance_budget=args.shard_rebalance_budget,
        # latency plane: continuous batching + check-cache grants
        continuous_batching=args.continuous_batching,
        continuous_depth=args.continuous_depth,
        check_grants=args.check_grants,
        grant_ttl_floor_s=args.grant_ttl_floor_s,
        grant_ttl_cap_s=args.grant_ttl_cap_s,
        # tail-latency forensics (runtime/forensics.py): flight
        # recorder threshold/mode + the /debug/profile capture dir
        flight_recorder=not args.no_flight_recorder,
        slow_threshold_ms=args.slow_threshold_ms,
        slow_adaptive=args.slow_adaptive,
        profile_dir=args.profile_dir,
        # mesh audit plane (runtime/audit.py): background invariant
        # auditor + fault explainability; /debug/audit + /debug/slo
        audit=not args.no_audit,
        audit_interval_s=args.audit_interval_ms / 1e3,
        # secure serving plane (istio_tpu/secure): mTLS posture +
        # CA-driven workload identity rotation parameters
        mtls=args.mtls,
        mtls_identity=args.mtls_identity,
        mtls_cert_ttl_minutes=args.mtls_cert_ttl_minutes,
        mtls_rotation_fraction=args.mtls_rotation_fraction))
    tls = None
    wi = None
    if args.mtls != "off":
        from istio_tpu.secure.mtls import ServingCerts

        def _read(path: str) -> bytes:
            with open(path, "rb") as f:
                return f.read()

        if args.tls_key and args.tls_cert and args.tls_root:
            # static operator-provisioned serving certs (no rotation)
            tls = ServingCerts(_read(args.tls_key),
                               _read(args.tls_cert),
                               _read(args.tls_root))
        elif args.ca_address:
            # CA-driven: obtain the serving bundle over the CSR flow,
            # rotate on the adapter-executor maintenance lane; every
            # rotation hot-swaps the live fronts AND revokes grants
            # keyed to the rotated identity (sign → swap → revoke)
            from istio_tpu.secure.identity import WorkloadIdentity
            from istio_tpu.security.ca_service import CAClient
            ca_root = _read(args.ca_root_cert) if args.ca_root_cert \
                else None
            credential = _read(args.bootstrap_cert) \
                if args.bootstrap_cert else b""
            wi = WorkloadIdentity(
                CAClient(args.ca_address, root_cert_pem=ca_root),
                args.mtls_identity,
                ttl_minutes=args.mtls_cert_ttl_minutes,
                rotation_fraction=args.mtls_rotation_fraction,
                credential=credential,
                dns_names=(args.tls_dns,))
            try:
                key_pem, cert_pem, root_pem = wi.ensure()
            except Exception as exc:
                print(f"mixs: initial serving-cert issuance failed "
                      f"({exc}); refusing to serve {args.mtls} without "
                      "credentials", file=sys.stderr)
                runtime.close()
                return 2
            tls = ServingCerts(key_pem, cert_pem, root_pem)
            wi.subscribe(lambda b: tls.rotate(b[0], b[1], b[2]))
            if runtime.grants is not None:
                wi.subscribe(lambda b: runtime.grants
                             .on_identity_rotate(wi.identity))
            if runtime.executor is not None:
                runtime.executor.register_refreshable(
                    "workload_identity", wi)
        else:
            print("mixs: --mtls needs serving credentials: either "
                  "--tls-key/--tls-cert/--tls-root or --ca-address",
                  file=sys.stderr)
            runtime.close()
            return 2
    server = MixerGrpcServer(runtime, f"{args.address}:{args.port}",
                             tls=tls, mtls_mode=args.mtls)
    port = server.start()
    print(f"mixs: istio.mixer.v1 on {args.address}:{port} "
          f"(config={'fs:' + args.config_store if args.config_store else 'memory'}"
          f"{', mtls=' + args.mtls if args.mtls != 'off' else ''})")
    intro = None
    if args.monitoring_port:
        # the reference's :9093 self-monitoring port, upgraded to the
        # full introspection surface (istio_tpu/introspect/): /metrics
        # merges BOTH registries, plus /healthz /readyz /debug/*
        from istio_tpu.introspect import IntrospectServer
        # trace ring OFF unless asked: enabling it flips the global
        # tracer to recording, and span construction (2x uuid per
        # span) is hot-path work the bench-certified p99 never pays
        intro = IntrospectServer(runtime=runtime,
                                 port=args.monitoring_port,
                                 host=args.monitoring_host,
                                 trace_capacity=args.trace_ring,
                                 tls=tls if args.introspect_tls
                                 else None)
        intro.start()
        print(f"mixs: introspection on "
              f"{args.monitoring_host}:{intro.port} "
              "(/metrics /healthz /readyz /debug/config /debug/queues"
              " /debug/cache /debug/traces /debug/resilience"
              " /debug/analysis /debug/rulestats /debug/canary"
              " /debug/slow /debug/events /debug/profile"
              " /debug/threads)")
    _serve_forever()
    server.stop()
    if intro is not None:
        intro.close()
    runtime.close()
    return 0


def cmd_rule_dump(args: argparse.Namespace) -> int:
    """Disassemble a config snapshot's compiled ruleset; optionally
    step one synthetic request through it (the il/text + Stepper
    tooling, mixer/pkg/il/text/write.go + interpreter/stepper.go)."""
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.attribute.global_dict import GLOBAL_MANIFEST
    from istio_tpu.compiler.disasm import Stepper, disassemble
    from istio_tpu.runtime import FsStore
    from istio_tpu.runtime.config import SnapshotBuilder

    store = FsStore(args.config_store)
    snapshot = SnapshotBuilder(GLOBAL_MANIFEST).build(store)
    for err in snapshot.errors:
        print(f"# config error: {err}")
    print(disassemble(snapshot.ruleset), end="")
    if args.explain:
        values = {}
        for pair in args.explain:
            name, _, value = pair.partition("=")
            values[name] = value
        print()
        print(Stepper(snapshot.ruleset, snapshot.finder).explain(
            bag_from_mapping(values)), end="")
    return 0


def cmd_analyze(args: argparse.Namespace) -> int:
    """Static snapshot verification (istio_tpu/analysis): build the
    snapshot a server would serve from this config store and run the
    full analyzer — expression checking, rule shadowing/conflicts with
    oracle-confirmed witnesses, NFA/tile budget prediction. Exits 1
    when any ERROR-severity finding is present (CI-gateable), 0 on a
    clean or warning-only config."""
    from istio_tpu.analysis import analyze_store
    from istio_tpu.runtime import FsStore

    store = FsStore(args.config_store)
    report = analyze_store(store)
    if args.json:
        print(json.dumps(report.to_dict(), indent=1, default=str))
    else:
        for f in sorted(report.findings,
                        key=lambda f: -int(f.severity)):
            rules = f" [{', '.join(f.rules)}]" if f.rules else ""
            print(f"{f.severity.name:7s} {f.code}{rules}: {f.message}")
            if f.witness:
                print(f"        witness: {f.witness}")
        print(f"analyze: {len(report.errors)} error(s), "
              f"{len(report.warnings)} warning(s), "
              f"{len(report.findings)} finding(s) over "
              f"{report.n_rules} rule(s) in {report.wall_ms:.0f}ms")
    return 1 if report.has_errors else 0


def cmd_lint(args: argparse.Namespace) -> int:
    """meshlint — the CODE-side sibling of `analyze`: run the
    concurrency & discipline passes (lock order, inferred hot-path
    reachability, metric zero-shaping, typed rejections) over the
    package's own source. Exits 1 when any ERROR-severity finding is
    present (CI-gateable) or when --selftest finds a violation class
    the analyzer no longer detects."""
    from istio_tpu.analysis.meshlint import fixtures, run_meshlint

    if args.selftest:
        problems = fixtures.selftest()
        for p in problems:
            print(f"lint selftest: {p}")
        if not problems:
            print(f"lint selftest: ok "
                  f"({len(fixtures.FIXTURES)} fixtures)")
        return 1 if problems else 0
    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    report = run_meshlint(root=root)
    if args.json:
        print(json.dumps(report.to_dict(), indent=1, default=str))
    else:
        for f in report.findings:
            print(f)
        print(f"lint: {len(report.errors)} error(s), "
              f"{len(report.warnings)} warning(s), "
              f"{len(report.findings)} finding(s) over "
              f"{report.n_functions} function(s) in "
              f"{report.n_modules} module(s) in "
              f"{report.wall_ms:.0f}ms")
    return 1 if report.has_errors else 0


def cmd_canary(args: argparse.Namespace) -> int:
    """Offline canary replay (the dynamic sibling of `analyze`): load
    a recorded live-traffic corpus (saved by a serving mixs via
    /debug/canary tooling or canary.save_corpus) and shadow-replay it
    through the candidate config store's compiled snapshot. Prints the
    divergence report; exits 1 when the non-waived divergence rate
    exceeds --max-divergence (CI-gateable: a config PR that flips
    recorded production decisions fails before rollout)."""
    from istio_tpu.canary import (diff_decisions, load_corpus,
                                  replay_entries)
    from istio_tpu.runtime import FsStore
    from istio_tpu.runtime.config import SnapshotBuilder
    from istio_tpu.runtime.fused import build_fused_plan
    from istio_tpu.attribute.global_dict import GLOBAL_MANIFEST

    entries = load_corpus(args.corpus)
    if args.limit and len(entries) > args.limit:
        entries = entries[-args.limit:]
    if not entries:
        print("canary: corpus is empty", file=sys.stderr)
        return 2
    store = FsStore(args.config_store)
    snapshot = SnapshotBuilder(GLOBAL_MANIFEST).build(store)
    for err in snapshot.errors:
        print(f"# config error: {err}", file=sys.stderr)
    plan = build_fused_plan(snapshot, rule_telemetry=False)
    if plan is None:
        print("canary: candidate snapshot has no rules to replay "
              "against", file=sys.stderr)
        return 2
    replay = replay_entries(snapshot, plan, entries,
                            identity_attr=args.identity_attr)
    report = diff_decisions(entries, replay,
                            waivers=tuple(args.waive or ()))
    report.mode = "gate"
    report.threshold = args.max_divergence
    gated = report.divergence_rate > args.max_divergence
    report.verdict = "veto" if gated else "publish"
    if args.json:
        print(json.dumps(report.to_dict(), indent=1, default=str))
    else:
        for rule in report.diverging_rules():
            c = report.per_rule[rule]
            print(f"DIVERGE {rule}: {c['total']} rows "
                  f"(status_flip={c['status_flip']} "
                  f"precondition={c['precondition']} "
                  f"quota={c['quota']})")
        print(f"canary: {report.n_divergent}/{report.n_rows} rows "
              f"diverge (rate {report.divergence_rate:.4f}, "
              f"{report.n_waived} waived) at "
              f"{report.replay_rows_per_s:.0f} rows/s — "
              f"{report.verdict.upper()}")
    return 1 if gated else 0


def cmd_mixc(args: argparse.Namespace) -> int:
    """mixer client (cmd/mixc check/report)."""
    from istio_tpu.api import MixerClient
    attrs = {}
    for kv in args.string_attributes or []:
        k, _, v = kv.partition("=")
        attrs[k] = v
    for kv in args.int64_attributes or []:
        k, _, v = kv.partition("=")
        attrs[k] = int(v)
    client = MixerClient(args.mixer)
    if args.command == "check":
        resp = client.check(attrs)
        print(json.dumps({
            "status_code": resp.precondition.status.code,
            "status_message": resp.precondition.status.message,
            "valid_use_count": resp.precondition.valid_use_count}))
        return 0 if resp.precondition.status.code == 0 else 1
    client.report([attrs])
    print("{}")
    return 0


def cmd_pilot_discovery(args: argparse.Namespace) -> int:
    """pilot-discovery (bootstrap/server.go assembly): initMesh →
    config stores → service registries → discovery."""
    from istio_tpu.pilot import MemoryConfigStore, MemoryRegistry
    from istio_tpu.pilot.discovery import DiscoveryService
    from istio_tpu.pilot.mesh import init_mesh
    from istio_tpu.pilot.registry import AggregateRegistry

    # initMesh (server.go:245): defaults ← file ← flag overrides
    mesh = init_mesh(
        config_file=args.mesh_config,
        overrides={"mixer_address": args.mixer_address},
        on_warn=lambda msg: print(f"pilot-discovery: {msg}"))
    proxy_defaults = mesh["default_config"]
    # flat view: the envoy config generators read the proxy-level
    # fields at top level (envoy_config.py)
    mesh_view = {**mesh,
                 "discovery_address": proxy_defaults["discovery_address"],
                 "admin_port": proxy_defaults["proxy_admin_port"],
                 "zipkin_address": mesh["zipkin_address"] or
                 proxy_defaults["zipkin_address"]}

    memory = MemoryRegistry()
    store = MemoryConfigStore()
    if args.registry_file:
        _load_world(memory, store, args.registry_file)
    backends = [memory]
    # platform registries (bootstrap/server.go:360 initServiceControllers)
    if args.consul_address:
        from istio_tpu.pilot.consul import ConsulRegistry
        consul = ConsulRegistry(args.consul_address)
        consul.start()
        backends.append(consul)
    if args.eureka_address:
        from istio_tpu.pilot.eureka import EurekaRegistry
        eka = EurekaRegistry(args.eureka_address)
        eka.start()
        backends.append(eka)
    registry = backends[0] if len(backends) == 1 \
        else AggregateRegistry(backends)
    ds = DiscoveryService(registry, store, mesh_view)
    reload_stop = None
    if args.registry_file:
        # live reload: istioctl register/deregister edits the file and
        # must take effect without a restart (the reference writes to
        # the live registry; here the file IS the registry backend).
        # The watcher starts AFTER the DiscoveryService so a reload's
        # per-service add/remove storm coalesces into ONE snapshot
        # publish (ds.hold_publishes) instead of a full-world rebuild
        # per service.
        reload_stop = _watch_registry_file(memory, args.registry_file,
                                           ds)
    port = ds.start(args.address, args.port)
    print(f"pilot-discovery: v1 xDS on {args.address}:{port}")
    _serve_forever()
    if reload_stop is not None:
        reload_stop.set()
    ds.stop()
    return 0


def _watch_registry_file(memory, path: str, ds=None):
    """Poll the registry YAML's content; on change, rebuild the memory
    registry's service set (service handlers fire → scoped snapshot
    publish). `ds`: the DiscoveryService whose hold_publishes()
    coalesces the rebuild's event storm into one publish."""
    import contextlib
    import hashlib
    import threading
    import yaml
    from istio_tpu.pilot import Port, Service

    stop = threading.Event()

    def digest() -> bytes:
        try:
            with open(path, "rb") as f:
                return hashlib.sha256(f.read()).digest()
        except OSError:
            return b""

    last = digest()

    def loop() -> None:
        nonlocal last
        while not stop.wait(1.0):
            now = digest()
            if now == last:
                continue
            last = now
            try:
                with open(path, encoding="utf-8") as f:
                    world = yaml.safe_load(f) or {}
            except (OSError, yaml.YAMLError) as exc:
                print(f"pilot-discovery: registry reload failed: {exc}")
                continue
            wanted = {}
            for s in world.get("services") or ():
                svc = Service(
                    hostname=s["hostname"],
                    address=s.get("address", "0.0.0.0"),
                    ports=tuple(Port(p["name"], int(p["port"]),
                                     p.get("protocol", "HTTP"))
                                for p in s.get("ports") or ()))
                wanted[svc.hostname] = (svc, [
                    (e["address"], e.get("labels", {}))
                    for e in s.get("endpoints") or ()])
            hold = ds.hold_publishes() if ds is not None \
                else contextlib.nullcontext()
            with hold:
                for host in [svc.hostname
                             for svc in memory.services()]:
                    if host not in wanted:
                        memory.remove_service(host)
                for svc, endpoints in wanted.values():
                    memory.add_service(svc, endpoints)

    t = threading.Thread(target=loop, daemon=True,
                         name="registry-reload")
    t.start()
    return stop


def _register_endpoint(args: argparse.Namespace) -> int:
    """istioctl register <svc> <ip> [name:port...] /
    deregister <svc> <ip> over the registry YAML."""
    import yaml
    path = args.registry_file
    try:
        with open(path, encoding="utf-8") as f:
            world = yaml.safe_load(f) or {}
    except FileNotFoundError:
        world = {}
    # normalize null-valued keys (a hand-written "services:" with no
    # value loads as None)
    world["services"] = services = list(world.get("services") or ())
    hostname = args.kind        # positional reuse: <svc> <ip>
    address = args.name
    if not hostname or not address:
        print("usage: istioctl register <service> <ip> [name:port ...]",
              file=sys.stderr)
        return 2
    svc = next((s for s in services if s.get("hostname") == hostname),
               None)
    if args.command == "register":
        ports = []
        specs = [p for p in (args.ports or "http:80").split(",") if p]
        for spec in specs:
            name, sep, num = spec.partition(":")
            if not sep or not num.isdigit():
                print(f"bad port spec {spec!r}: expected name:port",
                      file=sys.stderr)
                return 2
            ports.append({"name": name, "port": int(num)})
        if svc is None:
            svc = {"hostname": hostname, "ports": ports, "endpoints": []}
            services.append(svc)
        else:
            # reconcile ports on an existing service like the
            # reference RegisterEndpoint (register.go:126-136)
            existing = {p.get("name") for p in (svc.get("ports") or ())}
            svc["ports"] = list(svc.get("ports") or ()) + \
                [p for p in ports if p["name"] not in existing]
        svc["endpoints"] = eps = list(svc.get("endpoints") or ())
        if not any(e.get("address") == address for e in eps):
            eps.append({"address": address})
        print(f"registered {address} -> {hostname}")
    else:
        if svc is None:
            print(f"unknown service {hostname}", file=sys.stderr)
            return 1
        svc["endpoints"] = [e for e in (svc.get("endpoints") or ())
                            if e.get("address") != address]
        print(f"deregistered {address} from {hostname}")
    with open(path, "w", encoding="utf-8") as f:
        yaml.safe_dump(world, f, sort_keys=False)
    return 0


def cmd_generate_key_cert(args: argparse.Namespace) -> int:
    """generate_cert / generate_csr (security/cmd): standalone key +
    self-signed cert or CSR for an identity."""
    from istio_tpu.security import pki
    key = pki.generate_key()
    key_pem = pki.key_to_pem(key)
    if args.mode == "csr":
        out = pki.generate_csr(key, args.identity, org=args.org)
    else:
        from istio_tpu.security.ca import IstioCA
        ca = IstioCA.new_self_signed(org=args.org)
        out = ca.sign(pki.generate_csr(key, args.identity, org=args.org))
        with open(args.out_root, "wb") as f:
            f.write(ca.get_root_certificate())
    with open(args.out_key, "wb") as f:
        f.write(key_pem)
    with open(args.out_cert, "wb") as f:
        f.write(out)
    print(f"wrote {args.out_key} + {args.out_cert}")
    return 0


def _load_world(registry, store, path: str) -> None:
    """Topology + config from one YAML file: {services: [...],
    configs: [...]} — the file-based registry mode."""
    import yaml
    from istio_tpu.pilot import Config, ConfigMeta, Port, Service
    with open(path, encoding="utf-8") as f:
        world = yaml.safe_load(f) or {}
    for s in world.get("services", ()):
        svc = Service(hostname=s["hostname"],
                      address=s.get("address", "0.0.0.0"),
                      ports=tuple(Port(p["name"], int(p["port"]),
                                       p.get("protocol", "HTTP"))
                                  for p in s.get("ports", ())))
        registry.add_service(svc, [(e["address"], e.get("labels", {}))
                                   for e in s.get("endpoints", ())])
    for c in world.get("configs", ()):
        meta = c.get("metadata", {})
        store.create(Config(ConfigMeta(type=c["kind"],
                                       name=meta.get("name", ""),
                                       namespace=meta.get("namespace",
                                                          "default")),
                            c.get("spec", {})))


def cmd_pilot_agent(args: argparse.Namespace) -> int:
    """pilot-agent proxy (cmd/pilot-agent/main.go:71)."""
    import subprocess
    from istio_tpu.pilot.agent import Agent, CertWatcher, Proxy

    class EnvoyProxy(Proxy):
        def run(self, config, epoch, abort):
            # config is (path, cert_hash): the hash participates in the
            # agent's config comparison so cert rotation forces an epoch
            path, _cert_hash = config
            cmd = [args.binary_path, "--restart-epoch", str(epoch),
                   "--drain-time-s", str(args.drain_duration),
                   "-c", path]
            proc = subprocess.Popen(cmd)
            while proc.poll() is None:
                if abort.wait(0.2):
                    proc.terminate()
                    proc.wait(timeout=10)
                    return
            if proc.returncode != 0:
                raise RuntimeError(f"envoy exited {proc.returncode}")

    agent = Agent(EnvoyProxy())
    agent.schedule_config_update((args.config_path, ""))
    watcher = CertWatcher([args.cert_dir],
                          lambda h: agent.schedule_config_update(
                              (args.config_path, h))) \
        if args.cert_dir else None
    if watcher:
        watcher.start()
    print(f"pilot-agent: managing {args.binary_path} epochs")
    _serve_forever()
    if watcher:
        watcher.stop()
    agent.close()
    return 0


def cmd_istioctl(args: argparse.Namespace) -> int:
    """istioctl create/get/delete/kube-inject/register/deregister over
    an FsStore-style config dir (the reference talks to k8s CRDs; the
    file store is this build's durable backend)."""
    import os
    import yaml
    from istio_tpu.pilot.model import IstioConfigTypes, ValidationError
    if args.command == "kube-inject":
        from istio_tpu.pilot.inject import InjectParams, into_resource_file
        with open(args.filename, encoding="utf-8") as f:
            print(into_resource_file(InjectParams(), f.read()))
        return 0
    if args.command in ("register", "deregister"):
        # VM endpoint (de)registration (serviceregistry/kube/
        # register.go:120: create the Service if absent, then add or
        # remove the endpoint address) — against the registry file
        # pilot-discovery serves from
        return _register_endpoint(args)
    cfg_dir = args.config_dir
    if args.command in ("create", "replace"):
        with open(args.filename, encoding="utf-8") as f:
            docs = list(yaml.safe_load_all(f))
        for doc in docs:
            if not doc:
                continue
            kind = doc.get("kind", doc.get("type", ""))
            schema = IstioConfigTypes.get(kind)
            if schema is None:
                print(f"unknown config kind {kind}", file=sys.stderr)
                return 1
            try:
                schema.validate(doc.get("spec", {}))
            except ValidationError as exc:
                print(f"invalid {kind}: {exc}", file=sys.stderr)
                return 1
            meta = doc.get("metadata", {})
            name = meta.get("name", "unnamed")
            ns = meta.get("namespace", "default")
            path = os.path.join(cfg_dir, f"{kind}-{ns}-{name}.yaml")
            if args.command == "create" and os.path.exists(path):
                print(f"{kind} {name} already exists", file=sys.stderr)
                return 1
            with open(path, "w", encoding="utf-8") as f:
                yaml.safe_dump(doc, f, sort_keys=False)
            print(f"{args.command}d {kind} {name}.{ns}")
        return 0
    if args.command == "get":
        import glob
        for path in sorted(glob.glob(os.path.join(cfg_dir, "*.yaml"))):
            with open(path, encoding="utf-8") as f:
                for doc in yaml.safe_load_all(f):
                    if doc and (args.kind in ("all", doc.get("kind"))):
                        meta = doc.get("metadata", {})
                        print(f"{doc.get('kind')}\t{meta.get('name')}"
                              f"\t{meta.get('namespace', 'default')}")
        return 0
    if args.command == "delete":
        import glob
        pattern = f"{args.kind}-{args.namespace}-{args.name}.yaml"
        hits = glob.glob(os.path.join(cfg_dir, pattern))
        for path in hits:
            os.unlink(path)
            print(f"deleted {args.kind} {args.name}.{args.namespace}")
        return 0 if hits else 1
    return 2


def cmd_istio_ca(args: argparse.Namespace) -> int:
    """istio_ca (security/cmd/istio_ca/main.go:146)."""
    import pickle
    from istio_tpu.security import IstioCA
    from istio_tpu.security.ca_service import CAGrpcServer
    secrets: dict = {}
    if args.secret_file:
        try:
            with open(args.secret_file, "rb") as f:
                secrets.update(pickle.load(f))
        except FileNotFoundError:
            pass
    ca = IstioCA.new_self_signed(secrets)
    if args.secret_file:
        with open(args.secret_file, "wb") as f:
            pickle.dump(secrets, f)
    if args.insecure_allow_all:
        from istio_tpu.security.ca_service import (
            allow_any_identity_authorizer,
            insecure_allow_all_authenticator)
        print("WARNING: --insecure-allow-all signs ANY identity for ANY "
              "caller over plaintext; never use outside tests")
        server = CAGrpcServer(
            ca, authenticator=insecure_allow_all_authenticator,
            authorizer=allow_any_identity_authorizer,
            address=f"{args.address}:{args.port}", insecure_port=True)
    else:
        # onprem flow: callers present an existing cert signed by this
        # root; they may renew only their own SPIFFE identity. With
        # --trusted-tokens-file, gcp/aws bearer credentials map to
        # identities from the operator-provisioned token table.
        import json as _json
        from istio_tpu.security.ca_service import (
            cert_authenticator, composite_authenticator,
            token_authenticator)
        authenticator = cert_authenticator(ca.get_root_certificate())
        if args.trusted_tokens_file:
            with open(args.trusted_tokens_file) as f:
                authenticator = composite_authenticator(
                    authenticator, token_authenticator(_json.load(f)))
        server = CAGrpcServer(
            ca, authenticator=authenticator,
            address=f"{args.address}:{args.port}")
    port = server.start()
    print(f"istio_ca: CSR service on {args.address}:{port}")
    _serve_forever()
    server.stop()
    return 0


def cmd_node_agent(args: argparse.Namespace) -> int:
    """node_agent (security/cmd/node_agent): the bootstrap credential
    comes from a platform fetcher (security/pkg/platform client.go)."""
    import os
    from istio_tpu.security.ca_service import CAClient, NodeAgent
    from istio_tpu.security.workload import (SecretConfig,
                                             SecretFileServer)
    os.makedirs(args.cert_dir, exist_ok=True)
    sink = SecretFileServer(SecretConfig(
        service_identity_cert_file=os.path.join(args.cert_dir,
                                                "cert-chain.pem"),
        service_identity_private_key_file=os.path.join(args.cert_dir,
                                                       "key.pem")))

    def write_certs(key_pem: bytes, cert_pem: bytes, root_pem: bytes):
        sink.set_service_identity_private_key(key_pem)
        sink.set_service_identity_cert(cert_pem)
        with open(os.path.join(args.cert_dir, "root-cert.pem"),
                  "wb") as f:
            f.write(root_pem)

    root_pem = None
    credential = b""
    cred_type = args.platform
    if args.platform != "onprem":
        # gcp/aws need a metadata endpoint; hermetic runs inject one
        # from a JSON path→value file
        import json as _json
        from istio_tpu.security.platform import (PlatformError,
                                                 new_platform_client)

        class _FileMetadata:
            def __init__(self, path: str):
                with open(path) as f:
                    self._data = _json.load(f)

            def available(self) -> bool:
                return True

            def fetch(self, path: str, audience: str = "") -> str:
                value = self._data.get(path, "")
                if isinstance(value, str):
                    return value
                return _json.dumps(value)   # nested docs stay valid JSON

        if not args.platform_metadata_file:
            print("node_agent: --platform-metadata-file is required for "
                  f"platform {args.platform} (no metadata service here)")
            return 2
        if not args.root_cert and not args.insecure_ca:
            print("node_agent: --root-cert is required (the bearer "
                  "credential must not travel in cleartext); pass "
                  "--insecure-ca only against a test CA")
            return 2
        try:
            cfg = {
                "ca_addr": args.ca_address,
                "metadata": _FileMetadata(args.platform_metadata_file),
                "root_ca_cert_file": args.root_cert}
            if args.platform == "aws":
                # AwsClient fails closed without a PKCS7 verifier and
                # none ships in this build — the operator must opt out
                # explicitly (mirrors --insecure-ca's posture)
                if not args.skip_identity_verify:
                    print("node_agent: --platform aws requires "
                          "--skip-identity-verify (no PKCS7 verifier "
                          "in this build; identity signature would "
                          "fail closed)")
                    return 2
                cfg["verify"] = False
            pc = new_platform_client(args.platform, cfg)
            credential = pc.get_agent_credential()
            cred_type = pc.get_credential_type()
        except (OSError, ValueError, PlatformError) as exc:
            print(f"node_agent: platform credential fetch failed: {exc}")
            return 2
    elif not args.insecure_ca and not (args.root_cert and
                                       args.bootstrap_cert):
        print("node_agent: --root-cert and --bootstrap-cert are required"
              " (the CA serves TLS and authenticates onprem credentials);"
              " pass --insecure-ca only against a test CA running with"
              " --insecure-allow-all")
        return 2
    if args.root_cert:
        with open(args.root_cert, "rb") as f:
            root_pem = f.read()
    if args.bootstrap_cert and not credential:
        with open(args.bootstrap_cert, "rb") as f:
            credential = f.read()
    client = CAClient(args.ca_address, root_cert_pem=root_pem)
    agent = NodeAgent(client, args.identity, write_certs,
                      ttl_minutes=args.ttl_minutes,
                      credential=credential, credential_type=cred_type)
    agent.start()
    print(f"node_agent: rotating {args.identity} certs in {args.cert_dir}")
    _serve_forever()
    agent.stop()
    client.close()
    return 0


def cmd_brkcol(args: argparse.Namespace) -> int:
    """brkcol (broker/cmd/brkcol): broker-config collector — read the
    service-class / service-plan kinds out of a config store, assemble
    the OSB catalog exactly as a serving brks would (controller.go:48
    via BrokerConfigStore.catalog), and print it. The offline
    collection/inspection half of the broker pair: run it against the
    store a broker will mount to see the catalog it would serve."""
    from istio_tpu.broker.model import BrokerConfigStore
    from istio_tpu.runtime import FsStore

    store = FsStore(args.config_store)
    bcs = BrokerConfigStore(store)
    classes = bcs.service_classes()
    plans = bcs.service_plans()
    catalog = bcs.catalog().to_wire()
    if args.json:
        print(json.dumps({"service_classes": sorted(classes),
                          "service_plans": sorted(plans),
                          "catalog": catalog}, indent=1))
    else:
        print(f"brkcol: {len(classes)} service-class(es), "
              f"{len(plans)} service-plan(s), "
              f"{len(catalog['services'])} catalog service(s)")
        for key in sorted(classes):
            print(f"  class {key}")
        for key, plan in sorted(plans.items()):
            svcs = ",".join(plan.get("services") or ())
            print(f"  plan  {key} -> [{svcs}]")
    return 0


def cmd_brks(args: argparse.Namespace) -> int:
    """brks (broker/cmd/brks)."""
    import yaml
    from istio_tpu.broker import BrokerServer
    services = []
    if args.catalog:
        with open(args.catalog, encoding="utf-8") as f:
            services = (yaml.safe_load(f) or {}).get("services", [])
    broker = BrokerServer(services)
    port = broker.start(args.address, args.port)
    print(f"brks: OSB v2 on {args.address}:{port}")
    _serve_forever()
    broker.stop()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="istio-tpu",
                                description=__doc__)
    sub = p.add_subparsers(dest="tool", required=True)

    s = sub.add_parser("mixs", help="mixer (policy) server")
    s.add_argument("--address", default="127.0.0.1")
    s.add_argument("--port", type=int, default=9091)
    s.add_argument("--monitoring-port", type=int, default=9093)
    s.add_argument("--monitoring-host", default="127.0.0.1",
                   help="introspection bind address (loopback by "
                        "default; 0.0.0.0 restores the reference's "
                        "network-scrapable :9093)")
    s.add_argument("--trace-ring", type=int, default=0,
                   help="/debug/traces ring capacity; 0 (default) "
                        "keeps span recording OFF the serving hot "
                        "path")
    s.add_argument("--config-store", default="",
                   help="YAML config dir (FsStore); empty = memory")
    s.add_argument("--batch-window-us", type=int, default=300)
    s.add_argument("--max-batch", type=int, default=1024)
    s.add_argument("--default-check-deadline-ms", type=float,
                   default=0.0,
                   help="server-side Check deadline for fronts whose "
                        "wire carries none (the native front); "
                        "expired requests answer DEADLINE_EXCEEDED "
                        "before tensorize. 0 = off")
    s.add_argument("--check-queue-cap", type=int, default=None,
                   help="check batcher queue cap: submits past it "
                        "shed RESOURCE_EXHAUSTED (default "
                        "8*max-batch; 0 = unbounded)")
    s.add_argument("--report-queue-cap", type=int, default=None,
                   help="report record coalescer admission cap: the "
                        "ack-after-enqueue contract's bound — records "
                        "past it shed typed RESOURCE_EXHAUSTED "
                        "(default 16*max-batch; 0 = unbounded)")
    s.add_argument("--brownout", action="store_true",
                   help="shed the newest check requests while the "
                        "live p99 gauge is over the SLO target and "
                        "the queue is half full")
    s.add_argument("--check-fail-policy", default="closed",
                   choices=("open", "closed"),
                   help="answer when device AND oracle check paths "
                        "are down: open = OK (Mixer-client fail-"
                        "open), closed = UNAVAILABLE")
    s.add_argument("--breaker-failures", type=int, default=3,
                   help="consecutive failed device batches that trip "
                        "the circuit breaker onto the CPU oracle path")
    s.add_argument("--breaker-reset-ms", type=float, default=5000.0,
                   help="how long the breaker stays open before a "
                        "half-open device probe")
    s.add_argument("--host-fail-policy", default="closed",
                   choices=("open", "closed"),
                   help="verdict an unresolvable host adapter action "
                        "(deadline overrun, bulkhead shed, open "
                        "lane breaker) contributes: open = OK with a "
                        "1s/1-use TTL, closed = UNAVAILABLE")
    s.add_argument("--executor-workers", type=int, default=2,
                   help="worker threads per handler lane in the "
                        "adapter executor (the bulkhead's "
                        "concurrency share)")
    s.add_argument("--executor-queue-cap", type=int, default=256,
                   help="pending host actions per handler lane; "
                        "overflow sheds typed RESOURCE_EXHAUSTED "
                        "semantics onto the fail policy")
    s.add_argument("--host-action-timeout-ms", type=float,
                   default=0.0,
                   help="extra per-host-action wall bound even when "
                        "the request carries no deadline (0 = bound "
                        "by the request deadline only)")
    s.add_argument("--no-host-executor", action="store_true",
                   help="run host adapter work inline on the batch "
                        "worker (the pre-executor loop) instead of "
                        "the bulkheaded executor plane")
    s.add_argument("--host-breaker-failures", type=int, default=3,
                   help="consecutive failed/overrun actions that trip "
                        "a handler lane's circuit breaker")
    s.add_argument("--host-breaker-reset-ms", type=float,
                   default=5000.0,
                   help="how long an open handler-lane breaker waits "
                        "before a half-open probe")
    s.add_argument("--canary", default="off",
                   choices=("off", "warn", "gate"),
                   help="config canary: shadow-replay recorded live "
                        "traffic through every rebuilt snapshot "
                        "before the atomic publish; gate vetoes "
                        "divergent swaps (the old config keeps "
                        "serving), warn publishes but records the "
                        "report on /debug/canary")
    s.add_argument("--canary-max-divergence", type=float, default=0.0,
                   help="divergence rate (non-waived divergent rows /"
                        " replayed rows) beyond which gate mode "
                        "vetoes; 0 = any divergence vetoes")
    s.add_argument("--canary-capacity", type=int, default=2048,
                   help="recorder sampling-ring capacity")
    s.add_argument("--canary-sample-every", type=int, default=1,
                   help="record every k-th check request")
    s.add_argument("--canary-replay-limit", type=int, default=1024,
                   help="newest recorded rows replayed per candidate "
                        "evaluation")
    s.add_argument("--canary-waive", action="append", metavar="RULE",
                   help="qualified rule name (ns/name) whose "
                        "divergences never gate (repeatable)")
    s.add_argument("--shards", type=int, default=0,
                   help="partition the snapshot by namespace into "
                        "this many compiled banks (the sharded "
                        "serving plane, istio_tpu/sharding); 0 = "
                        "monolithic")
    s.add_argument("--replicas", type=int, default=1,
                   help="replica-parallel serving lanes behind the "
                        "one front (sticky-by-namespace)")
    s.add_argument("--jax-compile-cache-dir", default=None,
                   metavar="DIR",
                   help="JAX persistent compilation cache directory: "
                        "restarts and rolling deploys skip warm XLA "
                        "compiles for unchanged banks "
                        "(compiler/cache.py). Falls back to the "
                        "MIXS_JAX_COMPILE_CACHE_DIR env var; unset = "
                        "jax's own defaulting")
    s.add_argument("--no-delta-compile", action="store_true",
                   help="kill switch for delta compilation: every "
                        "config publish rebuilds every shard bank "
                        "instead of diffing by content hash")
    s.add_argument("--shard-rebalance-budget", type=int, default=0,
                   help="namespaces the delta planner may relocate "
                        "per republish to chase LPT balance (each "
                        "move recompiles two banks; 0 = perfect plan "
                        "stability)")
    s.add_argument("--continuous-batching", action="store_true",
                   help="latency lane: the check batcher dispatches "
                        "a batch the moment an in-flight slot under "
                        "--continuous-depth frees instead of holding "
                        "for window/occupancy fill "
                        "(runtime/batcher.py)")
    s.add_argument("--continuous-depth", type=int, default=2,
                   help="in-flight step bound for continuous "
                        "batching (default 2: one step executing, "
                        "one dispatching)")
    s.add_argument("--no-flight-recorder", action="store_true",
                   help="disable the per-request flight recorder "
                        "(/debug/slow stays empty; the event "
                        "timeline keeps recording)")
    s.add_argument("--slow-threshold-ms", type=float, default=0.0,
                   help="flight-recorder capture threshold in ms "
                        "(0 = the live SLO target)")
    s.add_argument("--slow-adaptive", action="store_true",
                   help="adaptive threshold: track the live window "
                        "p99 (never below the configured base)")
    s.add_argument("--profile-dir", default=None,
                   help="directory for /debug/profile jax.profiler "
                        "captures (default: MIXS_PROFILE_DIR env or "
                        "a per-capture tempdir)")
    s.add_argument("--no-audit", action="store_true",
                   help="disable the background mesh audit plane "
                        "(invariant auditor + fault-explainability "
                        "scorer; /debug/audit reports enabled=false)")
    s.add_argument("--audit-interval-ms", type=float, default=500.0,
                   help="audit evaluation cadence in ms (the quota "
                        "recount samples every 8th evaluation)")
    s.add_argument("--check-grants", action="store_true",
                   help="server-issued check-cache grants: "
                        "valid_duration/valid_use_count derived from "
                        "config-generation age (runtime/grants.py) — "
                        "repeat traffic serves from the client cache "
                        "and a config delta revokes within "
                        "--grant-ttl-floor-s")
    s.add_argument("--grant-ttl-floor-s", type=float, default=1.0,
                   help="grant TTL right after a config change (the "
                        "revocation window)")
    s.add_argument("--grant-ttl-cap-s", type=float, default=5.0,
                   help="grant TTL ceiling for a long-stable config")
    s.add_argument("--trace-zipkin-url", default="",
                   help="zipkin v2 collector (POST /api/v2/spans)")
    s.add_argument("--trace-log-spans", action="store_true",
                   help="log every span (pkg/tracing LogTraceSpans)")
    s.add_argument("--mtls", default="off",
                   choices=("off", "permissive", "strict"),
                   help="secure serving plane (istio_tpu/secure): "
                        "strict = TLS fronts REQUIRE a CA-signed "
                        "client cert at handshake and its SPIFFE "
                        "identity feeds source.user/connection.mtls "
                        "into the compiled RBAC plane (a verified "
                        "cert with no SPIFFE SAN answers typed "
                        "UNAUTHENTICATED); permissive = TLS "
                        "encryption only, client certs optional and "
                        "no identity flows; off = plaintext")
    s.add_argument("--mtls-identity",
                   default="spiffe://cluster.local/ns/istio-system"
                           "/sa/istio-mixer",
                   help="SPIFFE identity on the serving certificate")
    s.add_argument("--tls-dns", default="mixer.local",
                   help="DNS SAN on the serving certificate (clients "
                        "match their target-name override against "
                        "this)")
    s.add_argument("--tls-key", default="",
                   help="static serving key PEM (with --tls-cert/"
                        "--tls-root; no rotation)")
    s.add_argument("--tls-cert", default="",
                   help="static serving cert chain PEM")
    s.add_argument("--tls-root", default="",
                   help="static client-verification root PEM")
    s.add_argument("--ca-address", default="",
                   help="CSR service (istio-ca) to obtain + rotate "
                        "the serving bundle from; rotation runs on "
                        "the adapter-executor maintenance lane and "
                        "hot-swaps live fronts with zero dropped "
                        "requests")
    s.add_argument("--ca-root-cert", default="",
                   help="root PEM for TLS to the CA service")
    s.add_argument("--bootstrap-cert", default="",
                   help="existing cert presented as the onprem CSR "
                        "credential")
    s.add_argument("--mtls-cert-ttl-minutes", type=int, default=60,
                   help="requested serving-cert TTL")
    s.add_argument("--mtls-rotation-fraction", type=float,
                   default=0.5,
                   help="rotate when less than this fraction of the "
                        "TTL remains")
    s.add_argument("--introspect-tls", action="store_true",
                   help="wrap the introspection HTTP port in TLS "
                        "from the same serving bundle (client certs "
                        "optional — scrapers rarely hold workload "
                        "identities)")
    s.set_defaults(fn=cmd_mixs)

    s = sub.add_parser("rule-dump",
                       help="disassemble a compiled config snapshot")
    s.add_argument("--config-store", required=True,
                   help="config directory (k8s-style YAML docs)")
    s.add_argument("--explain", nargs="*", metavar="attr=value",
                   help="step one request (string attrs) through the "
                        "ruleset and show per-atom/per-rule verdicts")
    s.set_defaults(fn=cmd_rule_dump)

    s = sub.add_parser("analyze",
                       help="static snapshot verification (exit 1 on "
                            "ERROR findings)")
    s.add_argument("--config-store", required=True,
                   help="config directory (k8s-style YAML docs)")
    s.add_argument("--json", action="store_true",
                   help="machine-readable report")
    s.set_defaults(fn=cmd_analyze)

    s = sub.add_parser("lint",
                       help="meshlint: concurrency & discipline "
                            "static analysis over the package source "
                            "(exit 1 on ERROR findings)")
    s.add_argument("--root", default=None,
                   help="repo root holding the istio_tpu package "
                        "(default: the installed package's parent)")
    s.add_argument("--selftest", action="store_true",
                   help="run the seeded violation corpus instead of "
                        "the tree (proves every violation class is "
                        "still detected)")
    s.add_argument("--json", action="store_true",
                   help="machine-readable report")
    s.set_defaults(fn=cmd_lint)

    s = sub.add_parser("canary",
                       help="offline shadow replay: recorded corpus "
                            "vs candidate config (exit 1 on "
                            "divergence past the threshold)")
    s.add_argument("--config-store", required=True,
                   help="candidate config directory (k8s-style YAML)")
    s.add_argument("--corpus", required=True,
                   help="recorded corpus file (canary.save_corpus)")
    s.add_argument("--max-divergence", type=float, default=0.0,
                   help="gate threshold (0 = any divergence fails)")
    s.add_argument("--limit", type=int, default=0,
                   help="replay only the newest N corpus rows")
    s.add_argument("--waive", action="append", metavar="RULE",
                   help="qualified rule name excluded from gating "
                        "(repeatable)")
    s.add_argument("--identity-attr", default="destination.service",
                   help="namespace-targeting identity attribute — "
                        "must match the serving server's "
                        "ServerArgs.identity_attr the corpus was "
                        "recorded under")
    s.add_argument("--json", action="store_true",
                   help="machine-readable report")
    s.set_defaults(fn=cmd_canary)

    s = sub.add_parser("mixc", help="mixer client")
    s.add_argument("command", choices=["check", "report"])
    s.add_argument("--mixer", default="127.0.0.1:9091")
    s.add_argument("-s", "--string-attributes", action="append")
    s.add_argument("-i", "--int64-attributes", action="append")
    s.set_defaults(fn=cmd_mixc)

    s = sub.add_parser("pilot-discovery", help="discovery server")
    s.add_argument("--address", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8080)
    s.add_argument("--registry-file", default="",
                   help="YAML world file: {services: [], configs: []}")
    s.add_argument("--mixer-address", default="")
    s.add_argument("--mesh-config", default="",
                   help="mesh config YAML (defaults applied; bad file "
                        "falls back to defaults with a warning)")
    s.add_argument("--consul-address", default="",
                   help="consul agent addr (host:port) to federate")
    s.add_argument("--eureka-address", default="",
                   help="eureka server URL to federate")
    s.set_defaults(fn=cmd_pilot_discovery)

    s = sub.add_parser("pilot-agent", help="sidecar agent")
    s.add_argument("--binary-path", default="/usr/local/bin/envoy")
    s.add_argument("--config-path", default="/etc/istio/proxy/envoy.json")
    s.add_argument("--cert-dir", default="")
    s.add_argument("--drain-duration", type=int, default=45)
    s.set_defaults(fn=cmd_pilot_agent)

    s = sub.add_parser("istioctl", help="config CRUD + kube-inject + "
                                        "VM registration")
    s.add_argument("command",
                   choices=["create", "replace", "get", "delete",
                            "kube-inject", "register", "deregister"])
    s.add_argument("-f", "--filename", default="")
    s.add_argument("--config-dir", default=".")
    s.add_argument("--registry-file", default="registry.yaml",
                   help="registry YAML for register/deregister")
    s.add_argument("--ports", default="",
                   help="comma-separated name:port pairs for register")
    s.add_argument("kind", nargs="?", default="all",
                   help="config kind, or <service> for register")
    s.add_argument("name", nargs="?", default="",
                   help="config name, or <ip> for register")
    s.add_argument("-n", "--namespace", default="default")
    s.set_defaults(fn=cmd_istioctl)

    s = sub.add_parser("generate-cert",
                       help="standalone key + CA-signed cert")
    s.add_argument("--identity", required=True)
    s.add_argument("--org", default="istio_tpu")
    s.add_argument("--out-key", default="key.pem")
    s.add_argument("--out-cert", default="cert.pem")
    s.add_argument("--out-root", default="root-cert.pem")
    s.set_defaults(fn=cmd_generate_key_cert, mode="cert")

    s = sub.add_parser("generate-csr", help="standalone key + CSR")
    s.add_argument("--identity", required=True)
    s.add_argument("--org", default="istio_tpu")
    s.add_argument("--out-key", default="key.pem")
    s.add_argument("--out-cert", default="csr.pem")
    s.set_defaults(fn=cmd_generate_key_cert, mode="csr")

    s = sub.add_parser("istio-ca", help="certificate authority")
    s.add_argument("--address", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8060)
    s.add_argument("--secret-file", default="",
                   help="persist the self-signed root here")
    s.add_argument("--insecure-allow-all", action="store_true",
                   help="TEST ONLY: plaintext port, no authn/authz")
    s.add_argument("--trusted-tokens-file", default="",
                   help="JSON token→identity map for gcp/aws bearer "
                        "credentials")
    s.set_defaults(fn=cmd_istio_ca)

    s = sub.add_parser("node-agent", help="workload cert rotation")
    s.add_argument("--ca-address", default="127.0.0.1:8060")
    s.add_argument("--identity", required=True)
    s.add_argument("--cert-dir", default="/etc/certs")
    s.add_argument("--ttl-minutes", type=int, default=60)
    s.add_argument("--root-cert", default="",
                   help="CA root for TLS to the CA service")
    s.add_argument("--bootstrap-cert", default="",
                   help="existing cert presented as the onprem credential")
    s.add_argument("--insecure-ca", action="store_true",
                   help="TEST ONLY: plaintext CA without credentials")
    s.add_argument("--platform", default="onprem",
                   choices=("onprem", "gcp", "aws"),
                   help="bootstrap credential fetcher")
    s.add_argument("--platform-metadata-file", default="",
                   help="JSON path→value metadata fixture for gcp/aws")
    s.add_argument("--skip-identity-verify", action="store_true",
                   help="INSECURE: accept the aws instance-identity "
                        "document without PKCS7 signature verification "
                        "(no verifier is available in this build; "
                        "required for --platform aws)")
    s.set_defaults(fn=cmd_node_agent)

    s = sub.add_parser("brks", help="OSB broker")
    s.add_argument("--address", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8090)
    s.add_argument("--catalog", default="")
    s.set_defaults(fn=cmd_brks)

    s = sub.add_parser("brkcol",
                       help="broker-config collector: assemble + "
                            "print the OSB catalog a broker would "
                            "serve from this config store")
    s.add_argument("--config-store", required=True,
                   help="directory of YAML config documents "
                        "(service-class / service-plan kinds)")
    s.add_argument("--json", action="store_true",
                   help="machine-readable output")
    s.set_defaults(fn=cmd_brkcol)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
