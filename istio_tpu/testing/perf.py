"""Perf rig — load generation decoupled from the server.

Reference: mixer/pkg/perf (controller.go:27 + clientserver.go): a
controller drives external client processes that fire attribute load at
the server, and throughput/latency are measured AT THE CLIENT, through
the full stack (gRPC decode → tensorize → device step → response).
Benchmarks: mixer/test/perf/singlecheck_test.go:53.

Clients are separate OS processes (the GIL must not couple load
generation to the server under test); each worker keeps `concurrency`
requests in flight from one issuing thread, cycling through
pre-serialized payloads, and reports latency samples back over a queue.

Measurement is COMPLETION-COUNTED, not wall-clock (VERDICT r3 item 1):
after attach + steady-state detection the worker records the next
`n_record` RPC *completions* and reports the span from first to last.
A window defined by completions cannot close empty while the server is
answering at all — a stalled issue thread (mid-stream compile, 1-core
contention) merely stretches the window instead of voiding it, which is
exactly the failure mode that produced three rounds of wall-clock
windows with zero recorded requests.
"""
from __future__ import annotations

import dataclasses
import multiprocessing as mp
import os
import time
from typing import Any, Mapping, Sequence

import numpy as np


def make_check_payloads(dicts: Sequence[Mapping[str, Any]],
                        quota_every: int = 0,
                        quota_name: str = "rq") -> list[bytes]:
    """Pre-serialized CheckRequest bytes for the worker processes.
    `quota_every` > 0 attaches a quota request (amount 1, no dedup) to
    every Nth payload — served quota traffic rides the e2e number."""
    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.api.wire import bag_to_compressed
    from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST

    out = []
    for i, values in enumerate(dicts):
        req = pb.CheckRequest(global_word_count=len(GLOBAL_WORD_LIST))
        bag_to_compressed(values, msg=req.attributes)
        if quota_every and i % quota_every == 0:
            req.quotas[quota_name].amount = 1
            req.quotas[quota_name].best_effort = True
        out.append(req.SerializeToString())
    return out


def make_batch_check_payloads(dicts: Sequence[Mapping[str, Any]],
                              batch_size: int,
                              n_payloads: int = 8) -> list[bytes]:
    """Pre-serialized BatchCheckRequest bytes (the shim protocol):
    each payload carries `batch_size` independent bags."""
    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.api.wire import bag_to_compressed, \
        encode_batch_check_request
    from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST

    blobs = []
    for values in dicts:
        msg = pb.CompressedAttributes()
        bag_to_compressed(values, msg=msg)
        blobs.append(msg.SerializeToString())
    out = []
    for k in range(n_payloads):
        batch = [blobs[(k * batch_size + i) % len(blobs)]
                 for i in range(batch_size)]
        out.append(encode_batch_check_request(
            batch, len(GLOBAL_WORD_LIST)))
    return out


def make_report_payloads(dicts: Sequence[Mapping[str, Any]],
                         records_per_request: int = 64,
                         n_payloads: int = 8) -> list[bytes]:
    """Pre-serialized ReportRequest bytes: `records_per_request`
    attribute records per RPC (the report_batch shape). Records are
    encoded whole (not deltas) — with a consistent key set across
    `dicts` each record fully overwrites the accumulator, which is
    delta-decoding-correct server-side."""
    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.api.wire import bag_to_compressed
    from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST

    out = []
    for k in range(n_payloads):
        req = pb.ReportRequest(
            global_word_count=len(GLOBAL_WORD_LIST))
        for i in range(records_per_request):
            values = dicts[(k * records_per_request + i) % len(dicts)]
            bag_to_compressed(values, msg=req.attributes.add())
        out.append(req.SerializeToString())
    return out


def run_h2load(port: int, payloads: Sequence[bytes], n_record: int,
               depth: int, warmup_s: float,
               timeout_s: float = 300.0,
               method: str = "/istio.mixer.v1.Mixer/Check") -> dict:
    """Drive the native front-end (native/httpd.cpp) with the C++
    closed-loop client (native/h2load.cpp) — the wire-speed
    counterpart of run_load for servers whose transport is not bounded
    by the python grpc stack. Payloads are serialized CheckRequests
    (make_check_payloads) or, with method=.../Report, ReportRequests
    (make_report_payloads); returns h2load's JSON report dict."""
    import json
    import struct
    import subprocess
    import tempfile

    from istio_tpu.native.build import ensure_h2load_built

    with tempfile.NamedTemporaryFile(suffix=".bin", delete=False) as f:
        for raw in payloads:
            f.write(struct.pack("<I", len(raw)) + raw)
        path = f.name
    try:
        out = subprocess.run(
            [ensure_h2load_built(), str(port), path, str(n_record),
             str(depth), str(warmup_s), method],
            capture_output=True, text=True, timeout=timeout_s)
        if out.returncode != 0:
            raise PerfError(f"h2load rc={out.returncode}: "
                            f"{out.stderr.strip()[-300:]}")
        return json.loads(out.stdout.strip().splitlines()[-1])
    finally:
        os.unlink(path)


@dataclasses.dataclass
class PerfReport:
    checks_per_sec: float
    p50_ms: float
    p99_ms: float
    mean_ms: float
    n_requests: int          # recorded successful completions
    n_errors: int            # recorded errored completions
    duration_s: float        # longest per-worker recording span
    n_procs: int
    concurrency: int
    first_error: str = ""
    warmup_completions: int = 0   # completions before the window opened
    steady_rate_per_sec: float = 0.0  # rate observed at window open
    truncated: bool = False  # hard deadline hit before n_record


class PerfError(RuntimeError):
    """The rig failed to measure — NEVER reported as a zero result."""


# worker-side budgets (seconds)
_ATTACH_TIMEOUT = 30.0       # channel ready + first RPC
_PRE_GO_HARD_STOP = 600.0    # parent died without a go signal
_STEADY_CAP_S = 12.0         # max extra wait for a stable rate
_RECORD_HARD_S = 240.0       # recording must finish within this
_CALL_TIMEOUT_S = 60.0


def _worker(target: str, payloads: list[bytes], n_record: int,
            concurrency: int, start_val, ready_q: "mp.Queue",
            q: "mp.Queue",
            method: str = "/istio.mixer.v1.Mixer/Check") -> None:
    """`concurrency` requests in flight via one issuing thread +
    completion callbacks on grpc's IO threads — a blocked thread per
    RPC melts the GIL at the depths a ~100ms-RTT device transport
    needs to stay busy (this rig has ONE core for server AND client).

    Readiness handshake (the mixer/pkg/perf/clientserver.go:30-90
    attach pattern): the worker connects AND completes one full RPC
    before reporting ready; the parent gives the go signal — by
    writing the shared `start_val` — only once every worker has
    attached, so a slow spawn/import can never eat the measurement.

    Phases after the go signal: (1) steady-state — watch 1s completion
    windows until two consecutive windows agree within 30% (cap
    _STEADY_CAP_S); (2) record — the next `n_record` completions
    (successes AND errors; both advance the window) with per-RPC
    latency; (3) drain + report."""
    import threading

    import grpc

    try:
        channel = grpc.insecure_channel(target)
        call = channel.unary_unary(
            method,
            request_serializer=lambda b: b,    # already serialized
            response_deserializer=lambda b: b)  # latency only; no parse
        grpc.channel_ready_future(channel).result(timeout=_ATTACH_TIMEOUT)
        call(payloads[0], timeout=_CALL_TIMEOUT_S)  # one RPC = attached
    except Exception as exc:
        ready_q.put(f"{type(exc).__name__}: {exc}"[:300])
        return
    ready_q.put("")

    lock = threading.Lock()
    lat: list[float] = []
    total_done = [0]          # every completion, any phase
    rec_count = [0]           # completions recorded (success + error)
    rec_t_first = [0.0]
    rec_t_last = [0.0]
    errors = [0]              # errors inside the recording window
    first_error: list[str] = []
    recording = threading.Event()
    done_evt = threading.Event()
    sem = threading.Semaphore(concurrency)
    steady_rate = [0.0]
    truncated = [False]

    def on_done(fut, t0: float) -> None:
        now = time.perf_counter()
        # window edges use wall clock: the parent aggregates edges
        # ACROSS worker processes (perf_counter epochs are per-process)
        wall = time.time()
        ok, msg = True, ""
        try:
            fut.result()
        except Exception as exc:
            ok, msg = False, f"{type(exc).__name__}: {exc}"[:300]
        with lock:
            total_done[0] += 1
            if recording.is_set() and rec_count[0] < n_record:
                rec_count[0] += 1
                if rec_t_first[0] == 0.0:
                    rec_t_first[0] = wall
                rec_t_last[0] = wall
                if ok:
                    lat.append(now - t0)
                else:
                    errors[0] += 1
                    if not first_error:
                        first_error.append(msg)
                if rec_count[0] >= n_record:
                    done_evt.set()
            elif not ok and not first_error:
                first_error.append(msg)
        sem.release()

    def phase_monitor() -> None:
        # wait for the parent's go signal
        t_hard = time.time() + _PRE_GO_HARD_STOP
        while start_val.value == 0.0 and time.time() < t_hard:
            time.sleep(0.05)
        # steady-state: two consecutive 1s windows within 30%
        t_cap = time.time() + _STEADY_CAP_S
        prev = -1
        stable = 0
        while time.time() < t_cap and stable < 2:
            with lock:
                c0 = total_done[0]
            time.sleep(1.0)
            with lock:
                rate = total_done[0] - c0
            if prev >= 0 and rate > 0 and \
                    abs(rate - prev) <= 0.3 * max(rate, prev):
                stable += 1
            else:
                stable = 0
            prev = rate
        steady_rate[0] = float(max(prev, 0))
        recording.set()
        if not done_evt.wait(timeout=_RECORD_HARD_S):
            truncated[0] = True
            done_evt.set()

    mon = threading.Thread(target=phase_monitor, daemon=True)
    mon.start()

    i = 0
    # traffic flows immediately (warming jit buckets/caches); the
    # monitor thread decides when completions start being recorded
    while not done_evt.is_set():
        if not sem.acquire(timeout=1.0):
            continue      # stall: re-check done_evt, never block blind
        if done_evt.is_set():
            sem.release()
            break
        p = payloads[i % len(payloads)]
        i += 1
        t0 = time.perf_counter()
        fut = call.future(p, timeout=_CALL_TIMEOUT_S)
        fut.add_done_callback(lambda f, t0=t0: on_done(f, t0))
    # drain by re-acquiring every permit: all callbacks have run (and
    # released) once acquisition succeeds; the per-call deadline bounds
    # the wait
    for _ in range(concurrency):
        sem.acquire(timeout=2 * _CALL_TIMEOUT_S)
    channel.close()
    with lock:
        q.put((np.asarray(lat, np.float64), errors[0],
               first_error[0] if first_error else "",
               rec_count[0], rec_t_first[0], rec_t_last[0],
               total_done[0] - rec_count[0],
               steady_rate[0], truncated[0]))


def run_load(target: str, payloads: Sequence[bytes],
             n_record: int = 2000, n_procs: int = 4,
             concurrency: int = 32, warmup_s: float = 2.0,
             method: str = "/istio.mixer.v1.Mixer/Check",
             checks_per_payload: int = 1,
             on_go: Any = None) -> PerfReport:
    """Fire Check load at `target`; record the next `n_record`
    completions per worker after attach + warmup + steady-state, and
    report client-side numbers from those completions.

    `on_go`: zero-arg callable invoked IN THIS PROCESS the moment the
    go signal fires (warmup over, workers entering steady-state
    detection) — the hook the bench uses to reset server-side latency
    windows / take stage baselines so warmup traffic stays out of the
    scraped decomposition. Exceptions are swallowed: a metrics hook
    must never kill a measurement.

    Raises PerfError only if attachment fails or literally no RPC
    completes inside the recording window's hard deadline — a rig that
    can report a plausible zero without failing is worse than no rig
    (VERDICT r2 weak #1); a window defined by completions cannot close
    empty while the server answers at all (VERDICT r3 item 1).
    """
    # spawn, not fork: grpc's internal threads/state do not survive a
    # fork once the parent has created a server/channel
    ctx = mp.get_context("spawn")
    q: "mp.Queue" = ctx.Queue()
    ready_q: "mp.Queue" = ctx.Queue()
    start_val = ctx.Value("d", 0.0)   # 0 = warmup not yet begun
    procs = [ctx.Process(target=_worker,
                         args=(target, list(payloads), int(n_record),
                               concurrency, start_val, ready_q, q,
                               method),
                         daemon=True)
             for _ in range(n_procs)]
    for p in procs:
        p.start()
    try:
        try:
            for _ in procs:
                err = ready_q.get(timeout=300)
                if err:
                    raise PerfError(f"worker failed to attach: {err}")
        except PerfError:
            raise
        except Exception as exc:
            raise PerfError(f"worker never reported ready: "
                            f"{type(exc).__name__}: {exc}") from exc
        # every worker is connected and has a response in hand — give
        # the go signal after warmup_s of free-running traffic; each
        # worker then self-detects a steady completion rate before it
        # starts recording
        time.sleep(warmup_s)
        if on_go is not None:
            try:
                on_go()
            except Exception:
                pass
        start_val.value = time.time()
        all_lat: list[np.ndarray] = []
        n_err = 0
        n_rec_total = 0
        n_warm = 0
        t_first_min = float("inf")
        t_last_max = 0.0
        steady_sum = 0.0
        first_error = ""
        truncated = False
        per_worker_timeout = (warmup_s + _STEADY_CAP_S +
                              _RECORD_HARD_S + 3 * _CALL_TIMEOUT_S)
        for _ in procs:
            (lat, errs, err_msg, n_rec, t_first, t_last, warm, steady,
             trunc) = q.get(timeout=per_worker_timeout)
            all_lat.append(lat)
            n_err += errs
            n_rec_total += n_rec
            n_warm += warm
            if n_rec:
                t_first_min = min(t_first_min, t_first)
                t_last_max = max(t_last_max, t_last)
            steady_sum += steady
            truncated = truncated or trunc
            first_error = first_error or err_msg
        for p in procs:
            p.join(timeout=10)
    except Exception:
        # attached workers would otherwise keep firing warmup traffic
        # until their hard stop, polluting everything after us
        for p in procs:
            if p.is_alive():
                p.terminate()
        raise
    lat = np.concatenate(all_lat) if all_lat else np.zeros(0)
    n = int(lat.size)
    if n_rec_total == 0:
        raise PerfError(
            "no RPC completed inside the recording window "
            f"(warmup completions={n_warm}, errors={n_err}, "
            f"first_error={first_error!r})")
    if n == 0:
        raise PerfError(
            f"all {n_rec_total} recorded completions were errors "
            f"(first_error={first_error!r})")
    # aggregate rate over the UNION of worker windows: per-worker rates
    # summed over staggered windows would credit still-recording
    # workers with the capacity freed by already-finished ones; the
    # union span slightly UNDERestimates instead — the right bias for
    # a benchmark artifact
    span = max(t_last_max - t_first_min, 0.0)
    rate = (n_rec_total - 1) / span if n_rec_total > 1 and span > 0 \
        else 0.0
    return PerfReport(
        checks_per_sec=rate * checks_per_payload,
        p50_ms=float(np.percentile(lat, 50) * 1e3),
        p99_ms=float(np.percentile(lat, 99) * 1e3),
        mean_ms=float(lat.mean() * 1e3),
        n_requests=n, n_errors=n_err, duration_s=span,
        n_procs=len(procs), concurrency=concurrency,
        first_error=first_error,
        warmup_completions=n_warm,
        steady_rate_per_sec=steady_sum,
        truncated=truncated)
