"""Perf rig — load generation decoupled from the server.

Reference: mixer/pkg/perf (controller.go:27 + clientserver.go): a
controller drives external client processes that fire attribute load at
the server, and throughput/latency are measured AT THE CLIENT, through
the full stack (gRPC decode → tensorize → device step → response).
Benchmarks: mixer/test/perf/singlecheck_test.go:53.

Clients are separate OS processes (the GIL must not couple load
generation to the server under test); each worker runs `concurrency`
threads of blocking Check RPCs over its own channel, cycling through
pre-serialized request payloads, and reports latency samples back over
a queue.
"""
from __future__ import annotations

import dataclasses
import multiprocessing as mp
import time
from typing import Any, Mapping, Sequence

import numpy as np


def make_check_payloads(dicts: Sequence[Mapping[str, Any]],
                        quota_every: int = 0,
                        quota_name: str = "rq") -> list[bytes]:
    """Pre-serialized CheckRequest bytes for the worker processes.
    `quota_every` > 0 attaches a quota request (amount 1, no dedup) to
    every Nth payload — served quota traffic rides the e2e number."""
    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.api.wire import bag_to_compressed
    from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST

    out = []
    for i, values in enumerate(dicts):
        req = pb.CheckRequest(global_word_count=len(GLOBAL_WORD_LIST))
        bag_to_compressed(values, msg=req.attributes)
        if quota_every and i % quota_every == 0:
            req.quotas[quota_name].amount = 1
            req.quotas[quota_name].best_effort = True
        out.append(req.SerializeToString())
    return out


@dataclasses.dataclass
class PerfReport:
    checks_per_sec: float
    p50_ms: float
    p99_ms: float
    mean_ms: float
    n_requests: int
    n_errors: int
    duration_s: float
    n_procs: int
    concurrency: int
    first_error: str = ""


class PerfError(RuntimeError):
    """The rig failed to measure — NEVER reported as a zero result."""


def _worker(target: str, payloads: list[bytes], duration_s: float,
            concurrency: int, start_val, ready_q: "mp.Queue",
            q: "mp.Queue") -> None:
    """`concurrency` requests in flight via one issuing thread +
    completion callbacks on grpc's IO threads — a blocked thread per
    RPC melts the GIL at the depths a ~100ms-RTT device transport
    needs to stay busy (this rig has ONE core for server AND client).

    Readiness handshake (the mixer/pkg/perf/clientserver.go:30-90
    attach pattern): the worker connects AND completes one full RPC
    before reporting ready; the parent opens the measurement window —
    by writing the shared `start_val` — only once every worker has
    attached, so a slow spawn/import can never eat the window."""
    import threading

    import grpc

    try:
        channel = grpc.insecure_channel(target)
        call = channel.unary_unary(
            "/istio.mixer.v1.Mixer/Check",
            request_serializer=lambda b: b,    # already serialized
            response_deserializer=lambda b: b)  # latency only; no parse
        grpc.channel_ready_future(channel).result(timeout=30)
        call(payloads[0], timeout=60)   # one full round-trip = attached
    except Exception as exc:
        ready_q.put(f"{type(exc).__name__}: {exc}"[:300])
        return
    ready_q.put("")

    lat: list[float] = []
    errors = [0]
    first_error: list[str] = []
    lock = threading.Lock()
    sem = threading.Semaphore(concurrency)
    hard_stop = time.time() + 600.0   # parent died without a go signal

    def on_done(fut, t0: float, measured: bool) -> None:
        try:
            fut.result()
            if measured:
                with lock:
                    lat.append(time.perf_counter() - t0)
        except Exception as exc:
            with lock:
                if measured:
                    errors[0] += 1
                if not first_error:
                    first_error.append(f"{type(exc).__name__}: "
                                       f"{exc}"[:300])
        finally:
            sem.release()

    i = 0
    # traffic flows immediately (warming jit buckets/caches); only
    # calls begun inside the [start_at, start_at+duration) window are
    # recorded. start_val is 0 until the parent opens the window.
    while True:
        start_at = start_val.value
        now = time.time()
        if (start_at and now >= start_at + duration_s) or now >= hard_stop:
            break
        sem.acquire()
        p = payloads[i % len(payloads)]
        i += 1
        t0 = time.perf_counter()
        fut = call.future(p, timeout=60)
        fut.add_done_callback(
            lambda f, t0=t0, m=bool(start_at) and now >= start_at:
                on_done(f, t0, m))
    # drain by re-acquiring every permit: all callbacks have run (and
    # released) once acquisition succeeds, so the snapshot below races
    # nothing; the per-call 60s deadline bounds the wait
    for _ in range(concurrency):
        sem.acquire()
    channel.close()
    with lock:
        q.put((np.asarray(lat, np.float64), errors[0],
               first_error[0] if first_error else ""))


def run_load(target: str, payloads: Sequence[bytes],
             duration_s: float = 5.0, n_procs: int = 4,
             concurrency: int = 32, warmup_s: float = 2.0) -> PerfReport:
    """Fire Check load at `target` and report client-side numbers.

    Three phases: (1) workers spawn, connect, and each completes one
    RPC, then reports ready; (2) the parent opens a shared measurement
    window `warmup_s` in the future (pre-window traffic warms the
    server's jit buckets); (3) only calls issued inside the window are
    recorded. Raises PerfError if attachment fails or the measured
    window contains zero requests — a rig that can report a plausible
    zero without failing is worse than no rig (VERDICT r2 weak #1)."""
    # spawn, not fork: grpc's internal threads/state do not survive a
    # fork once the parent has created a server/channel
    ctx = mp.get_context("spawn")
    q: "mp.Queue" = ctx.Queue()
    ready_q: "mp.Queue" = ctx.Queue()
    start_val = ctx.Value("d", 0.0)   # 0 = window not yet open
    procs = [ctx.Process(target=_worker,
                         args=(target, list(payloads), duration_s,
                               concurrency, start_val, ready_q, q),
                         daemon=True)
             for _ in range(n_procs)]
    for p in procs:
        p.start()
    try:
        try:
            for _ in procs:
                err = ready_q.get(timeout=300)
                if err:
                    raise PerfError(f"worker failed to attach: {err}")
        except PerfError:
            raise
        except Exception as exc:
            raise PerfError(f"worker never reported ready: "
                            f"{type(exc).__name__}: {exc}") from exc
        # every worker is connected and has a response in hand — NOW
        # the clock starts
        start_val.value = time.time() + warmup_s
        all_lat: list[np.ndarray] = []
        n_err = 0
        first_error = ""
        for _ in procs:
            lat, errs, err_msg = q.get(
                timeout=duration_s + warmup_s + 120)
            all_lat.append(lat)
            n_err += errs
            first_error = first_error or err_msg
        for p in procs:
            p.join(timeout=10)
    except Exception:
        # attached workers would otherwise keep firing warmup traffic
        # until their 600s hard stop, polluting everything after us
        for p in procs:
            if p.is_alive():
                p.terminate()
        raise
    lat = np.concatenate(all_lat) if all_lat else np.zeros(0)
    n = int(lat.size)
    if n == 0:
        raise PerfError(
            "measurement window closed with zero recorded requests "
            f"(errors={n_err}, first_error={first_error!r})")
    wall = duration_s
    return PerfReport(
        checks_per_sec=n / wall if wall > 0 else 0.0,
        p50_ms=float(np.percentile(lat, 50) * 1e3),
        p99_ms=float(np.percentile(lat, 99) * 1e3),
        mean_ms=float(lat.mean() * 1e3),
        n_requests=n, n_errors=n_err, duration_s=wall,
        n_procs=len(procs), concurrency=concurrency,
        first_error=first_error)
