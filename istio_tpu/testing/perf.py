"""Perf rig — load generation decoupled from the server.

Reference: mixer/pkg/perf (controller.go:27 + clientserver.go): a
controller drives external client processes that fire attribute load at
the server, and throughput/latency are measured AT THE CLIENT, through
the full stack (gRPC decode → tensorize → device step → response).
Benchmarks: mixer/test/perf/singlecheck_test.go:53.

Clients are separate OS processes (the GIL must not couple load
generation to the server under test); each worker runs `concurrency`
threads of blocking Check RPCs over its own channel, cycling through
pre-serialized request payloads, and reports latency samples back over
a queue.
"""
from __future__ import annotations

import dataclasses
import multiprocessing as mp
import time
from typing import Any, Mapping, Sequence

import numpy as np


def make_check_payloads(dicts: Sequence[Mapping[str, Any]]) -> list[bytes]:
    """Pre-serialized CheckRequest bytes for the worker processes."""
    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.api.wire import bag_to_compressed
    from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST

    out = []
    for values in dicts:
        req = pb.CheckRequest(global_word_count=len(GLOBAL_WORD_LIST))
        bag_to_compressed(values, msg=req.attributes)
        out.append(req.SerializeToString())
    return out


@dataclasses.dataclass
class PerfReport:
    checks_per_sec: float
    p50_ms: float
    p99_ms: float
    mean_ms: float
    n_requests: int
    n_errors: int
    duration_s: float
    n_procs: int
    concurrency: int
    first_error: str = ""


def _worker(target: str, payloads: list[bytes], duration_s: float,
            concurrency: int, start_at: float, q: "mp.Queue") -> None:
    """`concurrency` requests in flight via one issuing thread +
    completion callbacks on grpc's IO threads — a blocked thread per
    RPC melts the GIL at the depths a ~100ms-RTT device transport
    needs to stay busy (this rig has ONE core for server AND client)."""
    import threading

    import grpc

    channel = grpc.insecure_channel(target)
    call = channel.unary_unary(
        "/istio.mixer.v1.Mixer/Check",
        request_serializer=lambda b: b,       # already serialized
        response_deserializer=lambda b: b)    # latency only; skip parse
    grpc.channel_ready_future(channel).result(timeout=30)

    lat: list[float] = []
    errors = [0]
    first_error: list[str] = []
    lock = threading.Lock()
    sem = threading.Semaphore(concurrency)
    deadline = start_at + duration_s

    def on_done(fut, t0: float, measured: bool) -> None:
        try:
            fut.result()
            if measured:
                with lock:
                    lat.append(time.perf_counter() - t0)
        except Exception as exc:
            with lock:
                if measured:
                    errors[0] += 1
                if not first_error:
                    first_error.append(f"{type(exc).__name__}: "
                                       f"{exc}"[:300])
        finally:
            sem.release()

    i = 0
    # traffic flows immediately (warming jit buckets/caches); only
    # calls begun inside the measurement window are recorded
    while True:
        now = time.time()
        if now >= deadline:
            break
        sem.acquire()
        p = payloads[i % len(payloads)]
        i += 1
        t0 = time.perf_counter()
        fut = call.future(p, timeout=60)
        fut.add_done_callback(
            lambda f, t0=t0, m=now >= start_at: on_done(f, t0, m))
    # drain by re-acquiring every permit: all callbacks have run (and
    # released) once acquisition succeeds, so the snapshot below races
    # nothing; the per-call 60s deadline bounds the wait
    for _ in range(concurrency):
        sem.acquire()
    channel.close()
    with lock:
        q.put((np.asarray(lat, np.float64), errors[0],
               first_error[0] if first_error else ""))


def run_load(target: str, payloads: Sequence[bytes],
             duration_s: float = 5.0, n_procs: int = 4,
             concurrency: int = 32, warmup_s: float = 2.0) -> PerfReport:
    """Fire Check load at `target` and report client-side numbers.

    A shared start timestamp aligns the measurement window across
    workers; `warmup_s` of pre-traffic warms the server's jit buckets
    before the window opens."""
    # spawn, not fork: grpc's internal threads/state do not survive a
    # fork once the parent has created a server/channel
    ctx = mp.get_context("spawn")
    q: "mp.Queue" = ctx.Queue()
    start_at = time.time() + warmup_s
    procs = [ctx.Process(target=_worker,
                         args=(target, list(payloads), duration_s,
                               concurrency, start_at, q), daemon=True)
             for _ in range(n_procs)]
    for p in procs:
        p.start()
    all_lat: list[np.ndarray] = []
    n_err = 0
    first_error = ""
    for _ in procs:
        lat, errs, err_msg = q.get(timeout=duration_s + warmup_s + 120)
        all_lat.append(lat)
        n_err += errs
        first_error = first_error or err_msg
    for p in procs:
        p.join(timeout=10)
    lat = np.concatenate(all_lat) if all_lat else np.zeros(0)
    n = int(lat.size)
    wall = duration_s
    return PerfReport(
        checks_per_sec=n / wall if wall > 0 else 0.0,
        p50_ms=float(np.percentile(lat, 50) * 1e3) if n else 0.0,
        p99_ms=float(np.percentile(lat, 99) * 1e3) if n else 0.0,
        mean_ms=float(lat.mean() * 1e3) if n else 0.0,
        n_requests=n, n_errors=n_err, duration_s=wall,
        n_procs=len(procs), concurrency=concurrency,
        first_error=first_error)
