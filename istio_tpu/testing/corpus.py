"""The expression-conformance corpus.

One shared table of cases proving the semantics contract of the policy
expression language (reference behavior defined by mixer/pkg/il/testing/
tests.go and the IL compiler/interpreter it exercises). Consumed by:

  * tests/test_expr_oracle.py   — the host oracle interpreter
  * tests/test_tensor_compiler.py — the TPU tensor compiler
  * tests/test_ruleset.py      — the batched DNF rule matcher

Cases are authored fresh against the semantics in SURVEY.md §2.1: 3-valued
presence, `|` fallback, short-circuit booleans, typed equality (IP and
TIMESTAMP via externs), glob/regex string predicates, string-map indexing,
and exact referenced-attribute tracking.
"""
from __future__ import annotations

import dataclasses
import datetime
from typing import Any

from istio_tpu.attribute.types import ValueType, parse_go_duration, parse_ip, parse_rfc3339

V = ValueType

# The attribute vocabulary used by every corpus case.
CORPUS_MANIFEST: dict[str, ValueType] = {
    # generic typed test attributes (reference naming style: a<type>)
    "a": V.INT64, "b": V.INT64, "d": V.INT64, "x": V.INT64, "y": V.INT64,
    "ai": V.INT64, "ai2": V.INT64,
    "ad": V.DOUBLE, "ad2": V.DOUBLE,
    "ab": V.BOOL, "ab2": V.BOOL,
    "as": V.STRING, "as2": V.STRING,
    "ar": V.STRING_MAP, "ar2": V.STRING_MAP,
    "adur": V.DURATION,
    "at": V.TIMESTAMP, "at2": V.TIMESTAMP,
    "aip": V.IP_ADDRESS, "aip2": V.IP_ADDRESS,
    # mesh-flavored attributes
    "request.user": V.STRING, "request.user2": V.STRING,
    "request.user3": V.STRING,
    "request.size": V.INT64,
    "request.path": V.STRING,
    "request.time": V.TIMESTAMP,
    "request.header": V.STRING_MAP,
    "headername": V.STRING,
    "servicename": V.STRING,
    "origin.name": V.STRING,
    "service.name": V.STRING, "service.user": V.STRING,
    "source.name": V.STRING, "source.namespace": V.STRING,
    "source.labels": V.STRING_MAP,
    "destination.service": V.STRING,
    "destination.namespace": V.STRING,
    "context.protocol": V.STRING,
    "target.ip": V.IP_ADDRESS,
    "target.service": V.STRING,
    "connection.duration": V.DURATION,
    "api.operation": V.STRING,
}


@dataclasses.dataclass
class Case:
    e: str                           # expression source
    input: dict[str, Any] = dataclasses.field(default_factory=dict)
    result: Any = None               # expected value (when err is None)
    err: str | None = None           # expected runtime-error substring
    compile_err: str | None = None   # expected parse/type-check error substring
    type_: ValueType | None = None   # expected static type
    referenced: list[str] | None = None  # expected referenced-attribute snapshot
    name: str = ""

    def id(self) -> str:
        return self.name or self.e


_t1 = parse_rfc3339("2015-01-02T15:04:35Z")
_t2 = parse_rfc3339("2015-01-02T15:04:34Z")
_d19 = parse_go_duration("19ms")
_d20 = parse_go_duration("20ms")

CORPUS: list[Case] = [
    # ---- benchmark triple: the reference's ExprBench shapes ----
    Case(name="ExprBench/ok_1st",
         e='ai == 20 || ar["foo"] == "bar"', type_=V.BOOL,
         input={"ai": 20, "ar": {"foo": "bar"}}, result=True,
         referenced=["ai"]),
    Case(name="ExprBench/ok_2nd",
         e='ai == 20 || ar["foo"] == "bar"', type_=V.BOOL,
         input={"ai": 2, "ar": {"foo": "bar"}}, result=True,
         referenced=["ai", "ar", "ar[foo]"]),
    Case(name="ExprBench/not_found",
         e='ai == 20 || ar["foo"] == "bar"', type_=V.BOOL,
         input={"ai": 2, "ar": {"foo": "baz"}}, result=False,
         referenced=["ai", "ar", "ar[foo]"]),

    # ---- literals & bare attributes ----
    Case(e="2", type_=V.INT64, result=2),
    Case(e="2.25", type_=V.DOUBLE, result=2.25),
    Case(e='"str"', type_=V.STRING, result="str"),
    Case(e="true", type_=V.BOOL, result=True),
    Case(e="false", type_=V.BOOL, result=False),
    Case(e='"20ms"', type_=V.DURATION, result=_d20),
    Case(e='"1h2m"', type_=V.DURATION,
         result=parse_go_duration("1h2m")),
    Case(e="a", type_=V.INT64, input={"a": 2}, result=2, referenced=["a"]),
    Case(e="a ", type_=V.INT64, input={"a": 2}, result=2),
    Case(e="as", type_=V.STRING, input={"as": "v"}, result="v"),
    Case(e="ab", type_=V.BOOL, input={"ab": True}, result=True),
    Case(e="ad", type_=V.DOUBLE, input={"ad": 1.5}, result=1.5),
    Case(e="a", input={}, err="lookup failed: 'a'", referenced=["a"]),

    # ---- integer equality ----
    Case(e="a == 2", type_=V.BOOL, input={"a": 2}, result=True,
         referenced=["a"]),
    Case(e="a == 3", type_=V.BOOL, input={"a": 2}, result=False),
    Case(e="a != 2", type_=V.BOOL, input={"a": 2}, result=False,
         referenced=["a"]),
    Case(e="a != 2", type_=V.BOOL, input={"d": 2},
         err="lookup failed: 'a'", referenced=["a"]),
    Case(e="2 != a", type_=V.BOOL, input={"d": 2},
         err="lookup failed: 'a'", referenced=["a"]),
    Case(e="2 == 2", type_=V.BOOL, result=True),
    Case(e="a == b", type_=V.BOOL, input={"a": 5, "b": 5}, result=True),
    Case(e="a == b", type_=V.BOOL, input={"a": 5, "b": 6}, result=False),

    # ---- double / bool / string equality ----
    Case(e="ad == 1.5", type_=V.BOOL, input={"ad": 1.5}, result=True),
    Case(e="ad != 1.5", type_=V.BOOL, input={"ad": 2.5}, result=True),
    Case(e="ab == true", type_=V.BOOL, input={"ab": True}, result=True),
    Case(e="ab == false", type_=V.BOOL, input={"ab": True}, result=False),
    Case(e='as == "v"', type_=V.BOOL, input={"as": "v"}, result=True),
    Case(e='as == "w"', type_=V.BOOL, input={"as": "v"}, result=False),
    Case(e='as == as2', type_=V.BOOL, input={"as": "x", "as2": "x"},
         result=True),
    Case(e='request.user == "user1"', type_=V.BOOL,
         input={"request.user": "user1"}, result=True),

    # ---- type-check failures ----
    Case(e="true == a", input={"a": 2},
         compile_err="typeError got INT64, expected BOOL"),
    Case(e="3.14 == a", input={"a": 2},
         compile_err="typeError got INT64, expected DOUBLE"),
    Case(e='as == 2', input={"as": "v"},
         compile_err="typeError got INT64, expected STRING"),
    Case(e="(x/y) == 30", input={"x": 20, "y": 10},
         compile_err="unknown function: QUO"),
    # ---- ordered comparisons (reference expr/func.go LT/LEQ/GT/GEQ) ----
    Case(e="x < 2", input={"x": 1}, result=True, type_=V.BOOL,
         referenced=["x"]),
    Case(e="x <= 2", input={"x": 2}, result=True),
    Case(e="x > 2", input={"x": 3}, result=True),
    Case(e="x >= 4", input={"x": 3}, result=False),
    Case(e='as < "b"', input={"as": "a"}, result=True),
    Case(e='as >= "b"', input={"as": "a"}, result=False),
    Case(e="ad > 1.5", input={"ad": 1.0}, result=False),
    Case(e="adur <= adur", input={"adur": _d19}, result=True),
    Case(e="x > 2", input={}, err="lookup failed: 'x'"),
    Case(e="ab < ab2", input={"ab": True, "ab2": False},
         err="unordered operand"),
    # ordered-comparison edges the device order keys must get right
    Case(e="ad < 0.0", input={"ad": -0.0}, result=False,
         name="neg zero lt"),
    Case(e="ad >= 0.0", input={"ad": -0.0}, result=True,
         name="neg zero geq"),
    Case(e="ad < 0.5", input={"ad": float("nan")}, result=False,
         name="nan lt"),
    Case(e="ad >= 0.5", input={"ad": float("nan")}, result=False,
         name="nan geq"),
    Case(e="x < 0", input={"x": -5}, result=True),
    Case(e="x >= 1099511627776", input={"x": 1 << 41}, result=True,
         name="big int64 cmp"),
    Case(e="ad2 > ad", input={"ad": -1.5, "ad2": 2.5}, result=True),
    Case(e="at < at2", input={"at": _t2, "at2": _t1}, result=True),
    Case(e='as2 <= as', input={"as": "b", "as2": "ab"}, result=True),
    # dynamic byte patterns (runtime prefix/suffix/glob)
    Case(e="as.startsWith(as2)", input={"as": "hello", "as2": "he"},
         result=True),
    Case(e="as.startsWith(as2)", input={"as": "hello", "as2": "lo"},
         result=False),
    Case(e="as.endsWith(as2)", input={"as": "hello", "as2": "lo"},
         result=True),
    Case(e="match(as, as2)", input={"as": "svc.prod", "as2": "svc.*"},
         result=True, name="dyn glob prefix"),
    Case(e="match(as, as2)", input={"as": "svc.prod", "as2": "*.prod"},
         result=True, name="dyn glob suffix"),
    Case(e="match(as, as2)", input={"as": "svc.prod",
                                    "as2": "svc.prod"},
         result=True, name="dyn glob exact"),
    Case(e="match(as, as2)", input={"as": "svc.prod", "as2": "*x"},
         result=False, name="dyn glob miss"),
    # runtime extern conversions (ingest-normalized on device)
    Case(e='ip(as) == ip("1.2.3.4")', input={"as": "1.2.3.4"},
         result=True, name="runtime ip eq"),
    Case(e='ip(as) == aip', input={"as": "1.2.3.4",
                                   "aip": b"\x00" * 10 + b"\xff\xff"
                                   + bytes([1, 2, 3, 4])},
         result=True, name="runtime ip vs attr"),
    Case(e='ip(as) == ip("1.2.3.5")', input={"as": "not-an-ip"},
         err="could not convert", name="runtime ip bad"),
    Case(e='timestamp(as) == at',
         input={"as": "2015-01-02T15:04:35Z", "at": _t1},
         result=True, name="runtime timestamp eq"),
    # map fallback indexing
    Case(e='(ar | ar2)["foo"]', input={"ar2": {"foo": "x"}},
         result="x", type_=V.STRING, name="map fallback second"),
    Case(e='(ar | ar2)["foo"]', input={"ar": {"foo": "a"},
                                       "ar2": {"foo": "x"}},
         result="a", name="map fallback first"),
    Case(e='(ar | ar2)["foo"] | "dflt"', input={"ar": {"bar": "y"}},
         result="dflt", name="map fallback key miss"),
    Case(e="x < ad", input={"x": 1, "ad": 2.0},
         compile_err="typeError got DOUBLE, expected INT64"),
    Case(e="!ab", input={"ab": True}, compile_err="unknown function: NOT"),
    Case(e="a = 2", input={"a": 2}, compile_err="unable to parse"),
    Case(e="@23", compile_err="unable to parse"),
    Case(e="unknown.attr == 2", compile_err="unknown attribute unknown.attr"),
    Case(e="doesnotexist(as)", input={"as": "v"},
         compile_err="unknown function: doesnotexist"),
    Case(e='match(service.name, 1)', input={"service.name": "x"},
         compile_err="typeError got INT64, expected STRING"),
    Case(e='ip(2)', compile_err="typeError got INT64, expected STRING"),
    Case(e='timestamp(2)', compile_err="typeError got INT64, expected STRING"),
    Case(e='"aaa".matches(23)', compile_err="typeError got INT64, expected STRING"),
    Case(e='"aaa".startsWith(23)', compile_err="typeError got INT64, expected STRING"),
    Case(e='match(as)', input={"as": "v"}, compile_err="arity mismatch"),
    Case(e='startsWith("x")', compile_err="invoking instance method without an instance"),

    # ---- fallback `|` ----
    Case(e='request.user | "user1"', type_=V.STRING,
         input={"request.user": "u"}, result="u",
         referenced=["request.user"]),
    Case(e='request.user | "user1"', type_=V.STRING, input={},
         result="user1", referenced=["request.user"]),
    Case(e='request.user2 | request.user | "user1"', type_=V.STRING,
         input={"request.user": "user2"}, result="user2",
         referenced=["request.user", "request.user2"]),
    Case(e='request.user2 | request.user3 | "user1"', type_=V.STRING,
         input={"request.user": "user2"}, result="user1",
         referenced=["request.user2", "request.user3"]),
    Case(e="request.size | 200", type_=V.INT64,
         input={"request.size": 120}, result=120,
         referenced=["request.size"]),
    Case(e="request.size | 200", type_=V.INT64,
         input={"request.size": 0}, result=0),
    Case(e="request.size | 200", type_=V.INT64,
         input={"request.size1": 0}, result=200),
    Case(e='( origin.name | "unknown" ) == "users"', type_=V.BOOL,
         input={}, result=False),
    Case(e='( origin.name | "unknown" ) == "users"', type_=V.BOOL,
         input={"origin.name": "users"}, result=True),
    Case(e='origin.name | "users"', type_=V.STRING, input={},
         result="users"),
    Case(e="ab | true", type_=V.BOOL, input={}, result=True),
    Case(e="ab | false", type_=V.BOOL, input={"ab": True}, result=True),
    Case(e="ad | 1.25", type_=V.DOUBLE, input={}, result=1.25),
    Case(e='adur | "19ms"', type_=V.DURATION, input={}, result=_d19),
    Case(e='adur | "19ms"', type_=V.DURATION, input={"adur": _d20},
         result=_d20),
    Case(e="ai | ai2 | 42", type_=V.INT64, input={"ai2": 7}, result=7,
         referenced=["ai", "ai2"]),
    # fallback whose right side is a hard error still errors
    Case(e='target.ip | ip("10.1.12.3")', type_=V.IP_ADDRESS, input={},
         result=parse_ip("10.1.12.3"), referenced=["target.ip"]),
    Case(e='target.ip | ip("10.1.12")', type_=V.IP_ADDRESS, input={},
         err="could not convert 10.1.12 to IP_ADDRESS"),
    Case(e='request.time | timestamp("2015-01-02T15:04:35Z")',
         type_=V.TIMESTAMP, input={}, result=_t1,
         referenced=["request.time"]),
    Case(e='request.time | timestamp("242233")', type_=V.TIMESTAMP,
         input={}, err="could not convert '242233' to TIMESTAMP"),
    # type mismatch across `|` arms
    Case(e='request.size | "big"', compile_err="typeError"),

    # ---- short-circuit && / || ----
    Case(e="(x == 20 && y == 10) || x == 30", type_=V.BOOL,
         input={"x": 20, "y": 10}, result=True),
    Case(e="x == 20 && y == 10", input={"a": 20, "b": 10},
         err="lookup failed: 'x'"),
    Case(e="x == 20 && y == 10", input={"x": 20},
         err="lookup failed: 'y'"),
    # false && <error> short-circuits: no error
    Case(e="x == 21 && y == 10", type_=V.BOOL, input={"x": 20},
         result=False, referenced=["x"]),
    # true || <error> short-circuits: no error
    Case(e="x == 20 || y == 10", type_=V.BOOL, input={"x": 20},
         result=True, referenced=["x"]),
    Case(e="x == 21 || y == 10", input={"x": 20},
         err="lookup failed: 'y'", referenced=["x", "y"]),
    Case(e="ab && ab2", type_=V.BOOL, input={"ab": True, "ab2": True},
         result=True),
    Case(e="ab && ab2", type_=V.BOOL, input={"ab": False}, result=False),
    Case(e="ab || ab2", type_=V.BOOL, input={"ab": False, "ab2": True},
         result=True),
    Case(e="true && false", type_=V.BOOL, result=False,
         name="bench/land_tf"),
    Case(e="true && true", type_=V.BOOL, result=True, name="bench/land_tt"),
    Case(e="false && false", type_=V.BOOL, result=False,
         name="bench/land_ff"),
    Case(e="ab == true && as == \"v\"", type_=V.BOOL,
         input={"ab": True, "as": "v"}, result=True),

    # ---- string maps ----
    Case(e='ar["foo"]', type_=V.STRING, input={"ar": {"foo": "bar"}},
         result="bar", referenced=["ar", "ar[foo]"]),
    Case(e='ar["foo"]', input={"ar": {"baz": "bar"}},
         err="member lookup failed: 'foo'", referenced=["ar", "ar[foo]"]),
    Case(e='ar["foo"]', input={}, err="lookup failed: 'ar'",
         referenced=["ar"]),
    Case(e='request.header["X-FORWARDED-HOST"] == "aaa"', type_=V.BOOL,
         input={"request.header": {"X-FORWARDED-HOST": "bbb"}},
         result=False,
         referenced=["request.header", "request.header[X-FORWARDED-HOST]"]),
    Case(e='request.header["X-FORWARDED-HOST"] == "aaa"',
         input={"request.header1": {"X-FORWARDED-HOST": "bbb"}},
         err="lookup failed: 'request.header'",
         referenced=["request.header"]),
    Case(e='request.header[headername] == "aaa"',
         input={"request.header": {"X-FORWARDED-HOST": "bbb"}},
         err="lookup failed: 'headername'"),
    Case(e='request.header[headername] == "aaa"', type_=V.BOOL,
         input={"request.header": {"X-FORWARDED-HOST": "aaa"},
                "headername": "X-FORWARDED-HOST"},
         result=True),
    Case(e='ar["foo"] | "dflt"', type_=V.STRING,
         input={"ar": {"foo": "bar"}}, result="bar"),
    Case(e='ar["foo"] | "dflt"', type_=V.STRING,
         input={"ar": {"baz": "bar"}}, result="dflt"),
    # map absent under fallback ALSO falls through (tresolve_m path)
    Case(e='ar["foo"] | "dflt"', type_=V.STRING, input={},
         result="dflt"),
    Case(e='ar[as] | "dflt"', type_=V.STRING,
         input={"ar": {"k": "x"}, "as": "k"}, result="x"),
    Case(e='ar[as] | "dflt"', type_=V.STRING, input={"ar": {"k": "x"}},
         result="dflt"),
    Case(e='ar["a"] == ar2["b"]', type_=V.BOOL,
         input={"ar": {"a": "same"}, "ar2": {"b": "same"}}, result=True),

    # ---- externs: match (glob) ----
    Case(e='match(service.name, "*.ns1.cluster")', type_=V.BOOL,
         input={"service.name": "svc1.ns1.cluster"}, result=True,
         referenced=["service.name"]),
    Case(e='match(service.name, "*.ns1.cluster")', type_=V.BOOL,
         input={"service.name": "svc1.ns2.cluster"}, result=False),
    Case(e='match(service.name, "svc1.*")', type_=V.BOOL,
         input={"service.name": "svc1.ns1.cluster"}, result=True),
    Case(e='match(service.name, "svc1.*")', type_=V.BOOL,
         input={"service.name": "svc2.ns1.cluster"}, result=False),
    Case(e='match(service.name, "svc1.ns1.cluster")', type_=V.BOOL,
         input={"service.name": "svc1.ns1.cluster"}, result=True),
    Case(e='match(service.name, "svc1.ns1.cluster")', type_=V.BOOL,
         input={"service.name": "svc1.ns1.clusterX"}, result=False),
    Case(e='match(service.name, servicename)', input={"servicename": "*.a"},
         err="lookup failed: 'service.name'",
         referenced=["service.name"]),
    Case(e='match(service.name, servicename)',
         input={"service.name": "x"}, err="lookup failed: 'servicename'"),
    Case(e='match(service.name, "*.ns1.cluster") && service.user == "admin"',
         type_=V.BOOL,
         input={"service.name": "svc1.ns1.cluster", "service.user": "admin"},
         result=True),

    # ---- externs: matches (regex), startsWith, endsWith ----
    # NOTE: the RECEIVER of .matches() is the PATTERN, the argument is the
    # subject (reference corpus: `".*".matches("abc")` is true; extern
    # binding pushes the target first, externs.go:118 externMatches).
    Case(e='"st.*".matches(as)', type_=V.BOOL, input={"as": "str"},
         result=True),
    Case(e='"st.*".matches(as)', type_=V.BOOL, input={"as": "ts"},
         result=False),
    Case(e='"a.c".matches("abc")', type_=V.BOOL, result=True),
    Case(e='"^b".matches("abc")', type_=V.BOOL, result=False),
    Case(e='"ab.*d".matches("abc")', type_=V.BOOL, result=False),
    Case(e='"^/api/v[0-9]+/users/[^/]+$".matches(request.path)',
         type_=V.BOOL, input={"request.path": "/api/v1/users/alice"},
         result=True),
    Case(e='"^/api/v[0-9]+/users/[^/]+$".matches(request.path)',
         type_=V.BOOL, input={"request.path": "/api/v1/users/alice/pets"},
         result=False),
    Case(e='as.startsWith("pre")', type_=V.BOOL, input={"as": "prefix"},
         result=True),
    Case(e='as.startsWith("pre")', type_=V.BOOL, input={"as": "xprefix"},
         result=False),
    Case(e='as.endsWith("fix")', type_=V.BOOL, input={"as": "prefix"},
         result=True),
    Case(e='as.endsWith("fix")', type_=V.BOOL, input={"as": "fixed"},
         result=False),
    Case(e='"abc".startsWith("ab")', type_=V.BOOL, result=True),
    Case(e='"abc".endsWith("bc")', type_=V.BOOL, result=True),
    Case(e='as.matches("st.*")', input={},
         err="lookup failed: 'as'"),

    # ---- externs: ip / timestamp equality ----
    Case(e='aip == ip("10.1.12.3")', type_=V.BOOL,
         input={"aip": parse_ip("10.1.12.3")}, result=True),
    Case(e='aip == ip("10.1.12.4")', type_=V.BOOL,
         input={"aip": parse_ip("10.1.12.3")}, result=False),
    Case(e='aip == aip2', type_=V.BOOL,
         input={"aip": parse_ip("10.1.12.3"),
                "aip2": parse_ip("10.1.12.3")}, result=True),
    Case(e='at == at2', type_=V.BOOL, input={"at": _t1, "at2": _t1},
         result=True),
    Case(e='at == at2', type_=V.BOOL, input={"at": _t1, "at2": _t2},
         result=False),
    Case(e='at != at2', type_=V.BOOL, input={"at": _t1, "at2": _t2},
         result=True),
    Case(e='at == timestamp("2015-01-02T15:04:35Z")', type_=V.BOOL,
         input={"at": _t1}, result=True),

    # ---- fallback chains & typed defaults (tests.go OR breadth) ----
    Case(e="a | b | 2", type_=V.INT64, input={"a": 7, "b": 9}, result=7),
    Case(e="a | b | 2", type_=V.INT64, input={"b": 9}, result=9),
    Case(e="a | b | 2", type_=V.INT64, input={}, result=2),
    Case(e="(a | b) | 2", type_=V.INT64, input={"b": 5}, result=5),
    Case(e="a | b", type_=V.INT64, input={},
         err="lookup failed: 'b'"),
    Case(e="ab | true", type_=V.BOOL, input={}, result=True),
    Case(e="ab | false", type_=V.BOOL, input={"ab": True}, result=True),
    Case(e="ad | 0.5", type_=V.DOUBLE, input={}, result=0.5),
    Case(e='as | as2 | "z"', type_=V.STRING, input={"as2": "y"},
         result="y"),
    Case(e='ar["k"] | ar2["k"] | "d"', type_=V.STRING,
         input={"ar": {}, "ar2": {"k": "v2"}}, result="v2"),
    Case(e='ar[as] | "d"', type_=V.STRING, input={"ar": {"k": "x"}},
         result="d", name="dynkey-absent-key-falls-back"),
    Case(e='(ab | true) && (as | "x") == "x"', type_=V.BOOL,
         input={}, result=True),
    Case(e='a | "x"', compile_err="typeError"),

    # ---- error-masking boolean semantics (short-circuit parity) ----
    Case(e="false && a == 1", type_=V.BOOL, input={}, result=False,
         name="land-short-circuit-masks-absence"),
    Case(e="a == 1 && false", input={},
         err="lookup failed: 'a'",
         name="land-left-error-raises"),
    Case(e="true || a == 1", type_=V.BOOL, input={}, result=True,
         name="lor-short-circuit-masks-absence"),
    Case(e="a == 1 || true", input={},
         err="lookup failed: 'a'",
         name="lor-left-error-raises"),
    Case(e="ab && a == 1", input={"ab": True},
         err="lookup failed: 'a'"),
    Case(e="ab && a == 1", type_=V.BOOL, input={"ab": False},
         result=False),
    Case(e="(a == 1 || b == 2) && (as == \"x\" || ab)", type_=V.BOOL,
         input={"a": 9, "b": 2, "as": "y", "ab": True}, result=True),
    Case(e="(a == 1 || b == 2) && (as == \"x\" || ab)", type_=V.BOOL,
         input={"a": 1, "b": 9, "as": "y", "ab": False}, result=False),

    # ---- map edge semantics ----
    Case(e='ar[as]', input={"ar": {"k": "v"}, "as": "missing"},
         err="member lookup failed: 'missing'"),
    Case(e='ar[""]', type_=V.STRING, input={"ar": {"": "empty-key"}},
         result="empty-key", name="empty-string-map-key"),
    Case(e='ar["k"] == ar["k"]', type_=V.BOOL, input={"ar": {"k": "v"}},
         result=True, referenced=["ar", "ar[k]"]),
    Case(e='ar["a"] == ar["b"]', input={"ar": {"a": "x"}},
         err="member lookup failed: 'b'"),

    # ---- extern runtime errors & edge patterns ----
    Case(e='ip(as)', input={"as": "not-an-ip"},
         err="could not convert not-an-ip to IP_ADDRESS"),
    Case(e='timestamp(as)', input={"as": "not-a-time"},
         err="to TIMESTAMP. expected format: RFC3339"),
    Case(e='match(as, "*")', type_=V.BOOL, input={"as": "anything"},
         result=True, name="glob-star-matches-all"),
    Case(e='match(as, "")', type_=V.BOOL, input={"as": ""},
         result=True, name="glob-empty-exact"),
    Case(e='match(as, "")', type_=V.BOOL, input={"as": "x"},
         result=False),
    Case(e='match(as, "exact")', type_=V.BOOL, input={"as": "exact"},
         result=True),
    Case(e='match(as, "ex*") && match(as2, "*ct")', type_=V.BOOL,
         input={"as": "extra", "as2": "exact"}, result=True),
    Case(e='"[".matches(as)', input={"as": "x"},
         err="bad regex"),
    Case(e='"ab.*f".matches(as)', type_=V.BOOL, input={"as": "xabcdefy"},
         result=True, name="regex-unanchored-search"),
    Case(e='"^ab$".matches(as)', type_=V.BOOL, input={"as": "xaby"},
         result=False, name="regex-anchors-honored"),
    Case(e='as.startsWith("")', type_=V.BOOL, input={"as": "x"},
         result=True),
    Case(e='as.startsWith(as)', type_=V.BOOL, input={"as": "full"},
         result=True, name="prefix-equal-to-string"),
    Case(e='as.startsWith("longer-than-value")', type_=V.BOOL,
         input={"as": "lon"}, result=False),
    Case(e='as.endsWith("")', type_=V.BOOL, input={"as": "x"},
         result=True),
    Case(e='as.endsWith(as2)', type_=V.BOOL,
         input={"as": "a.svc.cluster", "as2": ".cluster"}, result=True),

    # ---- typed equality breadth ----
    Case(e='adur == "19ms"', type_=V.BOOL, input={"adur": _d19},
         result=True),
    Case(e='adur == "20ms"', type_=V.BOOL, input={"adur": _d19},
         result=False),
    Case(e="at == at2", type_=V.BOOL, input={"at": _t1, "at2": _t1},
         result=True),
    Case(e="at != at2", type_=V.BOOL, input={"at": _t1, "at2": _t2},
         result=True),
    Case(e='aip == ip("1.2.3.4")', type_=V.BOOL,
         input={"aip": parse_ip("1.2.3.4")}, result=True),
    Case(e='aip == ip("::ffff:1.2.3.4")', type_=V.BOOL,
         input={"aip": parse_ip("1.2.3.4")}, result=True,
         name="v4-equals-v4-in-v6"),
    Case(e='timestamp("2015-01-02T15:04:35Z") == at', type_=V.BOOL,
         input={"at": _t1}, result=True),

    # ---- parsing edges ----
    Case(e="((a)) == (2)", type_=V.BOOL, input={"a": 2}, result=True),
    Case(e='as == "quote\\"inside"', type_=V.BOOL,
         input={"as": 'quote"inside'}, result=True),
    Case(e="a==2&&b==3", type_=V.BOOL, input={"a": 2, "b": 3},
         result=True, name="no-whitespace"),

    # ---- realistic mesh predicates (the resolver's diet) ----
    Case(e='destination.service == "reviews.default.svc.cluster.local"',
         type_=V.BOOL,
         input={"destination.service": "reviews.default.svc.cluster.local"},
         result=True),
    Case(e='context.protocol == "tcp" && destination.service == "db.ns.svc"',
         type_=V.BOOL,
         input={"context.protocol": "http",
                "destination.service": "db.ns.svc"},
         result=False, referenced=["context.protocol"]),
    Case(e='source.labels["app"] == "reviews" && '
           'destination.namespace == "default"',
         type_=V.BOOL,
         input={"source.labels": {"app": "reviews"},
                "destination.namespace": "default"},
         result=True),
    # `|` binds tighter than `==` (Go precedence level 4 vs 3)
    Case(e='(source.namespace | "default") == "prod" || '
           'request.header["x-debug"] | "off" == "on"',
         type_=V.BOOL, input={}, result=False),
    Case(e='request.header["x-debug"] | "off" == "on"', type_=V.BOOL,
         input={"request.header": {"x-debug": "on"}}, result=True),
    Case(e='match(destination.service, "*.svc.cluster.local") && '
           '(request.user | "nobody") != "admin"',
         type_=V.BOOL,
         input={"destination.service": "a.svc.cluster.local"},
         result=True),
    Case(e='api.operation == "getPets" && '
           'request.header["authorization"].startsWith("Bearer ")',
         type_=V.BOOL,
         input={"api.operation": "getPets",
                "request.header": {"authorization": "Bearer tok"}},
         result=True),

    # ---- glob `match` edge semantics (externs.go:108-116: suffix-star
    # checked FIRST, so "*" alone is a prefix test against "") ----
    Case(e='match(as, "*")', type_=V.BOOL, input={"as": "anything"},
         result=True, name="glob-star-alone"),
    Case(e='match(as, "*")', type_=V.BOOL, input={"as": ""},
         result=True, name="glob-star-empty-value"),
    Case(e='match(as, "")', type_=V.BOOL, input={"as": ""},
         result=True, name="glob-empty-pattern-empty-value"),
    Case(e='match(as, "")', type_=V.BOOL, input={"as": "x"},
         result=False, name="glob-empty-pattern"),
    Case(e='match(as, "*x*")', type_=V.BOOL, input={"as": "axb"},
         result=False, name="glob-middle-star-is-literal-prefix"),
    Case(e='match(as, "*x*")', type_=V.BOOL, input={"as": "*xfoo"},
         result=True, name="glob-suffix-star-wins-over-prefix-star"),
    Case(e='match(as, "a*c")', type_=V.BOOL, input={"as": "abc"},
         result=False, name="glob-inner-star-not-wild"),
    Case(e='match(as, "a*c")', type_=V.BOOL, input={"as": "a*c"},
         result=True, name="glob-inner-star-literal-eq"),
    Case(e='match(as, "ns.*")', type_=V.BOOL, input={"as": "ns."},
         result=True, name="glob-prefix-boundary"),
    Case(e='match(as, "*.cluster")', type_=V.BOOL, input={"as": ".cluster"},
         result=True, name="glob-suffix-boundary"),
    Case(e='match(as, "*.cluster")', type_=V.BOOL, input={"as": "cluster"},
         result=False, name="glob-suffix-needs-dot"),
    Case(e="match(as, as2)", input={"as": "v"},
         err="lookup failed: 'as2'", referenced=["as", "as2"],
         name="glob-dynamic-pattern-absent"),

    # ---- regex `matches` edges (Go regexp.MatchString: unanchored) --
    Case(e='"".matches(as)', type_=V.BOOL, input={"as": "anything"},
         result=True, name="regex-empty-matches-all"),
    Case(e='"^$".matches(as)', type_=V.BOOL, input={"as": ""},
         result=True, name="regex-anchored-empty"),
    Case(e='"^$".matches(as)', type_=V.BOOL, input={"as": "x"},
         result=False),
    Case(e='"c$".matches(as)', type_=V.BOOL, input={"as": "abc"},
         result=True, name="regex-dollar-anchor"),
    Case(e='"^a".matches(as)', type_=V.BOOL, input={"as": "abc"},
         result=True),
    Case(e='"b".matches(as)', type_=V.BOOL, input={"as": "abc"},
         result=True, name="regex-unanchored-mid"),
    Case(e='"[0-9]+".matches(request.path)', type_=V.BOOL,
         input={"request.path": "/v2/pets"}, result=True),
    Case(e='"^/v[0-9]$".matches(request.path)', type_=V.BOOL,
         input={"request.path": "/v2/pets"}, result=False),
    Case(e='"(a|b)c".matches(as)', type_=V.BOOL, input={"as": "zbc"},
         result=True, name="regex-alternation"),
    Case(e='"a{2}".matches(as)', type_=V.BOOL, input={"as": "caab"},
         result=True, name="regex-repetition"),

    # ---- startsWith / endsWith edges ----
    Case(e='as.startsWith("")', type_=V.BOOL, input={"as": "x"},
         result=True, name="startswith-empty-prefix"),
    Case(e='as.endsWith("")', type_=V.BOOL, input={"as": "x"},
         result=True, name="endswith-empty-suffix"),
    Case(e='as.startsWith("xy")', type_=V.BOOL, input={"as": "x"},
         result=False, name="startswith-longer-than-value"),
    Case(e='as.endsWith("xy")', type_=V.BOOL, input={"as": "y"},
         result=False, name="endswith-longer-than-value"),
    Case(e='as.startsWith(as)', type_=V.BOOL, input={"as": "self"},
         result=True, name="startswith-self"),
    Case(e='as.startsWith("pre")', input={},
         err="lookup failed: 'as'", referenced=["as"]),

    # ---- fallback chains: maps and extern args ----
    Case(e='(ar | ar2)["foo"]', type_=V.STRING,
         input={"ar": {"foo": "bar"}}, result="bar",
         name="map-fallback-first"),
    Case(e='(ar | ar2)["foo"]', type_=V.STRING,
         input={"ar2": {"foo": "baz"}}, result="baz",
         name="map-fallback-second"),
    Case(e='(ar | ar2)["foo"]', input={},
         err="lookup failed", name="map-fallback-both-absent"),
    Case(e='(ar | ar2)["foo"]', input={"ar": {"x": "y"}},
         err="lookup failed", name="map-fallback-present-key-missing"),
    Case(e='ip(as | "5.6.7.8")', type_=V.IP_ADDRESS,
         input={}, result=parse_ip("5.6.7.8"),
         name="extern-arg-fallback-const"),
    Case(e='ip(as | "5.6.7.8")', type_=V.IP_ADDRESS,
         input={"as": "1.2.3.4"}, result=parse_ip("1.2.3.4"),
         name="extern-arg-fallback-attr"),
    Case(e='ip(as | as2)', input={}, err="lookup failed",
         name="extern-arg-fallback-both-absent"),
    Case(e='ip(ar["foo"])', type_=V.IP_ADDRESS,
         input={"ar": {"foo": "9.8.7.6"}}, result=parse_ip("9.8.7.6"),
         name="extern-arg-map-index"),
    Case(e='ip(as)', input={"as": "not-an-ip"},
         err="could not convert", name="ip-convert-error"),
    Case(e='timestamp(as)', input={"as": "242233"},
         err="could not convert", name="timestamp-convert-error"),

    # ---- empty-string and unicode values ----
    Case(e='as == ""', type_=V.BOOL, input={"as": ""}, result=True),
    Case(e='as == ""', type_=V.BOOL, input={"as": "x"}, result=False),
    Case(e='as == "héllo wörld"', type_=V.BOOL,
         input={"as": "héllo wörld"}, result=True, name="unicode-eq"),
    Case(e='as.startsWith("hé")', type_=V.BOOL,
         input={"as": "héllo"}, result=True, name="unicode-prefix"),
    Case(e='ar[""]', type_=V.STRING, input={"ar": {"": "empty-key-2"}},
         result="empty-key-2", name="map-empty-key"),
    Case(e='as | ""', type_=V.STRING, input={}, result="",
         name="fallback-to-empty-string"),

    # ---- referenced-attribute tracking through operators ----
    Case(e='(as | as2 | "z") == "y"', type_=V.BOOL, input={"as2": "y"},
         result=True, referenced=["as", "as2"],
         name="referenced-fallback-stops-at-hit"),
    Case(e="a == 1 && b == 2", type_=V.BOOL, input={"a": 1, "b": 2},
         result=True, referenced=["a", "b"]),
    Case(e="a == 9 && b == 2", type_=V.BOOL, input={"a": 1, "b": 2},
         result=False, referenced=["a"],
         name="referenced-shortcircuit-skips-right"),
    Case(e='match(service.name, "*.x") || match(as, "y.*")',
         type_=V.BOOL, input={"service.name": "q.z", "as": "y.q"},
         result=True, referenced=["service.name", "as"],
         name="referenced-both-extern-args"),

    # ---- type-mismatch breadth (checker parity) ----
    Case(e="a == ad", compile_err="typeError"),
    Case(e="as == a", compile_err="typeError"),
    Case(e="at == adur", compile_err="typeError"),
    Case(e="aip == as", compile_err="typeError"),
    Case(e="ab | 2", compile_err="typeError"),
    Case(e='adur | "x"', compile_err="typeError",
         name="duration-fallback-bad-literal"),
    Case(e="ar == ar2", type_=V.BOOL,
         input={"ar": {"a": "1"}, "ar2": {"a": "1"}}, result=True,
         name="map-equality-deep"),
    Case(e="ar != ar2", type_=V.BOOL,
         input={"ar": {"a": "1"}, "ar2": {"a": "2"}}, result=True,
         name="map-inequality-deep"),
    Case(e='ar | "x"', compile_err="typeError",
         name="map-fallback-to-string-rejected"),
    Case(e="a && ab", compile_err="typeError"),
    Case(e="as && ab", compile_err="typeError"),
    Case(e='match(a, "x")', compile_err="typeError"),
    Case(e="endsWith()",
         compile_err="invoking instance method without an instance"),
    Case(e="matches()",
         compile_err="invoking instance method without an instance"),
    Case(e='"a".startsWith("b", "c")', compile_err="arity mismatch"),

    # ---- three-valued AND/OR completeness (il semantics: an error on
    # one side survives only if the other side cannot decide) ----
    Case(e="ab || a == 1", type_=V.BOOL, input={"ab": True},
         result=True, name="lor-true-masks-right-absence"),
    Case(e="ab || a == 1", input={"ab": False},
         err="lookup failed: 'a'", name="lor-false-left-raises-right"),
    Case(e="ab && ab2", input={"ab": True},
         err="lookup failed: 'ab2'", name="land-true-left-needs-right"),
    Case(e="(a == 1 || true) && (b == 2 || true)", input={},
         err="lookup failed: 'a'",
         name="nested-lor-left-error-still-raises"),
    Case(e="false || false || true", type_=V.BOOL, result=True),
    Case(e="true && true && false", type_=V.BOOL, result=False),
    Case(e="(ab || ab2) && as == \"v\"", type_=V.BOOL,
         input={"ab": True, "as": "v"}, result=True,
         name="mixed-shortcircuit-chain"),
]


# ---------------------------------------------------------------------------
# Seeded analyzer corpora (istio_tpu/analysis)
# ---------------------------------------------------------------------------
#
# Snapshot/rule generation here takes an EXPLICIT seed end-to-end (the
# rng is created from it and every drawn constant derives from that
# rng), so the analyzer gate (scripts/analyze_gate.py), the property
# tests (tests/test_analysis.py) and any chaos corpus built on top
# replay identically across CI runs. `make_analyzer_clean_rules` is
# clean BY CONSTRUCTION (distinct services per rule ⇒ pairwise-disjoint
# predicates ⇒ no shadow/conflict findings possible); the fault
# injectors below each plant exactly one detectable defect class at an
# rng-chosen position.

ANALYZER_MANIFEST = {
    "destination.service": V.STRING,
    "source.namespace": V.STRING,
    "source.user": V.STRING,
    "request.path": V.STRING,
    "request.method": V.STRING,
    "request.host": V.STRING,
    "request.headers": V.STRING_MAP,
    "connection.mtls": V.BOOL,
}


@dataclasses.dataclass
class FaultCase:
    """One seeded defect for the analyzer gate: the faulted rule list
    (fault always LAST so admission can replay creation order), the
    finding code that must be reported, and which rules carry
    deny/allow actions when built into a snapshot."""
    kind: str                      # finding code expected from analysis
    description: str
    rules: list                    # list[compiler.ruleset.Rule]
    deny_idx: tuple = ()
    allow_idx: tuple = ()
    fault_rule: str = ""           # name of the planted rule


def make_analyzer_clean_rules(seed: int, n_rules: int = 24) -> list:
    """Seeded CLEAN rule world: one distinct service per rule (so no
    two predicates can overlap), varied secondary conjuncts and
    namespaces drawn from the seed's rng."""
    import numpy as np

    from istio_tpu.compiler.ruleset import Rule

    rng = np.random.default_rng(seed)
    rules = []
    for i in range(n_rules):
        ns = f"ns{int(rng.integers(9))}"
        svc = f"svc{i}.{ns}.svc.cluster.local"
        parts = [f'destination.service == "{svc}"']
        k = int(rng.integers(5))
        if k == 0:
            parts.append(f'source.namespace != '
                         f'"locked{int(rng.integers(7))}"')
        elif k == 1:
            parts.append(f'request.method == '
                         f'"{("GET", "POST")[int(rng.integers(2))]}"')
        elif k == 2:
            parts.append(f'request.path.startsWith('
                         f'"/api/v{int(rng.integers(4))}/")')
        elif k == 3:
            parts.append(f'"^/items/[0-9]+/r{int(rng.integers(9))}$"'
                         f'.matches(request.path)')
        # k == 4: service-only match
        rules.append(Rule(name=f"clean{i}", match=" && ".join(parts),
                          namespace=ns))
    return rules


def make_analyzer_faults(seed: int, n_rules: int = 24) -> list:
    """The seeded-fault corpus: one FaultCase per defect class the
    acceptance criteria pin — shadowed rule, ALLOW/DENY conflict, type
    error, NFA state-budget blow-up. (Plane divergence is exercised by
    `make_plane_divergence_pairs` — it is a pair-of-planes fault, not
    a single rule list.)"""
    import numpy as np

    from istio_tpu.compiler.ruleset import Rule

    rng = np.random.default_rng(seed)
    out = []

    def world():
        # independent clean world per case, same seed family
        return make_analyzer_clean_rules(int(rng.integers(1 << 30)),
                                         n_rules)

    # 1. shadowed rule: duplicate an rng-chosen rule with an EXTRA
    #    conjunct — strictly narrower, fully covered
    base = world()
    victim = base[int(rng.integers(len(base)))]
    shadowed = Rule(name="fault-shadowed",
                    match=victim.match + ' && request.method == "GET"'
                    if 'request.method' not in victim.match
                    else victim.match + ' && connection.mtls',
                    namespace=victim.namespace)
    out.append(FaultCase(
        kind="shadowed-rule",
        description=f"narrower copy of {victim.name} (same actions)",
        rules=base + [shadowed],
        deny_idx=tuple(range(len(base) + 1)),
        fault_rule=shadowed.name))

    # 2. ALLOW/DENY conflict: a deny rule and an allow rule whose
    #    byte-level path constraints overlap (regex ∩ prefix ≠ ∅ —
    #    decided by product-DFA construction, witnessed)
    base = world()
    svc = f"svcX.ns{int(rng.integers(9))}.svc.cluster.local"
    v = int(rng.integers(4))
    deny = Rule(name="fault-deny",
                match=f'destination.service == "{svc}" && '
                      f'"^/api/v[0-9]+/".matches(request.path)',
                namespace="")
    allow = Rule(name="fault-allow",
                 match=f'destination.service == "{svc}" && '
                       f'request.path.startsWith("/api/v{v}/")',
                 namespace="")
    out.append(FaultCase(
        kind="allow-deny-conflict",
        description="deny regex overlaps allow prefix on one service",
        rules=base + [deny, allow],
        deny_idx=(len(base),), allow_idx=(len(base) + 1,),
        fault_rule=allow.name))

    # 3. type error: undefined attribute drawn from the rng
    base = world()
    attr = f"nope{int(rng.integers(100))}.attr"
    bad = Rule(name="fault-typed", match=f'{attr} == "x"')
    out.append(FaultCase(
        kind="type-error",
        description=f"undefined attribute {attr}",
        rules=base + [bad], fault_rule=bad.name))

    # 4. state-budget blow-up: (a|b)*a(a|b)^m needs 2^m DFA states —
    #    m ≥ 12 explodes past the 2048-state device budget
    base = world()
    m = 12 + int(rng.integers(4))
    boom = Rule(name="fault-boom",
                match=f'"(a|b)*a(a|b){{{m}}}$".matches(request.path)')
    out.append(FaultCase(
        kind="state-budget",
        description=f"regex with 2^{m} DFA states",
        rules=base + [boom], fault_rule=boom.name))

    return out


def make_plane_divergence_pairs(seed: int, n_pairs: int = 6
                                ) -> tuple[list, int]:
    """(pairs for analysis.check_plane_pairs, index of the diverged
    pair): n_pairs route-style predicates where pilot and mixer sides
    agree everywhere except one rng-chosen pair whose mixer side was
    compiled from a DIFFERENT constant (the stale-recompile defect)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    diverge_at = int(rng.integers(n_pairs))
    pairs = []
    for i in range(n_pairs):
        svc = f"svc{i}.default.svc.cluster.local"
        v = int(rng.integers(7))
        pilot = (f'destination.service == "{svc}" && '
                 f'request.path.startsWith("/api/v{v}/")')
        mixer = pilot
        if i == diverge_at:
            mixer = (f'destination.service == "{svc}" && '
                     f'request.path.startsWith("/api/v{(v + 1) % 7}/")')
        pairs.append((f"route{i}", pilot, mixer))
    return pairs, diverge_at


# ---------------------------------------------------------------------------
# Seeded canary snapshot pairs (istio_tpu/canary)
# ---------------------------------------------------------------------------
#
# Each pair is one seeded rule world expressed THREE ways as
# MemStore-ready config docs: the base (the world live traffic was
# recorded against), a SEMANTICALLY IDENTICAL rewrite (conjuncts
# reordered, store insertion order shuffled — the canary must publish
# it with zero reported divergences), and a DELIBERATELY DIVERGENT
# rewrite planting exactly one decision-flipping defect class at an
# rng-chosen victim. Consumed by scripts/canary_smoke.py (the tier-1
# gate) and tests/test_canary.py.

@dataclasses.dataclass
class CanaryPair:
    """One seeded (identical, divergent) snapshot pair."""
    kind: str                  # planted defect class (see below)
    expected: str              # divergence kind the differ must report
    base_docs: list            # [(key, spec)] — MemStore.set pairs
    identical_docs: list
    divergent_docs: list
    divergent_rule: str        # qualified rule name ("ns/name") the
    #                            report must attribute divergences to
    services: list             # victim services (traffic targets)


def _canary_world(rng, n_rules: int) -> tuple[list, list, dict]:
    """(docs, rule specs, meta) for one seeded base world: denier /
    whitelist handlers + per-service rules, every 3rd carrying the
    deny action, one rng-chosen rule the quota rule."""
    docs = [
        (("handler", "istio-system", "denyall"),
         {"adapter": "denier",
          "params": {"status_code": 7,
                     "status_message": "denied by canary world",
                     "valid_duration_s": 2.5,
                     "valid_use_count": 500}}),
        (("handler", "istio-system", "mq"),
         {"adapter": "memquota",
          "params": {"quotas": [{"name": "rq.istio-system",
                                 "max_amount": 1 << 20,
                                 "valid_duration_s": 600.0}]}}),
        (("instance", "istio-system", "rq"),
         {"template": "quota", "params": {"dimensions": {}}}),
        (("instance", "istio-system", "nothing"),
         {"template": "checknothing", "params": {}}),
    ]
    # the quota rule must not double as a deny rule: a tightened match
    # on a deny+quota rule classifies as status_flip (checked first),
    # and the quota-drop pair pins the pure quota-delta class
    quota_at = int(rng.integers(n_rules))
    while quota_at % 3 == 0:
        quota_at = int(rng.integers(n_rules))
    rules = []
    for i in range(n_rules):
        ns = f"ns{i % 5}"
        svc = f"svc{i}.{ns}.svc.cluster.local"
        conjuncts = [f'destination.service == "{svc}"',
                     f'source.namespace != "locked{int(rng.integers(7))}"']
        actions = []
        if i % 3 == 0:
            actions.append({"handler": "denyall.istio-system",
                            "instances": ["nothing.istio-system"]})
        if i == quota_at:
            actions.append({"handler": "mq.istio-system",
                            "instances": ["rq.istio-system"]})
        rules.append({"name": f"canary{i}", "namespace": ns,
                      "svc": svc, "conjuncts": conjuncts,
                      "actions": actions, "idx": i})
    meta = {"quota_at": quota_at,
            "deny_idx": [i for i in range(n_rules) if i % 3 == 0]}
    return docs, rules, meta


def _canary_rule_doc(r, conjuncts=None) -> tuple:
    return (("rule", r["namespace"], r["name"]),
            {"match": " && ".join(conjuncts or r["conjuncts"]),
             "actions": [dict(a) for a in r["actions"]]})


def make_canary_snapshot_pairs(seed: int, n_rules: int = 12
                               ) -> list[CanaryPair]:
    """Three seeded pairs, one per divergence class the differ
    classifies:

      tightened-match — a firing deny rule's match gains an extra
          conjunct excluding the recorded traffic: DENY→OK status
          flips attributed to that rule;
      ttl-change — the shared denier handler's valid_duration_s
          param changes: same statuses, precondition (TTL) divergence
          on every denied row;
      quota-drop — the quota rule's match is tightened so it stops
          activating for recorded traffic: quota-set divergence.

    Identical variants reorder each rule's conjuncts AND reverse the
    store insertion order (rule indices renumber; decisions must not).
    """
    import numpy as np

    out: list[CanaryPair] = []
    rng = np.random.default_rng(seed)

    def build():
        docs, rules, meta = _canary_world(
            np.random.default_rng(int(rng.integers(1 << 30))), n_rules)
        base = list(docs) + [_canary_rule_doc(r) for r in rules]
        ident_rules = [_canary_rule_doc(r, list(reversed(r["conjuncts"])))
                       for r in rules]
        identical = list(docs) + list(reversed(ident_rules))
        return docs, rules, meta, base, identical

    # 1. tightened-match → status_flip on an rng-chosen deny rule
    docs, rules, meta, base, identical = build()
    victim = rules[int(rng.choice(meta["deny_idx"]))]
    divergent = list(docs) + [
        _canary_rule_doc(r, r["conjuncts"] +
                         ['request.method == "DELETE"']
                         if r is victim else None)
        for r in rules]
    out.append(CanaryPair(
        kind="tightened-match", expected="status_flip",
        base_docs=base, identical_docs=identical,
        divergent_docs=divergent,
        divergent_rule=f"{victim['namespace']}/{victim['name']}",
        services=[r["svc"] for r in rules]))

    # 2. ttl-change → precondition divergence on every denied row
    docs, rules, meta, base, identical = build()
    victim = rules[meta["deny_idx"][0]]
    divergent = []
    for key, spec in base:
        if key == ("handler", "istio-system", "denyall"):
            spec = {"adapter": "denier",
                    "params": dict(spec["params"],
                                   valid_duration_s=1.25)}
        divergent.append((key, spec))
    out.append(CanaryPair(
        kind="ttl-change", expected="precondition",
        base_docs=base, identical_docs=identical,
        divergent_docs=divergent,
        divergent_rule=f"{victim['namespace']}/{victim['name']}",
        services=[r["svc"] for r in rules]))

    # 3. quota-drop → quota-set divergence on the quota rule
    docs, rules, meta, base, identical = build()
    victim = rules[meta["quota_at"]]
    divergent = list(docs) + [
        _canary_rule_doc(r, r["conjuncts"] +
                         ['request.method == "DELETE"']
                         if r is victim else None)
        for r in rules]
    out.append(CanaryPair(
        kind="quota-drop", expected="quota",
        base_docs=base, identical_docs=identical,
        divergent_docs=divergent,
        divergent_rule=f"{victim['namespace']}/{victim['name']}",
        services=[r["svc"] for r in rules]))
    return out


def make_canary_traffic(pair: CanaryPair, seed: int,
                        extra_noise: int = 8) -> list[dict]:
    """Seeded request dicts exercising every rule of a canary world
    (GET traffic per victim service — the divergent variants all key
    on method/quota activity for that traffic) plus rng noise rows
    addressed at unknown services."""
    import numpy as np

    rng = np.random.default_rng(seed)
    dicts = []
    for svc in pair.services:
        dicts.append({
            "destination.service": svc,
            "source.namespace": f"src{int(rng.integers(9))}",
            "request.method": "GET",
            "request.path": f"/api/v{int(rng.integers(3))}/items",
        })
    for _ in range(extra_noise):
        dicts.append({
            "destination.service":
                f"noise{int(rng.integers(99))}.nsX.svc.cluster.local",
            "source.namespace": "srcN",
            "request.method": "GET",
            "request.path": "/healthz",
        })
    return dicts
