"""Synthetic mesh workloads for entry(), dryrun, benches and tests.

Shapes follow BASELINE.json's configs: Bookinfo-style denier +
listchecker rules, RBAC-ish authz predicates over source/destination
attributes, and header/URI match clauses (exact, prefix, glob, regex) —
the same predicate mix Pilot's VirtualService match tables compile to.
"""
from __future__ import annotations

import numpy as np

from istio_tpu.attribute.bag import Bag, bag_from_mapping
from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.ruleset import Rule
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.models.policy_engine import (DenySpec, ListEntrySpec,
                                            PolicyEngine, QuotaSpec)

V = ValueType

# the canonical vocabulary subset the synthetic workloads exercise —
# typed once in attribute/global_dict.py, never duplicated
from istio_tpu.attribute.global_dict import GLOBAL_MANIFEST as _G

MESH_MANIFEST: dict[str, ValueType] = {k: _G[k] for k in (
    "source.name", "source.namespace", "source.ip", "source.labels",
    "source.user", "source.service",
    "destination.name", "destination.namespace", "destination.service",
    "destination.labels",
    "request.headers", "request.host", "request.method", "request.path",
    "request.scheme", "request.size", "request.time", "request.useragent",
    "request.api_key",
    "response.code", "response.size", "response.duration",
    "connection.mtls",
    "context.protocol", "context.reporter.kind",
    "api.service", "api.operation", "api.version",
)}

MESH_FINDER = AttributeDescriptorFinder(MESH_MANIFEST)


def make_rules(n_rules: int, n_services: int | None = None,
               with_regex: bool = True,
               seed: int | None = None) -> list[Rule]:
    """Bookinfo/authz-flavored rule mix: mostly EQ/NEQ conjunctions
    (the vectorized tier), a sprinkling of header glob/regex and path
    prefix predicates (the byte-DFA tier).

    `seed` (explicit, end-to-end reproducible): varies the per-branch
    CONSTANTS (locked namespaces, methods, session ids, path/regex
    versions) from a named rng so analyzer and chaos corpora differ
    across seeds but replay identically for one seed. The svc/ns/
    branch STRUCTURE stays i-based under any seed — consumers key on
    it (every-3rd-rule deny wiring, chaos_smoke's deny bags). None =
    the legacy fixed constants, byte-identical to pre-seed output."""
    n_services = n_services or max(n_rules // 2, 1)
    rng = np.random.default_rng(seed) if seed is not None else None

    def draw(legacy, hi):
        return legacy if rng is None else int(rng.integers(hi))

    rules = []
    for i in range(n_rules):
        svc = f"svc{i % n_services}.ns{i % 23}.svc.cluster.local"
        parts = [f'destination.service == "{svc}"']
        k = i % 10
        if k < 4:
            parts.append(f'source.namespace != "locked{draw(i % 5, 5)}"')
        elif k == 4:
            parts.append(f'request.method == '
                         f'"{"GET" if draw(i % 2, 2) else "POST"}"')
        elif k == 5:
            parts.append(f'request.headers["cookie"] == '
                         f'"session={draw(i % 97, 97)}"')
        elif k == 6:
            parts.append('connection.mtls')
        elif k == 7 and with_regex:
            parts.append(f'request.path.startsWith('
                         f'"/api/v{draw(i % 3, 3)}/")')
        elif k == 8 and with_regex:
            parts.append(f'match(request.host, "*.ns{i % 23}.cluster.local")')
        elif k == 9 and with_regex:
            parts.append(
                f'"/(products|reviews)/[0-9]+/v{draw(i % 4, 4)}"'
                '.matches(request.path)')
        rules.append(Rule(name=f"rule{i}", match=" && ".join(parts),
                          namespace=f"ns{i % 23}"))
    return rules


def make_engine(n_rules: int = 1024,
                with_quota: bool = True, jit: bool = True) -> PolicyEngine:
    rules = make_rules(n_rules)
    deny = [DenySpec(rule=i) for i in range(0, n_rules, 3)]
    lists = [ListEntrySpec(rule=i, value_attr="source.namespace",
                           entries=[f"ns{j}" for j in range(0, 23, 2)])
             for i in range(1, n_rules, 97)]
    quotas = ([QuotaSpec(rule=i, key_attr="source.user", max_amount=1 << 20)
               for i in range(2, n_rules, 301)] if with_quota else [])
    return PolicyEngine(rules, MESH_FINDER, deny=deny, lists=lists,
                        quotas=quotas, jit=jit)


def _overlay_list_provider() -> list[str]:
    """Provider seam for the overlay workload's refreshed list (the
    reference's URL-fetch role; module-level named function so stores
    built in child processes resolve it by reference)."""
    return [f"ns{j}" for j in range(0, 23, 2)]


def make_store(n_rules: int, n_services: int | None = None,
               with_regex: bool = True,
               host_overlay_every: int | None = None,
               seed: int | None = None):
    """A MemStore carrying the make_rules() workload as REAL config
    kinds (handlers/instances/rules), for serving-path benches and the
    perf rig: every 3rd rule deny + every 97th a whitelist, mirroring
    make_engine()'s fused-action mix. Rules live in their own
    namespaces (namespace targeting identical to make_rules).

    `host_overlay_every`: every Nth rule additionally carries work the
    device GENUINELY cannot absorb — the host-overlay-heavy shape
    (VERDICT r2 weak #4) whose per-request python cost the overlay
    bench measures. r4's device lowering learned REGEX-entry lists and
    silently emptied the old overlay workload (`overlay_rules: 0`);
    the three shapes now cycle through the reference's genuinely
    host-bound list semantics (mixer/adapter/list/list.go:115-247):
    case-insensitive membership, provider-refreshed entries (the TTL
    refresh loop — entries change between requests, so no compiled
    bank can be current), and a dynamic `match(x, attr)` predicate
    whose pattern is an attribute (no constant DFA exists).

    `seed` forwards to make_rules (explicit, reproducible constant
    variation; None = legacy fixed constants). Action wiring stays
    i-based under any seed."""
    from istio_tpu.runtime.store import MemStore

    s = MemStore()
    s.set(("handler", "istio-system", "denyall"), {
        "adapter": "denier", "params": {"status_code": 7}})
    s.set(("handler", "istio-system", "nswhitelist"), {
        "adapter": "list",
        "params": {"overrides": [f"ns{j}" for j in range(0, 23, 2)],
                   "blacklist": False}})
    # served quota traffic (grpcServer.go:188-230 loop → device pools,
    # runtime/device_quota.py): per-user rate limit, requested by the
    # perf rig on a fraction of payloads
    s.set(("handler", "istio-system", "mq"), {
        "adapter": "memquota",
        "params": {"quotas": [{"name": "rq.istio-system",
                               "max_amount": 1 << 30}]}})
    s.set(("instance", "istio-system", "rq"), {
        "template": "quota",
        "params": {"dimensions": {"user": 'source.user | "anon"'}}})
    s.set(("rule", "istio-system", "quota-rule"), {
        "match": "",
        "actions": [{"handler": "mq", "instances": ["rq"]}]})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("instance", "istio-system", "srcns"), {
        "template": "listentry", "params": {"value": "source.namespace"}})
    # REPORT-path traffic (grpcServer.go:262 → dispatcher.Report →
    # metric adapter): a request-count metric into prometheus — the
    # served report bench drives this through the real gRPC surface
    s.set(("handler", "istio-system", "prom"), {
        "adapter": "prometheus",
        "params": {"metrics": [{
            "name": "reqcount.istio-system", "kind": "COUNTER",
            "label_names": ["destination"]}]}})
    s.set(("instance", "istio-system", "reqcount"), {
        "template": "metric",
        "params": {"value": "1",
                   "dimensions": {"destination":
                                  'destination.service | "unknown"'}}})
    s.set(("rule", "istio-system", "report-all"), {
        "match": "",
        "actions": [{"handler": "prom", "instances": ["reqcount"]}]})
    if host_overlay_every:
        # shape 1: CASE_INSENSITIVE_STRINGS membership — list.go's
        # ToLower path; the device's one-hot banks are case-exact, so
        # the fused plan must overlay these rules per request
        # (runtime/fused._split_list_instances keeps them host-side)
        s.set(("handler", "istio-system", "cilist"), {
            "adapter": "list",
            "params": {"overrides": [f"NS{j}" for j in range(0, 23, 2)],
                       "entry_type": "CASE_INSENSITIVE_STRINGS",
                       "blacklist": False}})
        # shape 2: provider-refreshed entries (the reference's URL-
        # fetch + TTL refresh loop, list.go:115-247) — entries can
        # change between requests, so membership stays a host call
        s.set(("handler", "istio-system", "provlist"), {
            "adapter": "list",
            "params": {"overrides": [],
                       "provider": _overlay_list_provider,
                       "refresh_interval_s": 3600.0,
                       "blacklist": False}})
        s.set(("instance", "istio-system", "nsinst"), {
            "template": "listentry",
            "params": {"value": "source.namespace"}})
        # shape 3: REGEX entries OUTSIDE the DFA-compilable subset
        # (a backreference — the dynamic per-entry match semantics
        # list.go applies that no compiled bank can express); the
        # plain dynamic match(x, attr) predicate form now lowers on
        # device (tensor_expr._compile_dyn_byte_pred), so this is the
        # remaining genuinely-dynamic pattern shape
        s.set(("handler", "istio-system", "dynpat"), {
            "adapter": "list",
            "params": {"overrides": [r"^/api/(v[0-9])/\1/"],
                       "entry_type": "REGEX", "blacklist": True}})
        s.set(("instance", "istio-system", "pathinst"), {
            "template": "listentry",
            "params": {"value": "request.path"}})
    for i, rule in enumerate(make_rules(n_rules, n_services, with_regex,
                                        seed=seed)):
        actions = []
        if i % 3 == 0:
            actions.append({"handler": "denyall.istio-system",
                            "instances": ["nothing.istio-system"]})
        if i % 97 == 1:
            actions.append({"handler": "nswhitelist.istio-system",
                            "instances": ["srcns.istio-system"]})
        if host_overlay_every and i % host_overlay_every == 2:
            k = (i // host_overlay_every) % 3
            if k == 0:
                actions.append({"handler": "cilist.istio-system",
                                "instances": ["nsinst.istio-system"]})
            elif k == 1:
                actions.append({"handler": "provlist.istio-system",
                                "instances": ["nsinst.istio-system"]})
            else:
                actions.append({"handler": "dynpat.istio-system",
                                "instances": ["pathinst.istio-system"]})
        if not actions:   # every rule carries at least a no-op check
            actions.append({"handler": "denyall.istio-system",
                            "instances": []})
        s.set(("rule", rule.namespace, rule.name),
              {"match": rule.match, "actions": actions})
    return s


OPA_POLICY = """package mixerauthz

    policy = [
      {
        "rule": {
          "verbs": [
            "GET"
          ],
          "users": [
            "reader",
            "admin"
          ]
        }
      },
      {
        "rule": {
          "verbs": [
            "GET",
            "POST",
            "DELETE"
          ],
          "users": [
            "admin"
          ]
        }
      }
    ]

    default allow = false

    allow = true {
      rule = policy[_].rule
      input.subject.user = rule.users[_]
      input.action.method = rule.verbs[_]
    }"""
"""Rego module for the OPA overlay scenario (the reference adapter's
bucket-admins policy shape, opa_test.go:180): readers may GET, admins
may do anything, everyone else is denied — evaluated per request by
the native Rego-subset engine (adapters/rego.py) on the adapter
executor's opa lane."""


def make_opa_store(n_rules: int, n_services: int | None = None,
                   opa_every: int = 7, fail_close: bool = True,
                   seed: int | None = None):
    """make_store's world with every `opa_every`-th rule additionally
    carrying an OPA authorization action: the 776-line Rego engine
    runs per matching request as a genuine external policy check —
    the authorization template has no device lowering for the opa
    adapter, so these are first-class host-overlay actions on the
    executor's opa lane. Requests crafted by make_opa_requests carry
    subject users the policy allows AND denies, so oracle-parity
    gates see real PERMISSION_DENIED flips."""
    s = make_store(n_rules, n_services, seed=seed)
    s.set(("handler", "istio-system", "opah"), {
        "adapter": "opa",
        "params": {"policies": [OPA_POLICY],
                   "check_method": "data.mixerauthz.allow",
                   "fail_close": fail_close}})
    s.set(("instance", "istio-system", "authzi"), {
        "template": "authorization",
        "params": {
            "subject": {"user": 'source.user | ""'},
            "action": {"service": 'destination.service | ""',
                       "method": 'request.method | ""',
                       "path": 'request.path | ""'}}})
    for i in range(0, n_rules, opa_every):
        key = ("rule", f"ns{i % 23}", f"rule{i}")
        spec = dict(s.get(key))
        spec["actions"] = list(spec["actions"]) + [
            {"handler": "opah.istio-system",
             "instances": ["authzi.istio-system"]}]
        s.set(key, spec)
    return s


def make_opa_requests(batch: int, n_rules: int,
                      n_services: int | None = None,
                      opa_every: int = 7, seed: int = 5) -> list[dict]:
    """Traffic targeting make_opa_store's OPA-carrying rules: each
    request addresses rule i (i % opa_every == 0) by its exact
    service, with the user cycling allowed (admin/reader-GET) and
    denied (reader-POST / intern) shapes — so every request fires the
    Rego check and the corpus carries both verdicts."""
    n_services = n_services or max(n_rules // 2, 1)
    rng = np.random.default_rng(seed)
    out = []
    opa_rules = list(range(0, n_rules, opa_every))
    for j in range(batch):
        i = opa_rules[int(rng.integers(len(opa_rules)))]
        kind = j % 4
        user, method = (("admin", "POST"), ("reader", "GET"),
                        ("reader", "DELETE"), ("intern", "GET"))[kind]
        out.append({
            "destination.service":
                f"svc{i % n_services}.ns{i % 23}.svc.cluster.local",
            "source.user": user,
            "source.namespace": f"ns{2 * int(rng.integers(12)) % 23}",
            "request.method": method,
            "request.path": f"/api/v{i % 3}/items",
        })
    return out


def make_shared_quota_store(backend=None, max_amount: int = 64,
                            duration_s: float = 0.0,
                            min_dedup_s: float = 5.0):
    """One global memquota rule over a SHARED QuotaBackend (adapters/
    memquota.QuotaBackend) — the cross-replica shared-quota dedup
    scenario: N stores built over the same `backend` give N replicas
    whose handlers allocate against one set of cells and one dedup
    cache, through the adapter executor's mq lane. A dedup_id retried
    on ANY replica replays the original grant; the window max is
    enforced globally."""
    from istio_tpu.runtime.store import MemStore

    s = MemStore()
    params: dict = {"quotas": [{"name": "rq.istio-system",
                                "max_amount": max_amount,
                                "valid_duration_s": duration_s}],
                    "min_deduplication_duration_s": min_dedup_s}
    if backend is not None:
        params["backend"] = backend
    s.set(("handler", "istio-system", "mq"), {
        "adapter": "memquota", "params": params})
    s.set(("instance", "istio-system", "rq"), {
        "template": "quota",
        "params": {"dimensions": {"user": 'source.user | "anon"'}}})
    s.set(("rule", "istio-system", "quota-rule"), {
        "match": "",
        "actions": [{"handler": "mq", "instances": ["rq"]}]})
    return s


def _fleet_ns_assignment(n_rules: int, n_namespaces: int,
                         seed: int) -> np.ndarray:
    """Rule → namespace index for the fleet workload, Zipf-skewed so
    namespace SIZES are realistic (a few big app namespaces, a long
    tail of small ones): rule i lands in namespace
    `(zipf(a=1.1) - 1) mod n_namespaces` (a=1.1 ⇒ the head namespace
    holds ~10% of all rules at 512 namespaces — skewed enough that a
    naive round-robin split misbalances, small enough that an LPT
    packing CAN balance). Shared by make_fleet_rules and
    make_fleet_traffic so traffic can craft requests that actually
    match rules — same (n_rules, n_namespaces, seed) ⇒ the same
    assignment, bit-for-bit."""
    rng = np.random.default_rng(seed)
    return ((rng.zipf(1.1, n_rules) - 1) % n_namespaces).astype(
        np.int64)


def make_fleet_rules(n_rules: int, n_namespaces: int,
                     seed: int = 0) -> list[Rule]:
    """Fleet-scale rule set for the sharded serving plane
    (istio_tpu/sharding): n_rules EQ-dominated predicates partitioned
    over n_namespaces namespaces (sizes Zipf-skewed via
    _fleet_ns_assignment — the shard planner has to balance REAL
    namespace skew, not uniform confetti). Rule i guards its own
    unique service `svc{i}.ns{k}.svc.cluster.local`, so a request is
    attributable to exactly the rules crafted for it, plus one extra
    conjunct cycling through the vectorized-tier shapes. Every
    predicate stays inside the fused gather-compare envelope by
    design: fleet scale is the point, and a 100k-rule snapshot must
    compile in host seconds."""
    ns_of = _fleet_ns_assignment(n_rules, n_namespaces, seed)
    rules = []
    for i in range(n_rules):
        ns = f"ns{int(ns_of[i])}"
        svc = f"svc{i}.{ns}.svc.cluster.local"
        parts = [f'destination.service == "{svc}"']
        k = i % 4
        if k < 2:
            parts.append(f'source.namespace != "locked{i % 5}"')
        elif k == 2:
            parts.append('request.method == "GET"')
        else:
            parts.append('connection.mtls')
        rules.append(Rule(name=f"fleet{i}", match=" && ".join(parts),
                          namespace=ns))
    return rules


def make_fleet_store(n_rules: int, n_namespaces: int, seed: int = 0,
                     with_quota: bool = False):
    """MemStore carrying make_fleet_rules as real config kinds: every
    3rd rule denies (status 7), every 97th runs a source-namespace
    whitelist, the rest a bare denier action with no instances (the
    no-op check) — make_store's action mix at fleet scale, WITHOUT the
    mesh-wide report rule (a 100k-rule parent snapshot must not lower
    a report plane the sharded path never serves). `with_quota` adds
    one GLOBAL per-user memquota rule — the shape the sharding tests
    pin: replicated into every bank, allocated once per request from
    the one controller-owned pool."""
    from istio_tpu.runtime.store import MemStore

    s = MemStore()
    s.set(("handler", "istio-system", "denyall"), {
        "adapter": "denier", "params": {"status_code": 7}})
    s.set(("handler", "istio-system", "nswhitelist"), {
        "adapter": "list",
        "params": {"overrides": [f"team{j}" for j in range(0, 40, 2)],
                   "blacklist": False}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("instance", "istio-system", "srcns"), {
        "template": "listentry", "params": {"value": "source.namespace"}})
    if with_quota:
        s.set(("handler", "istio-system", "mq"), {
            "adapter": "memquota",
            "params": {"quotas": [{"name": "rq.istio-system",
                                   "max_amount": 1 << 30}]}})
        s.set(("instance", "istio-system", "rq"), {
            "template": "quota",
            "params": {"dimensions": {"user": 'source.user | "anon"'}}})
        s.set(("rule", "istio-system", "quota-rule"), {
            "match": "",
            "actions": [{"handler": "mq", "instances": ["rq"]}]})
    for i, rule in enumerate(make_fleet_rules(n_rules, n_namespaces,
                                              seed)):
        if i % 3 == 0:
            actions = [{"handler": "denyall.istio-system",
                        "instances": ["nothing.istio-system"]}]
        elif i % 97 == 1:
            actions = [{"handler": "nswhitelist.istio-system",
                        "instances": ["srcns.istio-system"]}]
        else:
            actions = [{"handler": "denyall.istio-system",
                        "instances": []}]
        s.set(("rule", rule.namespace, rule.name),
              {"match": rule.match, "actions": actions})
    return s


FLEET_ZIPF_A = 1.2
"""Zipf skew of fleet sidecar traffic (make_fleet_traffic): namespace
index drawn as `(zipf(a=1.2) - 1) mod n_namespaces`, i.e. P(ns k) ∝
the mass the Zipf tail folds onto k — ns0 is the hot head (P(rank 1)
= 1/ζ(1.2) ≈ 18% of draws, plus whatever tail mass the mod folds
back), with a long informative tail. Rule namespaces are sized with
a=1.1 (_fleet_ns_assignment); traffic skew deliberately does NOT
match rule skew — hot traffic landing on namespaces of every size is
what makes shard occupancy a real measurement."""


def make_fleet_traffic(n_requests: int, n_rules: int,
                       n_namespaces: int, seed: int = 0,
                       zipf_a: float = FLEET_ZIPF_A,
                       sidecar_ids: int = 20_000) -> list[dict]:
    """Zipf-skewed sidecar Check() traffic against a make_fleet_rules
    world: each request carries a sidecar identity drawn uniformly
    from a `sidecar_ids`-wide id space (`source.user` = sidecar{i};
    consumers report the OBSERVED distinct count, not the space), and
    picks a namespace by Zipf rank (see FLEET_ZIPF_A), then a uniform
    rule within it, addressing that rule's own service — so
    predicates actually fire and deny/whitelist rules exercise their
    device lowerings. ~10% of rows carry a `locked{...}` source
    namespace (the k<2 rules' not-matched branch) and ~10% a
    namespace no rule knows (global rules only). Fully reproducible
    for one (n_rules, n_namespaces, seed, zipf_a, sidecar_ids)."""
    ns_of = _fleet_ns_assignment(n_rules, n_namespaces, seed)
    by_ns: dict[int, list[int]] = {}
    for i, k in enumerate(ns_of):
        by_ns.setdefault(int(k), []).append(i)
    rng = np.random.default_rng(seed + 1)
    out = []
    for j in range(n_requests):
        ns_rank = int((rng.zipf(zipf_a) - 1) % n_namespaces)
        roll = rng.random()
        if roll < 0.10 or ns_rank not in by_ns:
            # unknown-namespace traffic: only global rules can apply
            d = {"destination.service":
                 f"ghost{j % 251}.void{ns_rank}.svc.cluster.local"}
            ridx = None
        else:
            rules = by_ns[ns_rank]
            ridx = rules[int(rng.integers(len(rules)))]
            d = {"destination.service":
                 f"svc{ridx}.ns{ns_rank}.svc.cluster.local"}
        locked = rng.random() < 0.10
        d.update({
            "source.namespace":
                f"locked{(j if ridx is None else ridx) % 5}" if locked
                else f"team{int(rng.integers(40))}",
            "source.user": f"sidecar{int(rng.integers(sidecar_ids))}",
            "request.method": "GET" if rng.random() < 0.8 else "POST",
            "connection.mtls": bool(rng.random() < 0.8),
            "request.path": f"/api/v{j % 3}/items",
        })
        out.append(d)
    return out


def make_rbac_store(n_role_rules: int, n_users: int = 200,
                    n_services: int = 128):
    """BASELINE config 2: a 1k-role-rule RBAC world as real config
    kinds. One ServiceRole per role rule (services/methods/paths mixing
    exact, prefix `p*` and suffix `*s` stringMatch forms, every 5th
    with a constraint), one binding per role (user or group subjects,
    every 7th with a subject property) — all in namespace "default" —
    plus one authorization instance + rule. The whole policy lowers to
    device pseudo-rules (compiler/rbac_lower.py); reference semantics:
    mixer/adapter/rbac/rbac.go:181 HandleAuthorization."""
    from istio_tpu.runtime.store import MemStore

    s = MemStore()
    s.set(("handler", "istio-system", "authzh"), {
        "adapter": "rbac", "params": {"caching_ttl_s": 60.0}})
    s.set(("instance", "istio-system", "authz"), {
        "template": "authorization",
        "params": {
            "subject": {"user": 'source.user | ""',
                        "groups": 'source.labels["group"] | ""',
                        "properties": {
                            "version": 'source.labels["version"] | ""'}},
            "action": {"namespace": 'destination.namespace | ""',
                       "service": 'destination.service | ""',
                       "method": 'request.method | ""',
                       "path": 'request.path | ""',
                       "properties": {
                           "version":
                               'request.headers["version"] | ""'}}}})
    s.set(("rule", "istio-system", "authz-rule"), {
        "match": "", "actions": [{"handler": "authzh",
                                  "instances": ["authz"]}]})
    for i in range(n_role_rules):
        k = i % 4
        if k == 0:
            services = [f"svc{i % n_services}.default.svc.cluster.local"]
        elif k == 1:
            services = ["*.default.svc.cluster.local"]
        else:
            services = [f"svc{i % n_services}.*"]
        rule: dict = {"services": services,
                      "methods": (["GET"], ["GET", "POST"], ["*"],
                                  ["DELETE"])[i % 4],
                      "paths": ([f"/api/v{i % 9}/*"], ["*"],
                                [f"*/{i % 31}.html"],
                                [f"/data/{i % 100}"])[i % 4]}
        if i % 5 == 0:
            rule["constraints"] = [{"key": "version",
                                    "values": ["v1", f"v{i % 7}"]}]
        s.set(("servicerole", "default", f"role{i}"), {"rules": [rule]})
        subj: dict
        if i % 3 == 0:
            subj = {"user": f"user{i % n_users}"}
        elif i % 3 == 1:
            subj = {"group": f"group{i % 29}"}
        else:   # combined user AND group constraint
            subj = {"user": f"user{i % n_users}",
                    "group": f"group{i % 29}"}
        if i % 7 == 0:
            subj["properties"] = {"version": f"v{i % 7}"}
        s.set(("servicerolebinding", "default", f"bind{i}"), {
            "roleRef": {"kind": "ServiceRole", "name": f"role{i}"},
            "subjects": [subj]})
    return s


def make_rbac_request_dicts(batch: int, n_users: int = 200,
                            n_services: int = 128,
                            seed: int = 7) -> list[dict]:
    rng = np.random.default_rng(seed)
    out = []
    for i in range(batch):
        out.append({
            "source.user": f"user{int(rng.integers(n_users))}",
            "source.labels": {"group": f"group{int(rng.integers(32))}",
                              "version": f"v{int(rng.integers(8))}"},
            "destination.namespace": "default",
            "destination.service":
                f"svc{int(rng.integers(n_services))}"
                ".default.svc.cluster.local",
            "request.method": ("GET", "POST", "DELETE",
                               "PUT")[int(rng.integers(4))],
            "request.path": (f"/api/v{int(rng.integers(10))}/items",
                             f"/data/{int(rng.integers(120))}",
                             f"/static/{int(rng.integers(40))}.html"
                             )[i % 3],
            "request.headers": {"version": f"v{int(rng.integers(8))}"},
        })
    return out


def make_full_mesh(n_services: int = 5000, n_roles: int = 1000,
                   n_routes: int | None = None, seed: int = 11):
    """BASELINE config 5: the 5k-service full-mesh fused step — mTLS
    SAN whitelist + RBAC authz + quota + route NFA compiled into ONE
    ruleset/engine, evaluated in ONE device program per batch.

    → (engine, route_lo, route_hi, route_weights, meta dict).
    Row layout: [SAN rules | quota rule | authz rule | route rows |
    rbac pseudo-rows]. The full step wrapper (bench.py) computes check
    verdicts AND winning routes from the same matched plane.
    """
    from istio_tpu.compiler.rbac_lower import lower_rbac
    from istio_tpu.expr.parser import parse
    from istio_tpu.pilot.route_nfa import match_to_predicate
    from istio_tpu.models.policy_engine import RbacSpec

    n_routes = n_routes if n_routes is not None else n_services
    rng = np.random.default_rng(seed)
    preds: list[Rule] = []
    lists: list[ListEntrySpec] = []

    # 1. mTLS SAN whitelist per service (security/spiffe identities on
    #    source.user, the v0.4-era SAN attribute)
    for i in range(n_services):
        svc = f"svc{i}.ns{i % 41}.svc.cluster.local"
        preds.append(Rule(
            name=f"san{i}",
            match=f'destination.service == "{svc}" && connection.mtls'))
        sans = [f"spiffe://cluster.local/ns/ns{i % 41}/sa/sa{j}"
                for j in range(3)]
        lists.append(ListEntrySpec(rule=i, value_attr="source.user",
                                   entries=sans, blacklist=False))

    # 2. one mesh-wide per-user quota (device scatter-add counters)
    quota_rule = len(preds)
    preds.append(Rule(name="quota-all", match="connection.mtls"))
    quotas = [QuotaSpec(rule=quota_rule, key_attr="source.user",
                        max_amount=1 << 24, n_buckets=131_072)]

    # 3. RBAC authz over generated roles/bindings → pseudo-rules
    authz_rule = len(preds)
    preds.append(Rule(name="authz", match=""))
    roles, bindings = [], []
    for i in range(n_roles):
        roles.append({"namespace": "default", "name": f"role{i}",
                      "rules": [{
                          "services": [f"svc{i % n_services}.*"],
                          "methods": (["GET"], ["GET", "POST"],
                                      ["*"])[i % 3],
                          "paths": [f"/api/v{i % 9}/*"]}]})
        bindings.append({"namespace": "default", "name": f"bind{i}",
                         "roleRef": {"name": f"role{i}"},
                         "subjects": [{
                             "user": f"spiffe://cluster.local/ns/"
                                     f"ns{i % 41}/sa/sa{i % 3}"}]})
    inst_exprs = {
        "subject": {"user": parse("source.user")},
        "action": {"namespace": parse('destination.namespace | ""'),
                   "service": parse("destination.service"),
                   "method": parse("request.method"),
                   "path": parse("request.path")}}
    lowered = lower_rbac(roles, bindings, inst_exprs, MESH_FINDER)

    # 4. route NFA rows (VirtualService-style match blocks)
    route_lo = len(preds)
    services, rules_by_host = make_route_world(n_routes, n_services,
                                               seed=seed + 1)
    route_entries = []
    for hostname in sorted(rules_by_host):
        for cfg in rules_by_host[hostname]:
            src = cfg.spec.get("match", {}).get("source")
            pred = match_to_predicate(hostname, cfg.spec.get("match"),
                                      src)
            route_entries.append(
                (pred, int(cfg.spec.get("precedence", 0))))
    for j, (pred, _prec) in enumerate(route_entries):
        preds.append(Rule(name=f"route{j}", match=pred))
    route_hi = len(preds)

    # 5. rbac pseudo-rows at the tail
    allow_lo = len(preds)
    for k, ast in enumerate(lowered.allow_asts):
        preds.append(Rule(name=f"~rbac/{k}", ast=ast))
    allow_rows = tuple(range(allow_lo, allow_lo +
                             len(lowered.allow_asts)))
    guard_row = -1
    if lowered.guard_ast is not None:
        guard_row = len(preds)
        preds.append(Rule(name="~rbac/guard", ast=lowered.guard_ast))
    rbacs = [RbacSpec(rule=authz_rule, allow_rows=allow_rows,
                      guard_row=guard_row, valid_duration_s=60.0)]

    engine = PolicyEngine(preds, MESH_FINDER, deny=(), lists=lists,
                          quotas=quotas, rbacs=rbacs, jit=False)

    n_r = route_hi - route_lo
    order = sorted(range(n_r),
                   key=lambda i: (-route_entries[i][1], i))
    weights = np.zeros(max(n_r, 1), np.int32)
    for rank, idx in enumerate(order):
        weights[idx] = n_r - rank
    meta = {"n_services": n_services, "n_roles": n_roles,
            "n_routes": n_r, "n_rows": len(preds),
            "n_triples": lowered.n_triples,
            "host_fallback": len(engine.ruleset.host_fallback),
            # the route world, so request generators can craft traffic
            # that actually MATCHES route rows (VERDICT r3 item 7)
            "rules_by_host": rules_by_host}
    return engine, route_lo, route_hi, weights, meta


FULL_MESH_MIX = (0.30, 0.30, 0.20, 0.20)
"""Stated traffic fractions for make_full_mesh_requests (VERDICT r3
item 7): (routed+rbac-authorized, routed+rbac-denied, conformant
SAN/authz on ns-form hostnames, random)."""


def _route_request_pools(rules_by_host, n_roles: int):
    """→ (routed_pool, allowed_pool) of crafted request templates per
    route rule: (svc index, path-or-None, extra fields). allowed_pool
    entries additionally satisfy the generated role structure (role X
    covers svc X: path /api/v{X%9}/*, method GET, subject
    sa{X%3}@ns{X%41}) so the request both routes AND passes rbac."""
    routed, allowed = [], []
    for host, cfgs in sorted(rules_by_host.items()):
        x = int(host.split(".")[0][3:])
        for cfg in cfgs:
            m = cfg.spec.get("match", {}) or {}
            headers = m.get("request", {}).get("headers", {})
            fields = {"destination.service": host}
            path = None
            uri = headers.get("uri")
            if uri and "prefix" in uri:
                path = uri["prefix"] + "items"
            elif uri and "regex" in uri:
                # the generated regexes are ^/items/[0-9]+/r{k}$
                k = uri["regex"].rsplit("/r", 1)[-1].rstrip("$")
                path = f"/items/12345/r{k}"
            ck = headers.get("cookie")
            if ck and "exact" in ck:
                fields["cookie"] = ck["exact"]
            src = m.get("source")
            if src:
                fields["source.service"] = src
            entry = (x, path, fields)
            routed.append(entry)
            if x >= n_roles:
                continue        # no role covers this service
            if path is None:
                # cookie-only match: path is free — pick the role's
                allowed.append((x, f"/api/v{x % 9}/allowed", fields))
            elif path.startswith(f"/api/v{x % 9}/"):
                allowed.append(entry)
    return routed, allowed


def make_full_mesh_requests(batch: int, n_services: int = 5000,
                            seed: int = 12,
                            n_roles: int = 1000,
                            rules_by_host=None,
                            mix: tuple = FULL_MESH_MIX) -> list[dict]:
    """Traffic with STATED fractions (`mix`, VERDICT r3 item 7):
    routed+authorized and routed+denied classes craft requests that
    match an actual route rule of the generated route world (hostname
    + uri/header/source conditions — pass `rules_by_host` from
    make_full_mesh's meta); the conformant class follows the role
    structure against the ns-form SAN/authz world; the rest is random.
    Without `rules_by_host` the routed classes fall back to random
    (the pre-r4 shape)."""
    rng = np.random.default_rng(seed)
    covered = max(1, min(n_roles, n_services))
    routed_pool: list = []
    allowed_pool: list = []
    if rules_by_host:
        routed_pool, allowed_pool = _route_request_pools(
            rules_by_host, n_roles)
    out = []
    for i in range(batch):
        roll = rng.random()
        routed_entry = None
        conformant = False
        rbac_ok = False
        if roll < mix[0] and allowed_pool:
            routed_entry = allowed_pool[
                int(rng.integers(len(allowed_pool)))]
            rbac_ok = True
        elif roll < mix[0] + mix[1] and routed_pool:
            routed_entry = routed_pool[
                int(rng.integers(len(routed_pool)))]
        elif roll < mix[0] + mix[1]:
            # routed share with no route world available: fall back to
            # the pre-r4 50/50 conformant/random shape, NOT all-
            # conformant (r4 review finding)
            conformant = bool(rng.random() < 0.5)
        elif roll < mix[0] + mix[1] + mix[2]:
            conformant = True
        if routed_entry is not None:
            x, path, fields = routed_entry
            ns = x % 41
            if rbac_ok:
                user = f"spiffe://cluster.local/ns/ns{ns}/sa/sa{x % 3}"
                method = "GET"
                mtls = True
            else:
                user = (f"spiffe://cluster.local/ns/"
                        f"ns{int(rng.integers(41))}/sa/"
                        f"sa{int(rng.integers(4))}")
                method = ("GET", "POST", "DELETE")[int(rng.integers(3))]
                mtls = bool(rng.random() < 0.8)
            req = {
                "destination.namespace": "default",
                "source.user": user,
                "source.service":
                    fields.get("source.service",
                               f"svc{int(rng.integers(n_services))}"
                               ".default.svc.cluster.local"),
                "connection.mtls": mtls,
                "request.method": method,
                "request.path": path if path is not None else
                    f"/free/{i}",
                "request.headers": {"cookie": fields.get(
                    "cookie",
                    f"user=group{int(rng.integers(15))}")},
                "destination.service": fields["destination.service"],
            }
            out.append(req)
            continue
        svc = int(rng.integers(covered if conformant else n_services))
        ns = svc % 41
        if conformant:
            user_sa = svc % 3                   # bind{svc}'s subject
            method = "GET"                      # allowed by every role
            path = f"/api/v{svc % 9}/items"     # role's path prefix
        else:
            user_sa = int(rng.integers(4))
            method = ("GET", "POST", "DELETE")[int(rng.integers(3))]
            path = (f"/api/v{int(rng.integers(10))}/items",
                    f"/items/{int(rng.integers(1e6))}/r3",
                    f"/svc/{int(rng.integers(20))}/x")[i % 3]
        out.append({
            # conformant traffic hits the SAN/authz world (ns-form
            # hostnames); half the random remainder hits the route
            # world's default-form hostnames
            "destination.service":
                f"svc{svc}.ns{ns}.svc.cluster.local"
                if conformant or rng.random() < 0.5 else
                f"svc{svc}.default.svc.cluster.local",
            "destination.namespace": "default",
            "source.user": f"spiffe://cluster.local/ns/ns{ns}/sa/"
                           f"sa{user_sa}",
            "source.service": f"svc{int(rng.integers(n_services))}"
                              ".default.svc.cluster.local",
            "connection.mtls": bool(conformant or rng.random() < 0.8),
            "request.method": method,
            "request.path": path,
            "request.headers": {"cookie":
                                f"user=group{int(rng.integers(15))}"},
        })
    return out


def make_request_dicts(batch: int, seed: int = 1) -> list[dict]:
    rng = np.random.default_rng(seed)
    dicts = []
    for _ in range(batch):
        i = int(rng.integers(0, 4096))
        dicts.append({
            "destination.service":
                f"svc{rng.integers(0, 512)}.ns{i % 23}.svc.cluster.local",
            "source.namespace": f"ns{rng.integers(0, 25)}",
            "source.user": f"cluster.local/ns/ns{i % 23}/sa/sa{i % 61}",
            "request.method": "GET" if rng.random() < 0.7 else "POST",
            "request.path": f"/api/v{rng.integers(0, 4)}/products/{i}",
            "request.host": f"svc{i % 31}.ns{i % 23}.cluster.local",
            "request.size": i,
            "connection.mtls": bool(rng.random() < 0.5),
            "request.headers": {"cookie": f"session={rng.integers(0, 120)}",
                                ":authority": "productpage"},
        })
    return dicts


def make_bags(batch: int, seed: int = 1) -> list[Bag]:
    return [bag_from_mapping(d) for d in make_request_dicts(batch, seed)]


def make_request_ns(engine: PolicyEngine, batch: int,
                    seed: int = 2) -> np.ndarray:
    rng = np.random.default_rng(seed)
    ids = [engine.ruleset.namespace_id(f"ns{rng.integers(0, 25)}")
           for _ in range(batch)]
    return np.asarray(ids, np.int32)


def make_route_world(n_routes: int = 1000, n_services: int | None = None,
                     seed: int = 3):
    """Synthetic mesh routing world for the route-NFA bench: services
    with v1alpha1 route rules mixing URI prefix/regex, header exact
    matches, and source-label constraints (the VirtualService diet
    route.go compiles)."""
    from istio_tpu.pilot.model import (Config, ConfigMeta, Port, Service)

    rng = np.random.default_rng(seed)
    n_services = n_services or max(8, n_routes // 10)
    services = [Service(hostname=f"svc{i}.default.svc.cluster.local",
                        address=f"10.2.{i // 250}.{i % 250}",
                        ports=(Port("http", 9080, "HTTP"),))
                for i in range(n_services)]
    rules_by_host: dict = {}
    for r in range(n_routes):
        svc = services[int(rng.integers(n_services))]
        kind = int(rng.integers(4))
        match: dict = {"request": {"headers": {}}}
        headers = match["request"]["headers"]
        if kind == 0:
            headers["uri"] = {"prefix": f"/api/v{r % 7}/"}
        elif kind == 1:
            headers["uri"] = {"regex": f"^/items/[0-9]+/r{r % 11}$"}
        elif kind == 2:
            headers["cookie"] = {"exact": f"user=group{r % 13}"}
        else:
            headers["uri"] = {"prefix": f"/svc/{r % 17}/"}
            match["source"] = (f"svc{int(rng.integers(n_services))}"
                               ".default.svc.cluster.local")
        cfg = Config(ConfigMeta(type="route-rule", name=f"rr{r}",
                                namespace="default"),
                     {"destination": {"name": svc.hostname.split(".")[0]},
                      "precedence": int(rng.integers(4)),
                      "match": match,
                      "route": [{"labels": {"version": "v1"}}]})
        rules_by_host.setdefault(svc.hostname, []).append(cfg)
    return services, rules_by_host


def make_discovery_world(n_services: int = 48, n_namespaces: int = 8,
                         replicas: int = 3,
                         n_routes: int | None = None,
                         source_ns: int = 2, seed: int = 0):
    """Discovery-plane fleet world (the PR 9 Zipf fleet harness applied
    to Pilot): `n_services` services Zipf-assigned over `n_namespaces`
    namespaces (_fleet_ns_assignment — real namespace skew, a few big
    app namespaces and a long tail), each namespace's services sharing
    a PER-NAMESPACE http port (8000+k — per-namespace apps on their own
    ports is what makes RDS genuinely namespace-scoped: one-namespace
    churn touches one port's route configs), each service running
    `replicas` sidecar-fronted instances at distinct IPs. Route rules
    mix URI prefix/regex, header exact and presence matchers (the
    VirtualService diet), and services in the first `source_ns`
    namespaces additionally carry source-constrained rules — the part
    of generation that is per-node and rides the batched
    RouteScopeProgram device step; every other namespace's sidecars
    collapse to ONE shared RDS config per port.

    → (registry, store, nodes, meta): `nodes` are sidecar node-id
    strings (`sidecar~ip~id~domain`), meta carries ns_ports /
    nodes_by_ns / rules_by_ns for churn targeting. Build the world
    BEFORE constructing the DiscoveryService — store/registry events
    fire per mutation."""
    from istio_tpu.pilot.model import (Config, ConfigMeta,
                                       MemoryConfigStore, Port,
                                       Service)
    from istio_tpu.pilot.registry import MemoryRegistry

    rng = np.random.default_rng(seed)
    ns_of = _fleet_ns_assignment(n_services, n_namespaces, seed)
    registry = MemoryRegistry()
    store = MemoryConfigStore()
    nodes: list[str] = []
    nodes_by_ns: dict[int, list[str]] = {}
    hosts_by_ns: dict[int, list[str]] = {}
    node_idx = 0
    for i in range(n_services):
        k = int(ns_of[i])
        ns = f"ns{k}"
        host = f"svc{i}.{ns}.svc.cluster.local"
        port = Port("http", 8000 + k, "HTTP")
        endpoints = []
        for r in range(replicas):
            ip = (f"10.{8 + (node_idx >> 14)}."
                  f"{(node_idx >> 7) & 127}.{node_idx & 127}")
            endpoints.append((ip, {"version": f"v{r}"}))
            node = f"sidecar~{ip}~svc{i}-{r}.{ns}~cluster.local"
            nodes.append(node)
            nodes_by_ns.setdefault(k, []).append(node)
            node_idx += 1
        registry.add_service(
            Service(hostname=host,
                    address=f"10.3.{i // 250}.{i % 250}",
                    ports=(port,)),
            endpoints)
        hosts_by_ns.setdefault(k, []).append(host)
    n_routes = n_routes if n_routes is not None else n_services
    rules_by_ns: dict[int, list[str]] = {}
    for j in range(n_routes):
        i = int(rng.integers(n_services))
        k = int(ns_of[i])
        ns = f"ns{k}"
        host = f"svc{i}.{ns}.svc.cluster.local"
        kind = j % 4
        headers: dict = {}
        if kind == 0:
            headers["uri"] = {"prefix": f"/api/v{j % 7}/"}
        elif kind == 1:
            headers["uri"] = {"regex": f"^/items/[0-9]+/r{j % 11}$"}
        elif kind == 2:
            headers["cookie"] = {"exact": f"user=group{j % 13}"}
        else:
            headers["uri"] = {"prefix": f"/svc/{j % 17}/"}
            headers["x-debug"] = {"presence": True}
        match: dict = {"request": {"headers": headers}}
        if k < source_ns and j % 2 == 0:
            peers = hosts_by_ns[k]
            match["source"] = peers[(j * 7) % len(peers)]
        name = f"dr{j}"
        store.create(Config(
            ConfigMeta(type="route-rule", name=name, namespace=ns),
            {"destination": {"service": host},
             "precedence": int(rng.integers(4)),
             "match": match,
             "route": [{"labels": {"version": f"v{j % replicas}"}}]}))
        rules_by_ns.setdefault(k, []).append(name)
    meta = {
        "n_sidecars": len(nodes),
        "ns_ports": {k: 8000 + k for k in range(n_namespaces)},
        "ns_of": [int(x) for x in ns_of],
        "nodes_by_ns": nodes_by_ns,
        "hosts_by_ns": hosts_by_ns,
        "rules_by_ns": rules_by_ns,
        "source_ns": source_ns,
        "n_routes": n_routes,
    }
    return registry, store, nodes, meta


def churn_discovery_rule(store, meta: dict, ns_index: int,
                         tick: int) -> str:
    """One-namespace churn unit: bump one existing route rule's
    timeout in namespace `ns_index` (store.update fires the change
    event → scoped publish). Returns the rule name."""
    from istio_tpu.pilot.model import Config

    names = meta["rules_by_ns"].get(ns_index)
    if not names:
        raise ValueError(f"namespace ns{ns_index} has no route rules "
                         f"to churn")
    name = names[tick % len(names)]
    cfg = store.get("route-rule", name, f"ns{ns_index}")
    spec = dict(cfg.spec)
    spec["httpReqTimeout"] = {
        "simpleTimeout": {"timeout": f"{10 + tick}s"}}
    store.update(Config(cfg.meta, spec))
    return name


def make_route_requests(batch: int, n_services: int | None = None,
                        seed: int = 4) -> list[dict]:
    """Route-manifest-shaped requests (destination.service +
    request.path/headers + source.service)."""
    rng = np.random.default_rng(seed)
    n_services = n_services or 100
    out = []
    for i in range(batch):
        out.append({
            "destination.service": f"svc{int(rng.integers(n_services))}"
                                   ".default.svc.cluster.local",
            "request.path": f"/api/v{int(rng.integers(9))}/x{i}"
            if i % 2 == 0 else f"/items/{int(rng.integers(1e6))}/r3",
            "request.headers": {"cookie":
                                f"user=group{int(rng.integers(15))}"},
            "source.service": f"svc{int(rng.integers(n_services))}"
                              ".default.svc.cluster.local",
        })
    return out
