"""Loopback echo gRPC server — the transport-ceiling harness.

An aio server whose Check handler returns canned bytes with zero policy
work: loading it with the perf rig measures the box's python-grpc
structural ceiling, the upper bound for ANY served number (bench.py
reports it as served_grpc_ceiling_per_sec so "transport-bound" stays an
evidenced claim).
"""
from __future__ import annotations

import threading
from typing import Callable

_CANNED = b"\x0a\x02\x08\x00"


def start_echo_server(address: str = "127.0.0.1:0",
                      response: bytes = _CANNED
                      ) -> tuple[int, Callable[[], None]]:
    """Start the echo server on its own loop thread.
    → (port, stop()); raises RuntimeError if it fails to come up."""
    import asyncio

    import grpc
    from grpc import aio

    ready = threading.Event()
    box: list = [0, None, None]   # port, loop, server

    def run() -> None:
        async def echo(request, context):
            return response

        async def serve():
            server = aio.server()
            handlers = {"Check": grpc.unary_unary_rpc_method_handler(
                echo, request_deserializer=lambda b: b,
                response_serializer=lambda b: b)}
            server.add_generic_rpc_handlers((
                grpc.method_handlers_generic_handler(
                    "istio.mixer.v1.Mixer", handlers),))
            box[0] = server.add_insecure_port(address)
            await server.start()
            box[1] = asyncio.get_running_loop()
            box[2] = server
            ready.set()
            await server.wait_for_termination()

        asyncio.run(serve())

    threading.Thread(target=run, daemon=True).start()
    if not ready.wait(30):
        raise RuntimeError("echo server failed to start")

    def stop() -> None:
        import asyncio
        asyncio.run_coroutine_threadsafe(box[2].stop(0.2), box[1])

    return box[0], stop
