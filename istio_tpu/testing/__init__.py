"""Shared test substrate: the conformance corpus + fake bags.

Pattern from the reference's mixer/pkg/il/testing: ONE table of
expression → expected-result cases consumed by every engine (oracle
interpreter, TPU tensor compiler, ruleset matcher) so all backends prove
the same semantics.
"""

from istio_tpu.testing.corpus import CORPUS, Case, CORPUS_MANIFEST

__all__ = ["CORPUS", "Case", "CORPUS_MANIFEST"]
