"""Adapter inventory registry (reference:
mixer/pkg/config/adapterInfoRegistry.go + generated
mixer/adapter/inventory.gen.go)."""
from __future__ import annotations

from typing import Iterable

from istio_tpu.adapters.sdk import AdapterError, Info


class AdapterRegistry:
    def __init__(self) -> None:
        self._by_name: dict[str, Info] = {}

    def register(self, info: Info) -> Info:
        if info.name in self._by_name:
            raise AdapterError(f"duplicate adapter: {info.name}")
        self._by_name[info.name] = info
        return info

    def get(self, name: str) -> Info:
        info = self._by_name.get(name)
        if info is None:
            raise AdapterError(f"unknown adapter: {name}")
        return info

    def names(self) -> list[str]:
        return sorted(self._by_name)


adapter_registry = AdapterRegistry()


def load_inventory() -> AdapterRegistry:
    """Import every built-in adapter module (each registers itself)."""
    from istio_tpu.adapters import (circonus, denier, fluentd,  # noqa
                                    kubernetesenv, list_adapter, memquota,
                                    noop, opa, prometheus_adapter, rbac,
                                    servicecontrol, stackdriver, statsd,
                                    stdio)
    return adapter_registry
