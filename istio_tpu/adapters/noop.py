"""noop — benchmarking no-op adapter (reference: mixer/adapter/noop,
240 LoC): accepts every template, does nothing, returns OK."""
from __future__ import annotations

from typing import Any, Mapping, Sequence

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (Builder, CheckResult, Handler, Info,
                                    QuotaArgs, QuotaResult)


class NoopHandler(Handler):
    def handle_check(self, template: str,
                     instance: Mapping[str, Any]) -> CheckResult:
        return CheckResult()

    def handle_report(self, template: str,
                      instances: Sequence[Mapping[str, Any]]) -> None:
        return None

    def handle_quota(self, template: str, instance: Mapping[str, Any],
                     args: QuotaArgs) -> QuotaResult:
        return QuotaResult(granted_amount=args.quota_amount)

    def generate_attributes(self, template: str,
                            instance: Mapping[str, Any]) -> dict[str, Any]:
        return {}


class NoopBuilder(Builder):
    def build(self) -> Handler:
        return NoopHandler()


INFO = adapter_registry.register(Info(
    name="noop",
    supported_templates=("checknothing", "reportnothing", "listentry",
                         "quota", "authorization", "apikey", "metric",
                         "logentry", "tracespan", "kubernetes"),
    builder=NoopBuilder,
    description="no-op adapter for benchmarking"))
