"""rbac — role-based access control over ServiceRole/ServiceRoleBinding.

Reference: mixer/adapter/rbac (1,337 LoC; startController rbac.go:113,
HandleAuthorization :181). Roles grant access rules {services, methods,
paths, constraints}; bindings attach subjects {user, groups,
properties} to roles, both scoped to a namespace. `*` wildcards and
prefix/suffix `*` forms are honored exactly like the reference's
stringMatch. Config kinds arrive via the runtime config store
(ServiceRole/ServiceRoleBinding kinds, see runtime/config.py) instead
of a private k8s watcher — the runtime controller feeds `set_policies`
on snapshot swaps.

This host adapter is the semantics oracle for the fused NFA authz
path: compiler/rbac_lower.py compiles the same roles/bindings into
device pseudo-rule predicates (one row per binding-subject-rolerule
triple, OR-reduced by models/policy_engine.RbacSpec), and
tests/test_rbac_lower.py holds the two paths to field-by-field
agreement. Policies outside the lowerable subset stay here, on the
host overlay (snapshot.rbac_groups[...].lowered == False).
"""
from __future__ import annotations

import threading
from typing import Any, Mapping, Sequence

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import Builder, CheckResult, Env, Handler, Info
from istio_tpu.models.policy_engine import OK, PERMISSION_DENIED


def _string_match(pattern: str, value: str) -> bool:
    """reference rbac stringMatch: exact, `*`, prefix* or *suffix."""
    if pattern == "*":
        return True
    if pattern.endswith("*"):
        return value.startswith(pattern[:-1])
    if pattern.startswith("*"):
        return value.endswith(pattern[1:])
    return pattern == value


def _any_match(patterns: Sequence[str], value: str) -> bool:
    return not patterns or any(_string_match(p, value) for p in patterns)


class RbacHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env):
        self._lock = threading.Lock()
        self._roles: dict[tuple[str, str], Mapping] = {}
        self._bindings: dict[tuple[str, str], Mapping] = {}
        self.set_policies(config.get("roles", ()),
                          config.get("bindings", ()))
        self.caching_ttl_s = float(config.get("caching_ttl_s", 60.0))

    def set_policies(self, roles: Sequence[Mapping],
                     bindings: Sequence[Mapping]) -> None:
        """Atomic policy swap (controller feed, rbac.go:113 analog)."""
        new_roles = {(r.get("namespace", ""), r["name"]): r for r in roles}
        new_bindings = {(b.get("namespace", ""), b["name"]): b
                        for b in bindings}
        with self._lock:
            self._roles = new_roles
            self._bindings = new_bindings

    def handle_check(self, template: str,
                     instance: Mapping[str, Any]) -> CheckResult:
        subject = instance.get("subject", {}) or {}
        action = instance.get("action", {}) or {}
        namespace = str(action.get("namespace", ""))
        with self._lock:
            roles = dict(self._roles)
            bindings = dict(self._bindings)
        for (ns, name), binding in bindings.items():
            if ns != namespace:
                continue
            if not self._subject_bound(binding, subject):
                continue
            role_name = (binding.get("roleRef", {}) or {}).get("name", "")
            role = roles.get((ns, role_name))
            if role is not None and self._action_allowed(role, action):
                return CheckResult(status_code=OK,
                                   valid_duration_s=self.caching_ttl_s)
        return CheckResult(status_code=PERMISSION_DENIED,
                           status_message="RBAC: permission denied",
                           valid_duration_s=self.caching_ttl_s)

    @staticmethod
    def _subject_bound(binding: Mapping, subject: Mapping) -> bool:
        for s in binding.get("subjects", ()):
            if "user" in s and s["user"] != "*" and \
                    s["user"] != subject.get("user", ""):
                continue
            if "group" in s and s["group"] != "*" and \
                    s["group"] != subject.get("groups", ""):
                continue
            props = s.get("properties", {})
            sprops = subject.get("properties", {}) or {}
            if any(str(sprops.get(k, "")) != str(v)
                   for k, v in props.items()):
                continue
            return True
        return False

    @staticmethod
    def _action_allowed(role: Mapping, action: Mapping) -> bool:
        for rule in role.get("rules", ()):
            if not _any_match(rule.get("services", ()),
                              str(action.get("service", ""))):
                continue
            if not _any_match(rule.get("methods", ()),
                              str(action.get("method", ""))):
                continue
            if not _any_match(rule.get("paths", ()),
                              str(action.get("path", ""))):
                continue
            props = action.get("properties", {}) or {}
            constraints_ok = all(
                str(props.get(c.get("key", ""), "")) in
                [str(v) for v in c.get("values", ())]
                for c in rule.get("constraints", ()))
            if constraints_ok:
                return True
        return False


class RbacBuilder(Builder):
    def validate(self) -> list[str]:
        errs = []
        for r in self.config.get("roles", ()):
            if "name" not in r:
                errs.append("ServiceRole missing name")
        for b in self.config.get("bindings", ()):
            if "name" not in b:
                errs.append("ServiceRoleBinding missing name")
            if not (b.get("roleRef", {}) or {}).get("name"):
                errs.append(f"binding {b.get('name')}: missing roleRef")
        return errs

    def build(self) -> Handler:
        return RbacHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="rbac",
    supported_templates=("authorization",),
    builder=RbacBuilder,
    description="RBAC authz over ServiceRole/ServiceRoleBinding"))
