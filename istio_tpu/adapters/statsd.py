"""statsd — metrics to a statsd daemon over UDP.

Reference: mixer/adapter/statsd (1,351 LoC, go-statsd-client): each
metric instance maps to a statsd counter/gauge/timing with an optional
name template over the dimensions. UDP datagrams use the classic
`name:value|type[|@rate]` line protocol; sends are fire-and-forget
exactly like the reference.
"""
from __future__ import annotations

import socket
import string
from typing import Any, Mapping, Sequence

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import Builder, Env, Handler, Info

_TYPE_CODE = {"COUNTER": "c", "GAUGE": "g", "TIMING": "ms"}


class StatsdHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env,
                 sock: socket.socket | None = None):
        self.address = (config.get("address", "127.0.0.1"),
                        int(config.get("port", 8125)))
        self.prefix = config.get("prefix", "")
        self._sock = sock or socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._metrics: dict[str, Mapping[str, Any]] = {
            m["name"]: m for m in config.get("metrics", ())}
        self._env = env

    def _name_for(self, inst: Mapping[str, Any],
                  spec: Mapping[str, Any]) -> str:
        tmpl = spec.get("name_template", "")
        base = inst.get("name", "")
        if tmpl:
            dims = {k: str(v)
                    for k, v in (inst.get("dimensions", {}) or {}).items()}
            base = string.Template(tmpl).safe_substitute(dims)
        return self.prefix + base

    def handle_report(self, template: str,
                      instances: Sequence[Mapping[str, Any]]) -> None:
        for inst in instances:
            spec = self._metrics.get(inst.get("name", ""))
            if spec is None:
                continue
            code = _TYPE_CODE.get(spec.get("type", "COUNTER"), "c")
            value = inst.get("value", 0)
            if isinstance(value, bool):
                value = int(value)
            line = f"{self._name_for(inst, spec)}:{value}|{code}"
            rate = spec.get("sample_rate")
            if rate is not None:
                line += f"|@{rate}"
            try:
                self._sock.sendto(line.encode("utf-8"), self.address)
            except OSError as exc:   # fire-and-forget
                self._env.logger.warning("statsd send failed: %s", exc)

    def close(self) -> None:
        self._sock.close()


class StatsdBuilder(Builder):
    def validate(self) -> list[str]:
        errs = []
        for m in self.config.get("metrics", ()):
            if m.get("type", "COUNTER") not in _TYPE_CODE:
                errs.append(f"{m.get('name')}: unknown type")
        return errs

    def build(self) -> Handler:
        return StatsdHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="statsd",
    supported_templates=("metric",),
    builder=StatsdBuilder,
    description="metrics to statsd over UDP"))
