"""prometheus — expose metric instances as Prometheus metrics.

Reference: mixer/adapter/prometheus (2,767 LoC): each configured metric
maps a metric instance to a counter/gauge/histogram with label names
drawn from the instance's dimensions; an HTTP scrape endpoint serves
the registry. Backed by prometheus_client here; the scrape server is
started by the runtime's monitoring port (server assembly), not by the
adapter itself.
"""
from __future__ import annotations

import threading
from typing import Any, Mapping, Sequence

import prometheus_client

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (AdapterError, Builder, Env, Handler,
                                    Info)


def _label_value(v: Any) -> str:
    return str(v)


class PrometheusHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env,
                 registry: prometheus_client.CollectorRegistry | None = None):
        self.registry = registry or prometheus_client.CollectorRegistry()
        self._metrics: dict[str, tuple[str, Any, list[str]]] = {}
        self._lock = threading.Lock()
        namespace = config.get("namespace", "istio_tpu")
        for m in config.get("metrics", ()):
            name = m["name"]
            kind = m.get("kind", "COUNTER")
            labels = list(m.get("label_names", ()))
            pname = f"{namespace}_{name}".replace(".", "_").replace("-", "_")
            if kind == "COUNTER":
                metric = prometheus_client.Counter(
                    pname, m.get("description", name), labels,
                    registry=self.registry)
            elif kind == "GAUGE":
                metric = prometheus_client.Gauge(
                    pname, m.get("description", name), labels,
                    registry=self.registry)
            elif kind == "DISTRIBUTION":
                buckets = m.get("buckets") or prometheus_client.Histogram \
                    .DEFAULT_BUCKETS
                metric = prometheus_client.Histogram(
                    pname, m.get("description", name), labels,
                    buckets=buckets, registry=self.registry)
            else:
                raise AdapterError(f"unknown metric kind {kind}")
            self._metrics[name] = (kind, metric, labels)

    def handle_report(self, template: str,
                      instances: Sequence[Mapping[str, Any]]) -> None:
        for inst in instances:
            entry = self._metrics.get(inst.get("name", ""))
            if entry is None:
                continue
            kind, metric, labels = entry
            dims = inst.get("dimensions", {}) or {}
            values = [_label_value(dims.get(l, "")) for l in labels]
            bound = metric.labels(*values) if labels else metric
            value = inst.get("value", 0)
            if isinstance(value, bool):
                value = int(value)
            if kind == "COUNTER":
                bound.inc(float(value))
            elif kind == "GAUGE":
                bound.set(float(value))
            else:
                bound.observe(float(value))


class PrometheusBuilder(Builder):
    def validate(self) -> list[str]:
        errs = []
        for m in self.config.get("metrics", ()):
            if "name" not in m:
                errs.append("metric missing name")
            if m.get("kind", "COUNTER") not in ("COUNTER", "GAUGE",
                                                "DISTRIBUTION"):
                errs.append(f"{m.get('name')}: unknown kind")
        return errs

    def build(self) -> Handler:
        return PrometheusHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="prometheus",
    supported_templates=("metric",),
    builder=PrometheusBuilder,
    description="metric instances as prometheus counters/gauges/"
                "histograms"))
