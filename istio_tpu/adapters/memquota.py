"""memquota — in-memory rate limits / quotas with rolling windows.

Reference: mixer/adapter/memquota (2,230 LoC; HandleQuota memquota.go:
107, alloc :118, dedup buildWithDedup :259). Semantics reproduced:

  * per-quota `max_amount` with optional `valid_duration` — a rolling
    window implemented with per-slice expiry buckets (`ticks`), or an
    exact non-expiring counter when no duration is set;
  * dedup: a (dedup_id → granted amount, expiry) cache so sidecar
    retries of the same allocation don't double-count;
  * best-effort vs all-or-nothing allocation (QuotaArgs.best_effort);
  * quota keys are the instance's flattened dimensions (the reference
    hashes the instance signature; we use a stable repr).

State is per-replica and lost on restart — explicitly best-effort, like
the reference. Device-side variants: the SERVED quota pool
(runtime/device_quota.py) mirrors this adapter's ROLLING windows with
tick-exact parity; the engine-embedded QuotaSpec
(models/policy_engine.py) keeps a simplified fixed window for the
all-device benchmark step. This host adapter is the general path and
the semantics oracle.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Mapping

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (Builder, Env, Handler, Info, QuotaArgs,
                                    QuotaResult)
from istio_tpu.models.policy_engine import RESOURCE_EXHAUSTED

_TICKS_PER_WINDOW = 10


def _key(instance: Mapping[str, Any]) -> str:
    dims = instance.get("dimensions", {})
    return instance.get("name", "") + "|" + repr(sorted(dims.items()))


class QuotaBackend:
    """The shared mutable half of a memquota handler: cells + dedup
    cache under one lock. Injected via the `backend` config param
    (the adapter-executor plane's cross-replica seam — the redis-style
    shared-quota role: N replicas' handlers allocate against ONE
    backend, so a dedup_id retried on any replica replays the original
    grant and the window is enforced globally). Default: each handler
    builds its own (the reference's per-replica best-effort state)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.cells: dict[str, Any] = {}
        self.dedup: dict[str, tuple[int, float]] = {}


class _Window:
    """Rolling window: counts per tick; expired ticks are reclaimed."""

    def __init__(self, max_amount: int, duration_s: float):
        self.max = max_amount
        self.duration = duration_s
        self.tick_len = duration_s / _TICKS_PER_WINDOW
        self.ticks: dict[int, int] = {}

    def _gc(self, now: float) -> None:
        horizon = int(now / self.tick_len) - _TICKS_PER_WINDOW
        for t in [t for t in self.ticks if t <= horizon]:
            del self.ticks[t]

    def used(self, now: float) -> int:
        self._gc(now)
        return sum(self.ticks.values())

    def alloc(self, amount: int, best_effort: bool, now: float) -> int:
        avail = self.max - self.used(now)
        granted = min(amount, avail) if best_effort else \
            (amount if avail >= amount else 0)
        if granted > 0:
            t = int(now / self.tick_len)
            self.ticks[t] = self.ticks.get(t, 0) + granted
        return max(granted, 0)

    def release(self, amount: int, now: float) -> int:
        """ReleaseBestEffort: subtract from newest ticks."""
        self._gc(now)
        remaining = amount
        for t in sorted(self.ticks, reverse=True):
            take = min(self.ticks[t], remaining)
            self.ticks[t] -= take
            remaining -= take
            if remaining == 0:
                break
        return amount - remaining


class _Exact:
    def __init__(self, max_amount: int):
        self.max = max_amount
        self.count = 0

    def alloc(self, amount: int, best_effort: bool, now: float) -> int:
        avail = self.max - self.count
        granted = min(amount, avail) if best_effort else \
            (amount if avail >= amount else 0)
        self.count += max(granted, 0)
        return max(granted, 0)

    def release(self, amount: int, now: float) -> int:
        take = min(amount, self.count)
        self.count -= take
        return take


class MemQuotaHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env,
                 clock=time.monotonic):
        self._clock = clock
        backend = config.get("backend")
        if backend is None:
            backend = QuotaBackend()
        self._backend = backend
        self._lock = backend.lock
        self._limits: dict[str, dict] = {}
        self._cells = backend.cells
        self._dedup = backend.dedup
        self.min_dedup_s = float(config.get("min_deduplication_duration_s",
                                            1.0))
        for q in config.get("quotas", ()):
            self._limits[q["name"]] = {
                "max": int(q.get("max_amount", 0)),
                "duration": float(q.get("valid_duration_s", 0.0)),
            }

    def _cell(self, name: str, dims_key: str):
        lim = self._limits.get(name)
        if lim is None:
            return None
        cell = self._cells.get(dims_key)
        if cell is None:
            cell = (_Window(lim["max"], lim["duration"])
                    if lim["duration"] > 0 else _Exact(lim["max"]))
            self._cells[dims_key] = cell
        return cell

    def handle_quota(self, template: str, instance: Mapping[str, Any],
                     args: QuotaArgs) -> QuotaResult:
        name = instance.get("name", "")
        # quota-backend chaos seam (stall latency / injected failures,
        # keyed by instance name) — sits BEFORE the backend lock so a
        # stalled call exercises the executor lane's deadline path, not
        # a lock convoy. Lazy import keeps the adapter importable
        # standalone; the probe is two dict lookups when unarmed.
        from istio_tpu.runtime.resilience import CHAOS
        CHAOS.quota_call(name)
        now = self._clock()
        lim = self._limits.get(name)
        if lim is None:
            return QuotaResult(granted_amount=0,
                               status_code=RESOURCE_EXHAUSTED,
                               status_message=f"unknown quota {name}")
        with self._lock:
            self._gc_dedup(now)
            if args.dedup_id:
                hit = self._dedup.get(args.dedup_id)
                if hit is not None and hit[1] > now:
                    # replay the ORIGINAL outcome, including denial —
                    # a cached grant of 0 must not read as success
                    status = 0 if hit[0] > 0 or args.quota_amount == 0 \
                        else RESOURCE_EXHAUSTED
                    return QuotaResult(granted_amount=hit[0],
                                       valid_duration_s=lim["duration"],
                                       status_code=status)
            cell = self._cell(name, _key(instance))
            granted = cell.alloc(args.quota_amount, args.best_effort, now)
            if args.dedup_id:
                expiry = now + max(lim["duration"], self.min_dedup_s)
                self._dedup[args.dedup_id] = (granted, expiry)
        status = 0 if granted > 0 or args.quota_amount == 0 \
            else RESOURCE_EXHAUSTED
        return QuotaResult(granted_amount=granted,
                           valid_duration_s=lim["duration"],
                           status_code=status)

    def release(self, instance: Mapping[str, Any], amount: int) -> int:
        """ReleaseBestEffort (quota return path)."""
        with self._lock:
            cell = self._cell(instance.get("name", ""), _key(instance))
            if cell is None:
                return 0
            return cell.release(amount, self._clock())

    def _gc_dedup(self, now: float) -> None:
        if len(self._dedup) > 10_000:
            for k in [k for k, (_, exp) in self._dedup.items()
                      if exp <= now]:
                del self._dedup[k]


class MemQuotaBuilder(Builder):
    def validate(self) -> list[str]:
        errs = []
        for q in self.config.get("quotas", ()):
            if "name" not in q:
                errs.append("quota missing name")
            if int(q.get("max_amount", 0)) < 0:
                errs.append(f"{q.get('name')}: negative max_amount")
        return errs

    def build(self) -> Handler:
        return MemQuotaHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="memquota",
    supported_templates=("quota",),
    builder=MemQuotaBuilder,
    description="in-memory rolling-window quota with dedup"))
