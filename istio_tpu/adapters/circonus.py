"""circonus — metric aggregation + httptrap submission.

Reference: mixer/adapter/circonus/circonus.go — the handler feeds a
circonus-gometrics aggregator (counters, gauges, log-linear
histograms) that a ScheduleDaemon ticker flushes to the configured
httptrap submission URL every `submission_interval` (min 1s,
circonus.go:146-150); HandleMetric dispatches on the per-metric
configured type (GAUGE stores last value, COUNTER increments,
DISTRIBUTION records a timing sample, circonus.go:159-182). Validate
cross-checks the metric config against the inferred metric types both
ways (circonus.go:124-144).

This build re-implements the aggregation + wire payload natively: the
flush produces the httptrap JSON body (`{name: {"_type": ..,
"_value": ..}}`, histograms as circllhist "H[m.me±e]=n" bin strings)
and hands it to an injectable `transport(url, payload)` — the only
network hop, absent in this zero-egress image.
"""
from __future__ import annotations

import math
import threading
from typing import Any, Callable, Mapping, Sequence
from urllib.parse import urlparse

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (AdapterUnavailable, Builder, Env,
                                    Handler, Info)

GAUGE, COUNTER, DISTRIBUTION = "gauge", "counter", "distribution"


def histogram_bin(value: float) -> str:
    """circllhist log-linear bin label: two significant decimal digits
    times a power of ten, e.g. 0.0034 → 'H[+34e-4]'."""
    if value == 0 or not math.isfinite(value):
        return "H[0]"
    sign = "+" if value > 0 else "-"
    mag = abs(value)
    exp = math.floor(math.log10(mag)) - 1
    mant = int(mag / (10.0 ** exp))
    if mant >= 100:            # rounding pushed into the next decade
        mant //= 10
        exp += 1
    return f"H[{sign}{mant}e{exp:+03d}]"


class MetricAggregator:
    """The circonus-gometrics accumulation model: counters sum,
    gauges keep the last value, histograms count samples per
    log-linear bin. flush() drains to an httptrap JSON payload."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, dict[str, int]] = {}

    def increment(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def timing(self, name: str, value: float) -> None:
        with self._lock:
            bins = self._hists.setdefault(name, {})
            b = histogram_bin(value)
            bins[b] = bins.get(b, 0) + 1

    def flush(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            out: dict[str, dict[str, Any]] = {}
            for name, v in self._counters.items():
                out[name] = {"_type": "L", "_value": v}
            for name, v in self._gauges.items():
                out[name] = {"_type": "n", "_value": v}
            for name, bins in self._hists.items():
                out[name] = {"_type": "h",
                             "_value": [f"{b}={n}" for b, n in
                                        sorted(bins.items())]}
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            return out


class CirconusHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env):
        self.env = env
        self.url = str(config.get("submission_url", ""))
        self.metrics: dict[str, str] = {
            m["name"]: m.get("type", COUNTER)
            for m in config.get("metrics", ())}
        self.transport: Callable[[str, Mapping[str, Any]], Any] | None = \
            config.get("transport")
        self.agg = MetricAggregator()
        self._stop = threading.Event()
        interval = float(config.get("submission_interval_s", 10.0))
        self._ticker = threading.Thread(
            target=self._run, args=(interval,), daemon=True,
            name="circonus-flush")
        self._ticker.start()

    def _run(self, interval: float) -> None:
        while not self._stop.wait(interval):
            try:
                self._flush()
            except AdapterUnavailable:
                pass               # keep aggregating; drain on close
            except Exception:
                self.env.logger.exception("circonus flush failed")

    def _flush(self) -> None:
        if self.transport is None:
            # keep aggregating rather than dropping the drained batch
            raise AdapterUnavailable(
                "circonus: no egress in this build; inject `transport` "
                "to submit to an httptrap")
        payload = self.agg.flush()
        if payload:
            try:
                self.transport(self.url, payload)
            except Exception:
                self._restore(payload)   # retry next tick, don't drop
                raise

    def _restore(self, payload: Mapping[str, Any]) -> None:
        for name, entry in payload.items():
            if entry["_type"] == "L":
                self.agg.increment(name, entry["_value"])
            elif entry["_type"] == "n":
                with self.agg._lock:
                    self.agg._gauges.setdefault(name, entry["_value"])
            else:
                with self.agg._lock:
                    bins = self.agg._hists.setdefault(name, {})
                    for s in entry["_value"]:
                        b, n = s.rsplit("=", 1)
                        bins[b] = bins.get(b, 0) + int(n)

    def handle_report(self, template: str,
                      instances: Sequence[Mapping[str, Any]]) -> None:
        for inst in instances:
            name = str(inst.get("name", ""))
            mtype = self.metrics.get(name)
            if mtype == GAUGE:
                self.agg.gauge(name, float(inst.get("value", 0)))
            elif mtype == DISTRIBUTION:
                # durations normalize to seconds upstream; record raw
                self.agg.timing(name, float(inst.get("value", 0.0)))
            elif mtype == COUNTER:
                self.agg.increment(name)
            # unconfigured metrics are dropped (circonus.go switch
            # default: no case → no record)

    def close(self) -> None:
        self._stop.set()
        self._ticker.join(timeout=2.0)
        try:
            self._flush()          # final drain, circonus.go:94-96
        except AdapterUnavailable:
            pass


class CirconusBuilder(Builder):
    def validate(self) -> list[str]:
        errs: list[str] = []
        url = str(self.config.get("submission_url", ""))
        parsed = urlparse(url)
        if not (parsed.scheme and parsed.netloc):
            errs.append(f"submission_url: not a valid URL: {url!r}")
        if float(self.config.get("submission_interval_s", 10.0)) < 1.0:
            errs.append("submission_interval_s: must be at least 1 second")
        configured = {m.get("name") for m in self.config.get("metrics", ())}
        for m in self.config.get("metrics", ()):
            if m.get("type", COUNTER) not in (GAUGE, COUNTER, DISTRIBUTION):
                errs.append(f"metrics: bad type for {m.get('name')}")
        declared = set(getattr(self, "types", {}) or ())
        for name in declared - configured:
            errs.append(f"metrics: missing metric configuration {name}")
        for name in configured - declared:
            if declared:
                errs.append(f"metrics: missing metric type for {name}")
        return errs

    def build(self) -> Handler:
        return CirconusHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="circonus",
    supported_templates=("metric",),
    builder=CirconusBuilder,
    description="metric aggregation → circonus httptrap"))
