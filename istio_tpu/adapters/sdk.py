"""Adapter SDK — the contract between runtime and adapters.

Reference: mixer/pkg/adapter — `Info` (info.go:22), HandlerBuilder/
Handler (handler.go), `CheckResult{Status, ValidDuration,
ValidUseCount}` (check.go:28), `QuotaResult` (quotas.go:55), `Env`
(adapter.go). The reference's adapterlinter bans goroutines in adapters;
the equivalent rule here is that adapters must use `Env.schedule_work`
for background work so the runtime can drain on close.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Mapping, Sequence

from istio_tpu.models.policy_engine import OK

DEFAULT_VALID_DURATION_S = 5.0
DEFAULT_VALID_USE_COUNT = 10_000


class AdapterError(ValueError):
    """Config/build-time adapter error (configError.go role)."""


class AdapterUnavailable(RuntimeError):
    """Raised by gated stub adapters whose SaaS backend is not wired."""


@dataclasses.dataclass
class CheckResult:
    """adapter/check.go:28."""
    status_code: int = OK
    status_message: str = ""
    valid_duration_s: float = DEFAULT_VALID_DURATION_S
    valid_use_count: int = DEFAULT_VALID_USE_COUNT

    @property
    def ok(self) -> bool:
        return self.status_code == OK


@dataclasses.dataclass
class QuotaArgs:
    """adapter/quotas.go:33 QuotaArgs."""
    quota_amount: int = 1
    best_effort: bool = True
    dedup_id: str = ""


@dataclasses.dataclass
class QuotaResult:
    """adapter/quotas.go:55."""
    granted_amount: int = 0
    valid_duration_s: float = DEFAULT_VALID_DURATION_S
    status_code: int = OK
    status_message: str = ""


class Env:
    """adapter.Env: scoped logger + scheduled work (runtime/env.go)."""

    def __init__(self, adapter_name: str, pool=None):
        self.logger = logging.getLogger(f"istio_tpu.adapter.{adapter_name}")
        self._pool = pool

    def schedule_work(self, fn: Callable[[], None]) -> None:
        if self._pool is None:
            fn()
        else:
            self._pool.submit(fn)


class Handler:
    """Base runtime handler. Adapters override the Handle* methods for
    the templates they support; the dispatcher calls exactly one method
    per (instance, variety)."""

    def handle_check(self, template: str,
                     instance: Mapping[str, Any]) -> CheckResult:
        raise NotImplementedError

    def handle_report(self, template: str,
                      instances: Sequence[Mapping[str, Any]]) -> None:
        raise NotImplementedError

    def handle_quota(self, template: str, instance: Mapping[str, Any],
                     args: QuotaArgs) -> QuotaResult:
        raise NotImplementedError

    def generate_attributes(self, template: str,
                            instance: Mapping[str, Any]) -> dict[str, Any]:
        """APA adapters: returns output attributes (pre-binding)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class Builder:
    """HandlerBuilder: validate() config then build() a Handler."""

    def __init__(self, config: Mapping[str, Any], env: Env):
        self.config = dict(config)
        self.env = env

    def set_types(self, types: Mapping[str, Mapping[str, Any]]) -> None:
        """Inferred instance types per template (SetTypeFn payload)."""
        self.types = dict(types)

    def validate(self) -> list[str]:
        return []

    def build(self) -> Handler:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Info:
    """adapter/info.go:22."""
    name: str
    supported_templates: tuple[str, ...]
    builder: Callable[[Mapping[str, Any], Env], Builder]
    description: str = ""
    default_config: Mapping[str, Any] = dataclasses.field(
        default_factory=dict)
