"""stdio — logs and metrics to stdout/stderr/files as structured lines.

Reference: mixer/adapter/stdio (1,904 LoC, zap-backed). Emits one JSON
line per logentry/metric instance with the reference's field layout
(level, time, instance name, variables). Output stream selectable
(STDOUT/STDERR/file path) with max-days style rotation left to the
platform (files are opened append-only).
"""
from __future__ import annotations

import datetime
import json
import sys
import threading
from typing import Any, IO, Mapping, Sequence

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import Builder, Env, Handler, Info

_SEVERITY_LEVELS = {"default": "info", "info": "info", "warning": "warn",
                    "error": "error"}


def _jsonable(v: Any) -> Any:
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, datetime.datetime):
        return v.isoformat()
    if isinstance(v, datetime.timedelta):
        return f"{v.total_seconds()}s"
    if isinstance(v, Mapping):
        return {k: _jsonable(x) for k, x in v.items()}
    return v


class StdioHandler(Handler):
    def __init__(self, config: Mapping[str, Any]):
        stream = config.get("log_stream", "STDOUT")
        self._own_file = False
        if stream == "STDERR":
            self._out: IO[str] = sys.stderr
        elif stream == "STDOUT":
            self._out = sys.stdout
        else:
            self._out = open(stream, "a", encoding="utf-8")
            self._own_file = True
        self.metric_level = config.get("metric_level", "info")
        self._lock = threading.Lock()

    def _emit(self, record: Mapping[str, Any]) -> None:
        line = json.dumps(_jsonable(record), sort_keys=True, default=str)
        with self._lock:
            self._out.write(line + "\n")
            self._out.flush()

    def handle_report(self, template: str,
                      instances: Sequence[Mapping[str, Any]]) -> None:
        for inst in instances:
            if template == "logentry":
                sev = str(inst.get("severity", "default")).lower()
                self._emit({
                    "level": _SEVERITY_LEVELS.get(sev, "info"),
                    "time": inst.get("timestamp"),
                    "instance": inst.get("name"),
                    **(inst.get("variables", {}) or {})})
            elif template == "metric":
                self._emit({
                    "level": self.metric_level,
                    "instance": inst.get("name"),
                    "value": inst.get("value"),
                    **(inst.get("dimensions", {}) or {})})

    def close(self) -> None:
        if self._own_file:
            self._out.close()


class StdioBuilder(Builder):
    def build(self) -> Handler:
        return StdioHandler(self.config)


INFO = adapter_registry.register(Info(
    name="stdio",
    supported_templates=("logentry", "metric"),
    builder=StdioBuilder,
    description="logs/metrics to stdout/stderr/files as JSON lines"))
