"""A Rego-subset evaluator for the opa adapter.

Reference: mixer/adapter/opa embeds the full OPA engine
(opa.go:84-142: compile policy modules, evaluate `checkMethod` over an
`input` document). Embedding OPA is out of scope here; this module
implements the Rego subset the reference's own policy corpus
(opa_test.go:180-340) exercises, natively:

  * `package` / `import data.<pkg>` (import alias binding)
  * complete rules `name = value { body }`, `name { body }` (value
    true), `default name = value`, constants `name = literal`
  * bodies: conjunctions of expressions, `;` or newline separated,
    `#` comments
  * unification `a = b` with variable binding, element-wise over
    arrays/objects
  * references `input.a.b`, `data.pkg.rule`, `obj[key]`,
    `arr[_]` (existential iteration), `arr[i]`/`obj[var]` (binding
    iteration), chained `policy[_].rule`
  * negation-as-failure `not expr`
  * builtins: trim(s, cutset, out), split(s, sep, out),
    concat(sep, arr, out), lower/upper(s, out), startswith/endswith/
    contains(s, x), count(x, out), plus `=` itself

Evaluation is top-down with backtracking over generator-yielded
binding environments; rule dependencies memoize per query with a
cycle guard, and complete-rule definitions that succeed with
disagreeing values raise eval_conflict_error (OPA semantics — the
opa adapter turns that into a fail-closed deny). Enough to run the
reference's service-graph/org-chart/bucket-admin policies
byte-for-byte.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Iterator, Mapping

__all__ = ["RegoError", "RegoEngine", "parse_module"]


class RegoError(ValueError):
    pass


# ---------------------------------------------------------------------------
# terms
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Var:
    name: str


@dataclasses.dataclass(frozen=True)
class Wildcard:
    pass


@dataclasses.dataclass(frozen=True)
class Ref:
    """base.path[0].path[1]... — each element a str key, int index,
    Var, Wildcard, or Scalar from a bracket."""
    base: str
    path: tuple


@dataclasses.dataclass(frozen=True)
class ArrayT:
    items: tuple


@dataclasses.dataclass(frozen=True)
class ObjectT:
    items: tuple          # ((key_term, value_term), ...)


@dataclasses.dataclass(frozen=True)
class SetT:
    items: tuple


@dataclasses.dataclass(frozen=True)
class Call:
    name: str
    args: tuple


@dataclasses.dataclass(frozen=True)
class Unify:
    left: Any
    right: Any


@dataclasses.dataclass(frozen=True)
class NotExpr:
    expr: Any


@dataclasses.dataclass(frozen=True)
class RuleDef:
    name: str
    value: Any            # head value term (True for `name { body }`)
    body: tuple           # expressions; () for constants
    default: bool = False


@dataclasses.dataclass
class Module:
    package: str
    imports: dict         # alias → data path ("service_graph" → pkg)
    rules: dict           # name → [RuleDef]


# ---------------------------------------------------------------------------
# tokenizer / parser
# ---------------------------------------------------------------------------

_TOKEN = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>\#[^\n]*)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<punct>\{|\}|\[|\]|\(|\)|,|;|:=|:|=|\.)
""", re.VERBOSE)


def _tokenize(src: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None:
            raise RegoError(f"rego_parse_error: no match found at "
                            f"{src[pos:pos + 20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        out.append((kind, m.group()))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, value: str) -> None:
        kind, v = self.next()
        if v != value:
            raise RegoError(f"rego_parse_error: expected {value!r}, "
                            f"got {v!r}")

    def at(self, value: str) -> bool:
        return self.peek()[1] == value

    # -- module --

    def module(self) -> Module:
        self.expect("package")
        package = self._dotted_name()
        imports: dict[str, str] = {}
        rules: dict[str, list[RuleDef]] = {}
        while not self.at(""):
            if self.at("import"):
                self.next()
                path = self._dotted_name()
                parts = path.split(".")
                if parts[0] != "data":
                    raise RegoError("only `import data.<pkg>` supported")
                imports[parts[-1]] = ".".join(parts[1:])
                continue
            rule = self._rule()
            rules.setdefault(rule.name, []).append(rule)
        return Module(package=package, imports=imports, rules=rules)

    def _dotted_name(self) -> str:
        kind, v = self.next()
        if kind != "ident":
            raise RegoError(f"rego_parse_error: expected name, got {v!r}")
        parts = [v]
        while self.at("."):
            self.next()
            kind, v = self.next()
            if kind != "ident":
                raise RegoError("rego_parse_error: bad dotted name")
            parts.append(v)
        return ".".join(parts)

    def _rule(self) -> RuleDef:
        default = False
        if self.at("default"):
            self.next()
            default = True
        kind, name = self.next()
        if kind != "ident":
            raise RegoError(f"rego_parse_error: expected rule name, "
                            f"got {name!r}")
        value: Any = True
        body: tuple = ()
        if self.at("=") or self.at(":="):
            self.next()
            value = self._term()
        if self.at("{"):
            self.next()
            body = tuple(self._body())
            self.expect("}")
        if default and body:
            raise RegoError("default rules cannot have bodies")
        return RuleDef(name=name, value=value, body=body, default=default)

    def _body(self) -> list:
        exprs = []
        while not self.at("}"):
            exprs.append(self._expr())
            if self.at(";"):
                self.next()
        return exprs

    def _expr(self) -> Any:
        if self.at("not"):
            self.next()
            return NotExpr(self._expr())
        left = self._term()
        if self.at("=") or self.at(":="):
            self.next()
            right = self._term()
            return Unify(left, right)
        return left

    def _term(self) -> Any:
        kind, v = self.peek()
        if kind == "string":
            self.next()
            return _unquote(v)
        if kind == "number":
            self.next()
            return float(v) if "." in v else int(v)
        if v == "[":
            self.next()
            items = []
            while not self.at("]"):
                items.append(self._term())
                if self.at(","):
                    self.next()     # trailing comma allowed
            self.expect("]")
            return ArrayT(tuple(items))
        if v == "{":
            return self._object_or_set()
        if kind == "ident":
            return self._ref_or_call()
        raise RegoError(f"rego_parse_error: unexpected {v!r}")

    def _object_or_set(self) -> Any:
        self.expect("{")
        if self.at("}"):
            self.next()
            return ObjectT(())
        first = self._term()
        if self.at(":"):
            self.next()
            items = [(first, self._term())]
            while self.at(","):
                self.next()
                if self.at("}"):
                    break           # trailing comma
                k = self._term()
                self.expect(":")
                items.append((k, self._term()))
            self.expect("}")
            return ObjectT(tuple(items))
        items = [first]
        while self.at(","):
            self.next()
            if self.at("}"):
                break               # trailing comma
            items.append(self._term())
        self.expect("}")
        return SetT(tuple(items))

    def _ref_or_call(self) -> Any:
        kind, name = self.next()
        if name == "true":
            return True
        if name == "false":
            return False
        if name == "null":
            return None
        if self.at("("):
            self.next()
            args = []
            while not self.at(")"):
                args.append(self._term())
                if self.at(","):
                    self.next()
            self.expect(")")
            return Call(name, tuple(args))
        path: list = []
        while True:
            if self.at("."):
                self.next()
                kind, key = self.next()
                if kind != "ident":
                    raise RegoError("rego_parse_error: bad ref key")
                path.append(key)
            elif self.at("["):
                self.next()
                if self.peek() == ("ident", "_"):
                    self.next()
                    path.append(Wildcard())
                else:
                    inner = self._term()
                    path.append(inner if isinstance(
                        inner, (Var, Ref, str, int, float)) else inner)
                self.expect("]")
            else:
                break
        if not path and name not in ("input", "data"):
            return Var(name)
        return Ref(base=name, path=tuple(path))


def _unquote(s: str) -> str:
    return s[1:-1].replace('\\"', '"').replace("\\\\", "\\").replace(
        "\\n", "\n").replace("\\t", "\t")


def parse_module(src: str) -> Module:
    p = _Parser(_tokenize(src))
    mod = p.module()
    if not p.at(""):
        raise RegoError("rego_parse_error: trailing tokens")
    return mod


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------

_BUILTINS_OUT = {
    "trim": lambda s, cutset: s.strip(cutset),
    "split": lambda s, sep: list(s.split(sep)),
    "concat": lambda sep, arr: sep.join(arr),
    "lower": lambda s: s.lower(),
    "upper": lambda s: s.upper(),
    "count": lambda x: len(x),
}
_BUILTINS_BOOL = {
    "startswith": lambda s, p: s.startswith(p),
    "endswith": lambda s, p: s.endswith(p),
    "contains": lambda s, x: x in s,
}


class _Env(dict):
    """Binding environment; child() shares nothing (cheap copies —
    bodies are short)."""

    def child(self) -> "_Env":
        e = _Env(self)
        return e


class _QueryState:
    """Per-query evaluation state threaded through the evaluator in
    place of the bare `seen` frozenset: the cycle-guard set (immutable,
    grows down the call tree) plus the rule-value memo (shared across
    the whole query, never across queries/threads)."""

    __slots__ = ("seen", "memo")

    def __init__(self, seen: frozenset = frozenset(),
                 memo: dict | None = None):
        self.seen = seen
        self.memo: dict = {} if memo is None else memo

    def __contains__(self, key) -> bool:
        return key in self.seen

    def __or__(self, keys) -> "_QueryState":
        return _QueryState(self.seen | keys, self.memo)


class RegoEngine:
    """Compiled policy set: modules indexed by package path."""

    def __init__(self, sources: list[str]):
        self.modules: dict[str, Module] = {}
        for src in sources:
            if not src.strip():
                raise RegoError("empty policy module")
            mod = parse_module(src)
            if mod.package in self.modules:
                # merge rules of same package
                existing = self.modules[mod.package]
                for name, defs in mod.rules.items():
                    existing.rules.setdefault(name, []).extend(defs)
                existing.imports.update(mod.imports)
            else:
                self.modules[mod.package] = mod

    # -- public query --

    def query(self, method: str, input_doc: Mapping[str, Any]) -> Any:
        """Evaluate e.g. "data.mixerauthz.allow" against `input`.
        Returns the rule value (False from a default if no body
        succeeds; None if the rule is undefined)."""
        parts = method.split(".")
        if parts[0] != "data" or len(parts) < 3:
            raise RegoError(f"check method must be data.<pkg>.<rule>, "
                            f"got {method!r}")
        pkg, rule = ".".join(parts[1:-1]), parts[-1]
        # the memo is per-query local state carried on the threaded
        # `seen` object: the engine is shared across server threads, so
        # storing it on self would leak one request's memoized
        # decisions into another's
        return self._rule_value(pkg, rule, input_doc, _QueryState())

    # -- rule resolution --

    def _rule_value(self, pkg: str, name: str, input_doc, seen) -> Any:
        key = (pkg, name)
        if key in seen:
            raise RegoError(f"rego_recursion_error: {pkg}.{name}")
        memo = seen.memo if isinstance(seen, _QueryState) else None
        if memo is not None and key in memo:
            return memo[key]
        mod = self.modules.get(pkg)
        if mod is None:
            raise RegoError(f"unknown package {pkg!r}")
        defs = mod.rules.get(name)
        if defs is None:
            return None
        seen = seen | {key}
        default_value = None
        for d in defs:
            if d.default:
                default_value = self._ground(d.value)
        # OPA complete-rule semantics: EVERY successful evaluation —
        # across definitions AND across bindings within one body — must
        # agree on the value; disagreement is eval_conflict_error
        # (which the opa adapter fails closed on), never a silent
        # first-wins (ADVICE r2)
        result: Any = None
        have_result = False

        def absorb(value: Any) -> None:
            nonlocal result, have_result
            if have_result and value != result:
                raise RegoError(
                    f"eval_conflict_error: complete rule {pkg}.{name} "
                    f"defined with conflicting values")
            result, have_result = value, True

        for d in defs:
            if d.default:
                continue
            if not d.body:
                # constant: name = literal
                for env, value in self._eval_term(
                        d.value, _Env(), mod, input_doc, seen):
                    absorb(value)
                continue
            for env in self._eval_body(list(d.body), _Env(), mod,
                                       input_doc, seen):
                for env2, value in self._eval_term(d.value, env, mod,
                                                   input_doc, seen):
                    absorb(value)
        out = result if have_result else default_value
        if memo is not None:
            memo[key] = out
        return out

    @staticmethod
    def _ground(term: Any) -> Any:
        if isinstance(term, (bool, int, float, str)) or term is None:
            return term
        if isinstance(term, ArrayT):
            return [RegoEngine._ground(t) for t in term.items]
        raise RegoError("default value must be a literal")

    # -- body evaluation: generator of environments --

    def _eval_body(self, exprs: list, env: _Env, mod: Module,
                   input_doc, seen) -> Iterator[_Env]:
        if not exprs:
            yield env
            return
        head, rest = exprs[0], exprs[1:]
        for env2 in self._eval_expr(head, env, mod, input_doc, seen):
            yield from self._eval_body(rest, env2, mod, input_doc, seen)

    def _eval_expr(self, expr: Any, env: _Env, mod: Module,
                   input_doc, seen) -> Iterator[_Env]:
        if isinstance(expr, NotExpr):
            # negation as failure over the current bindings
            for _ in self._eval_expr(expr.expr, env, mod, input_doc,
                                     seen):
                return
            yield env
            return
        if isinstance(expr, Unify):
            for env2, lv in self._eval_term(expr.left, env, mod,
                                            input_doc, seen,
                                            allow_unbound=True):
                for env3, rv in self._eval_term(expr.right, env2, mod,
                                                input_doc, seen,
                                                allow_unbound=True):
                    env4 = self._unify(lv, rv, env3)
                    if env4 is not None:
                        yield env4
            return
        if isinstance(expr, Call):
            yield from self._eval_call(expr, env, mod, input_doc, seen)
            return
        # bare term: truthy check (e.g. `service_graph.allow`,
        # `is_hr`)
        for env2, value in self._eval_term(expr, env, mod, input_doc,
                                           seen):
            if value is not None and value is not False:
                yield env2
        return

    def _eval_call(self, call: Call, env: _Env, mod: Module,
                   input_doc, seen) -> Iterator[_Env]:
        if call.name in _BUILTINS_BOOL:
            fn = _BUILTINS_BOOL[call.name]
            args = []
            for t in call.args:
                got = next(self._eval_term(t, env, mod, input_doc,
                                           seen), None)
                if got is None:
                    return
                env, v = got
                args.append(v)
            try:
                if fn(*args):
                    yield env
            except TypeError as exc:
                raise RegoError(f"{call.name}: {exc}") from exc
            return
        if call.name in _BUILTINS_OUT:
            fn = _BUILTINS_OUT[call.name]
            *ins, out = call.args
            args = []
            for t in ins:
                got = next(self._eval_term(t, env, mod, input_doc,
                                           seen), None)
                if got is None:
                    return
                env, v = got
                args.append(v)
            try:
                result = fn(*args)
            except TypeError as exc:
                raise RegoError(f"{call.name}: {exc}") from exc
            env2 = self._unify_out(out, result, env)
            if env2 is not None:
                yield env2
            return
        raise RegoError(f"unknown builtin {call.name!r}")

    def _unify_out(self, term: Any, value: Any, env: _Env) -> _Env | None:
        if isinstance(term, Var):
            if term.name in env:
                return env if env[term.name] == value else None
            env2 = env.child()
            env2[term.name] = value
            return env2
        got = term
        return env if got == value else None

    # -- term evaluation: generator of (env, value) --

    def _eval_term(self, term: Any, env: _Env, mod: Module, input_doc,
                   seen, allow_unbound: bool = False
                   ) -> Iterator[tuple[_Env, Any]]:
        if isinstance(term, (bool, int, float, str)) or term is None:
            yield env, term
            return
        if isinstance(term, Var):
            if term.name in env:
                yield env, env[term.name]
            elif term.name in mod.rules or term.name in mod.imports:
                # a bare ident can only be disambiguated here: an
                # unbound name that names a rule (or package alias) is
                # a rule reference, not a variable
                yield from self._eval_ref(Ref(base=term.name, path=()),
                                          env, mod, input_doc, seen)
            elif allow_unbound:
                yield env, term        # unbound var flows to unify
            return
        if isinstance(term, ArrayT):
            yield from self._eval_seq(list(term.items), [], env, mod,
                                      input_doc, seen, allow_unbound)
            return
        if isinstance(term, ObjectT):
            yield from self._eval_obj(list(term.items), {}, env, mod,
                                      input_doc, seen)
            return
        if isinstance(term, SetT):
            for e, items in self._eval_seq(list(term.items), [], env,
                                           mod, input_doc, seen, False):
                yield e, list(items)
            return
        if isinstance(term, Ref):
            yield from self._eval_ref(term, env, mod, input_doc, seen)
            return
        if isinstance(term, Call):
            raise RegoError("call terms only valid as expressions")
        raise RegoError(f"cannot evaluate {term!r}")

    def _eval_seq(self, items: list, acc: list, env: _Env, mod, input_doc,
                  seen, allow_unbound) -> Iterator[tuple[_Env, list]]:
        if not items:
            yield env, list(acc)
            return
        head, rest = items[0], items[1:]
        for env2, v in self._eval_term(head, env, mod, input_doc, seen,
                                       allow_unbound):
            yield from self._eval_seq(rest, acc + [v], env2, mod,
                                      input_doc, seen, allow_unbound)

    def _eval_obj(self, items: list, acc: dict, env: _Env, mod,
                  input_doc, seen) -> Iterator[tuple[_Env, dict]]:
        if not items:
            yield env, dict(acc)
            return
        (kt, vt), rest = items[0], items[1:]
        for env2, k in self._eval_term(kt, env, mod, input_doc, seen):
            for env3, v in self._eval_term(vt, env2, mod, input_doc,
                                           seen):
                yield from self._eval_obj(rest, {**acc, k: v}, env3,
                                          mod, input_doc, seen)

    def _eval_ref(self, ref: Ref, env: _Env, mod: Module, input_doc,
                  seen) -> Iterator[tuple[_Env, Any]]:
        # resolve the base document
        if ref.base == "input":
            roots: list[tuple[_Env, Any]] = [(env, input_doc)]
            path = list(ref.path)
        elif ref.base == "data":
            # data.<pkg...>.<rule>[...]: the longest string prefix
            # whose tail names a rule of the prefix package wins
            path = list(ref.path)
            str_prefix = []
            for el in path:
                if isinstance(el, str):
                    str_prefix.append(el)
                else:
                    break
            value = None
            for cut in range(len(str_prefix), 0, -1):
                pkg = ".".join(str_prefix[:cut - 1])
                m = self.modules.get(pkg)
                if m is not None and str_prefix[cut - 1] in m.rules:
                    value = self._rule_value(pkg, str_prefix[cut - 1],
                                             input_doc, seen)
                    path = path[cut:]
                    break
            else:
                return
            roots = [(env, value)]
        elif ref.base in mod.imports:
            # imported package alias: alias.rule[...]
            pkg = mod.imports[ref.base]
            if not ref.path or not isinstance(ref.path[0], str):
                return
            value = self._rule_value(pkg, ref.path[0], input_doc, seen)
            roots = [(env, value)]
            path = list(ref.path[1:])
        elif ref.base in env:
            roots = [(env, env[ref.base])]
            path = list(ref.path)
        elif ref.base in mod.rules:
            value = self._rule_value(mod.package, ref.base, input_doc,
                                     seen)
            roots = [(env, value)]
            path = list(ref.path)
        else:
            return

        def walk(env_in: _Env, doc: Any, remaining: list
                 ) -> Iterator[tuple[_Env, Any]]:
            if doc is None:
                return
            if not remaining:
                yield env_in, doc
                return
            el, rest = remaining[0], remaining[1:]
            if isinstance(el, Wildcard):
                for item in _iterate(doc):
                    yield from walk(env_in, item, rest)
                return
            if isinstance(el, Var):
                if el.name in env_in:
                    yield from walk(env_in, _index(doc, env_in[el.name]),
                                    rest)
                    return
                for key, item in _enumerate(doc):
                    env2 = env_in.child()
                    env2[el.name] = key
                    yield from walk(env2, item, rest)
                return
            if isinstance(el, Ref):
                for env2, key in self._eval_ref(el, env_in, mod,
                                                input_doc, seen):
                    yield from walk(env2, _index(doc, key), rest)
                return
            yield from walk(env_in, _index(doc, el), rest)

        for env_in, doc in roots:
            yield from walk(env_in, doc, path)

    # -- unification --

    def _unify(self, a: Any, b: Any, env: _Env) -> _Env | None:
        if isinstance(a, Var):
            if isinstance(b, Var):
                return None if a.name != b.name else env
            env2 = env.child()
            env2[a.name] = b
            return env2
        if isinstance(b, Var):
            env2 = env.child()
            env2[b.name] = a
            return env2
        if isinstance(a, list) and isinstance(b, list):
            if len(a) != len(b):
                return None
            for x, y in zip(a, b):
                env = self._unify(x, y, env)   # type: ignore[assignment]
                if env is None:
                    return None
            return env
        # scalar / dict equality (bool vs int: Rego types differ)
        if isinstance(a, bool) != isinstance(b, bool):
            return None
        return env if a == b else None


def _iterate(doc: Any) -> Iterator[Any]:
    if isinstance(doc, list):
        yield from doc
    elif isinstance(doc, Mapping):
        yield from doc.values()


def _enumerate(doc: Any) -> Iterator[tuple[Any, Any]]:
    if isinstance(doc, list):
        yield from enumerate(doc)
    elif isinstance(doc, Mapping):
        yield from doc.items()


def _index(doc: Any, key: Any) -> Any:
    try:
        if isinstance(doc, list):
            if isinstance(key, bool) or not isinstance(key, int):
                return None
            return doc[key] if 0 <= key < len(doc) else None
        if isinstance(doc, Mapping):
            return doc.get(key)
    except (TypeError, KeyError, IndexError):
        return None
    return None
