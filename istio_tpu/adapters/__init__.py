"""Adapter SDK + built-in adapter inventory.

Role of the reference's mixer/pkg/adapter (SDK) + mixer/adapter/*
(inventory, SURVEY.md §2.5). An adapter declares `Info` (name, supported
templates, builder factory, default config), a `Builder` validated and
built once per distinct (adapter, config) signature, and a `Handler`
receiving template instances per request.

Inventory parity with the reference's 14 adapters: denier, list,
memquota, rbac, noop, stdio, prometheus, statsd, fluentd, opa,
kubernetesenv, circonus, stackdriver, servicecontrol — all with their
real processing logic. The three SaaS-backed ones (circonus,
stackdriver, servicecontrol) implement the full aggregation/translation
pipelines natively; only the final network hop is an injectable
`transport` seam (this image has zero egress).
"""
from istio_tpu.adapters.sdk import (AdapterError, AdapterUnavailable,
                                    Builder, CheckResult, Handler, Info,
                                    QuotaArgs, QuotaResult)
from istio_tpu.adapters.registry import adapter_registry

__all__ = ["Info", "Builder", "Handler", "CheckResult", "QuotaArgs",
           "QuotaResult", "AdapterError", "AdapterUnavailable",
           "adapter_registry"]
