"""opa — policy-engine authorization adapter.

Reference: mixer/adapter/opa (1,470 LoC) embeds the Open Policy Agent
Rego evaluator and asks it `checkMethod` over the authorization
instance. Rego itself is a Go library with no Python/TPU equivalent in
this image, so this adapter evaluates policies written in the
framework's OWN expression language over the flattened authorization
instance — the same attribute-expression dialect used everywhere else
(a deliberate TPU-native reinterpretation: policies stay compilable to
the device ruleset path). A policy is a list of allow rules; any rule
evaluating true allows the action (OPA-style default-deny).

Instance fields are exposed as attributes:
  subject.user, subject.groups, subject.properties[...],
  action.namespace, action.service, action.method, action.path,
  action.properties[...]
"""
from __future__ import annotations

from typing import Any, Mapping

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (AdapterError, Builder, CheckResult, Env,
                                    Handler, Info)
from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.attribute.types import ValueType as V
from istio_tpu.expr.checker import AttributeDescriptorFinder, TypeError_
from istio_tpu.expr.oracle import EvalError, OracleProgram
from istio_tpu.expr.parser import ParseError
from istio_tpu.models.policy_engine import OK, PERMISSION_DENIED

_POLICY_MANIFEST = {
    "subject.user": V.STRING, "subject.groups": V.STRING,
    "subject.properties": V.STRING_MAP,
    "action.namespace": V.STRING, "action.service": V.STRING,
    "action.method": V.STRING, "action.path": V.STRING,
    "action.properties": V.STRING_MAP,
}


def _flatten(instance: Mapping[str, Any]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for part in ("subject", "action"):
        sub = instance.get(part, {}) or {}
        for k, v in sub.items():
            if k == "properties":
                out[f"{part}.properties"] = {
                    str(pk): str(pv) for pk, pv in (v or {}).items()}
            else:
                out[f"{part}.{k}"] = v
    return out


class OpaHandler(Handler):
    def __init__(self, config: Mapping[str, Any]):
        finder = AttributeDescriptorFinder(_POLICY_MANIFEST)
        self.fail_close = bool(config.get("fail_close", True))
        self._rules: list[OracleProgram] = []
        for text in config.get("policies", ()):
            self._rules.append(OracleProgram(text, finder))

    def handle_check(self, template: str,
                     instance: Mapping[str, Any]) -> CheckResult:
        bag = bag_from_mapping(_flatten(instance))
        for prog in self._rules:
            try:
                if prog.evaluate(bag):
                    return CheckResult(status_code=OK)
            except EvalError:
                if self.fail_close:
                    continue   # treat errored rule as no-allow
                return CheckResult(status_code=OK,
                                   status_message="fail-open")
        return CheckResult(status_code=PERMISSION_DENIED,
                           status_message="opa: no policy allowed")


class OpaBuilder(Builder):
    def validate(self) -> list[str]:
        errs = []
        finder = AttributeDescriptorFinder(_POLICY_MANIFEST)
        for text in self.config.get("policies", ()):
            try:
                prog = OracleProgram(text, finder)
                if prog.result_type != V.BOOL:
                    errs.append(f"policy {text!r} is not boolean")
            except (ParseError, TypeError_) as exc:
                errs.append(f"policy {text!r}: {exc}")
        return errs

    def build(self) -> Handler:
        return OpaHandler(self.config)


INFO = adapter_registry.register(Info(
    name="opa",
    supported_templates=("authorization",),
    builder=OpaBuilder,
    description="default-deny policy authorization (expression-language "
                "policies; Rego not embedded)"))
