"""opa — policy-engine authorization adapter.

Reference: mixer/adapter/opa (1,470 LoC) embeds the Open Policy Agent
Rego evaluator and asks it `checkMethod` over an `input` document of
the authorization instance (opa.go:217-256). Two policy dialects:

  * **Rego** (reference-compatible): policies containing a `package`
    declaration compile through the native Rego-subset evaluator
    (adapters/rego.py) — the reference's own test policy corpus
    (bucket-admins, service-graph + org-chart) runs unmodified. Config
    keys follow the reference: `policies` (modules), `check_method`
    ("data.<pkg>.<rule>"), `fail_close`.
  * **Expression language** (TPU-native reinterpretation): policies
    without a `package` declaration evaluate in the framework's own
    attribute-expression dialect over the flattened instance — these
    stay compilable to the device ruleset path. Any rule evaluating
    true allows (default-deny).

Instance fields are exposed to expression policies as attributes
(subject.user, action.method, action.properties[...], ...) and to
Rego as the reference's input document {subject: {...},
action: {...}}.
"""
from __future__ import annotations

from typing import Any, Mapping

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (AdapterError, Builder, CheckResult, Env,
                                    Handler, Info)
from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.attribute.types import ValueType as V
from istio_tpu.expr.checker import AttributeDescriptorFinder, TypeError_
from istio_tpu.expr.oracle import EvalError, OracleProgram
from istio_tpu.expr.parser import ParseError
from istio_tpu.models.policy_engine import OK, PERMISSION_DENIED

_POLICY_MANIFEST = {
    "subject.user": V.STRING, "subject.groups": V.STRING,
    "subject.properties": V.STRING_MAP,
    "action.namespace": V.STRING, "action.service": V.STRING,
    "action.method": V.STRING, "action.path": V.STRING,
    "action.properties": V.STRING_MAP,
}


def _flatten(instance: Mapping[str, Any]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for part in ("subject", "action"):
        sub = instance.get(part, {}) or {}
        for k, v in sub.items():
            if k == "properties":
                out[f"{part}.properties"] = {
                    str(pk): str(pv) for pk, pv in (v or {}).items()}
            else:
                out[f"{part}.{k}"] = v
    return out


def _is_rego(policies) -> bool:
    """Rego modules carry a package declaration (possibly after
    comments); expression-language policies never contain one."""
    import re
    return any(re.search(r"^\s*package\s", p, re.M) for p in policies)


class OpaHandler(Handler):
    def __init__(self, config: Mapping[str, Any]):
        from istio_tpu.adapters.rego import RegoEngine, RegoError
        self.fail_close = bool(config.get("fail_close", True))
        policies = list(config.get("policies", ()))
        self._rego = None
        self._rego_error: str | None = None
        self._rules: list[OracleProgram] = []
        if _is_rego(policies):
            self.check_method = str(config.get("check_method",
                                               "data.mixerauthz.allow"))
            try:
                self._rego = RegoEngine(policies)
            except RegoError as exc:
                # the reference keeps serving with hasConfigError set;
                # every request then routes through handleFailClose
                # (opa.go:205-221 — denied under fail_close, allowed
                # under explicit fail-open)
                self._rego_error = str(exc)
        else:
            finder = AttributeDescriptorFinder(_POLICY_MANIFEST)
            for text in policies:
                self._rules.append(OracleProgram(text, finder))

    def _fail(self, message: str) -> CheckResult:
        if self.fail_close:
            return CheckResult(status_code=PERMISSION_DENIED,
                               status_message=message)
        return CheckResult(status_code=OK, status_message="fail-open")

    def handle_check(self, template: str,
                     instance: Mapping[str, Any]) -> CheckResult:
        if self._rego is not None or self._rego_error is not None:
            return self._check_rego(instance)
        bag = bag_from_mapping(_flatten(instance))
        for prog in self._rules:
            try:
                if prog.evaluate(bag):
                    return CheckResult(status_code=OK)
            except EvalError:
                if self.fail_close:
                    continue   # treat errored rule as no-allow
                return CheckResult(status_code=OK,
                                   status_message="fail-open")
        return CheckResult(status_code=PERMISSION_DENIED,
                           status_message="opa: no policy allowed")

    def _check_rego(self, instance: Mapping[str, Any]) -> CheckResult:
        """opa.go HandleAuthorization: evaluate checkMethod over
        input={action, subject}; non-bool/undefined → fail-close."""
        from istio_tpu.adapters.rego import RegoError
        if self._rego_error is not None:
            # config error → handleFailClose (opa.go:205-215): denied
            # under fail_close (the default), allowed when the
            # operator explicitly configured fail-open
            return self._fail("opa: request was rejected")
        input_doc = {
            "subject": dict(instance.get("subject") or {}),
            "action": dict(instance.get("action") or {}),
        }
        try:
            result = self._rego.query(self.check_method, input_doc)
        except RegoError as exc:
            return self._fail(f"opa: request was rejected. err: {exc}")
        if not isinstance(result, bool):
            return self._fail("opa: request was rejected")
        if not result:
            return CheckResult(status_code=PERMISSION_DENIED,
                               status_message="opa: request was rejected")
        return CheckResult(status_code=OK)


class OpaBuilder(Builder):
    def validate(self) -> list[str]:
        errs: list[str] = []
        policies = list(self.config.get("policies", ()))
        if _is_rego(policies):
            from istio_tpu.adapters.rego import RegoEngine, RegoError
            engine = None
            try:
                engine = RegoEngine(policies)
            except RegoError as exc:
                errs.append(f"Policy: {exc}")
            method = str(self.config.get("check_method",
                                         "data.mixerauthz.allow"))
            parts = method.split(".")
            if parts[0] != "data" or len(parts) < 3:
                errs.append(f"check_method: {method!r} must be "
                            "data.<package>.<rule>")
            elif engine is not None:
                # a typo'd package/rule would otherwise only surface
                # as a runtime deny on every request
                pkg, rule = ".".join(parts[1:-1]), parts[-1]
                mod = engine.modules.get(pkg)
                if mod is None:
                    errs.append(f"check_method: unknown package "
                                f"{pkg!r}")
                elif rule not in mod.rules:
                    errs.append(f"check_method: package {pkg!r} has "
                                f"no rule {rule!r}")
            return errs
        finder = AttributeDescriptorFinder(_POLICY_MANIFEST)
        for text in policies:
            try:
                prog = OracleProgram(text, finder)
                if prog.result_type != V.BOOL:
                    errs.append(f"policy {text!r} is not boolean")
            except (ParseError, TypeError_) as exc:
                errs.append(f"policy {text!r}: {exc}")
        return errs

    def build(self) -> Handler:
        return OpaHandler(self.config)


INFO = adapter_registry.register(Info(
    name="opa",
    supported_templates=("authorization",),
    builder=OpaBuilder,
    description="policy authorization: native Rego-subset evaluator "
                "(reference corpus compatible) or expression-language "
                "policies"))
