"""list — white/blacklist membership checks for listentry instances.

Reference: mixer/adapter/list/list.go (1,905 LoC; HandleListEntry :68,
list refresh :115-247). Entry types match the reference's
ListEntryType: STRINGS, CASE_INSENSITIVE_STRINGS, IP_ADDRESSES
(entries are CIDRs or addresses), REGEX. Lists come from `overrides`
config plus an optional refreshing provider; this build has zero
network egress, so `provider_url` supports file:// URLs and a
`provider` callable injection seam (the reference's URL-fetch loop with
TTL refresh is reproduced for those sources).
"""
from __future__ import annotations

import ipaddress
import re
import threading
from typing import Any, Callable, Mapping
from urllib.parse import urlparse

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (AdapterError, Builder, CheckResult, Env,
                                    Handler, Info)
from istio_tpu.models.policy_engine import NOT_FOUND, OK, PERMISSION_DENIED


class ListHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env):
        self.entry_type = config.get("entry_type", "STRINGS")
        self.blacklist = bool(config.get("blacklist", False))
        self.caching_ttl_s = float(config.get("caching_ttl_s", 300.0))
        self.caching_use_count = int(config.get("caching_use_count", 10_000))
        self._env = env
        self._lock = threading.Lock()
        self._provider: Callable[[], list[str]] | None = \
            config.get("provider")
        url = config.get("provider_url", "")
        if url and self._provider is None:
            parsed = urlparse(url)
            if parsed.scheme != "file":
                raise AdapterError(
                    "only file:// provider_url supported (no egress); "
                    "inject `provider` for other sources")
            path = parsed.path
            self._provider = lambda: [
                ln.strip() for ln in open(path, encoding="utf-8")
                if ln.strip()]
        self._base_overrides = tuple(config.get("overrides", ()))
        # refresh bookkeeping (surfaced via refresh_stats() →
        # /debug/executor): a provider that starts failing keeps the
        # LAST GOOD list serving — the counters and last-refresh age
        # are the only signal, so they must exist
        self.refresh_failures = 0
        self.last_refresh_wall: float | None = None
        self.last_refresh_error: str | None = None
        self._set_entries(list(self._base_overrides) +
                          (self._provider() if self._provider else []))
        if self._provider is not None:
            import time
            self.last_refresh_wall = time.time()
        self.refresh_interval_s = float(
            config.get("refresh_interval_s", 60.0))

    def refresh(self) -> None:
        """Re-pull the provider list (the reference's TTL refresh loop
        body, list.go:115-247; driven by the adapter executor's
        maintenance lane). A failing provider NEVER clobbers the last
        good list: the pull happens before _set_entries, the failure
        is recorded (refresh_failures / last_refresh_error) and
        re-raised so the maintenance runner's counters move."""
        import time
        if self._provider is None:
            return
        try:
            entries = self._provider()
        except Exception as exc:
            with self._lock:
                self.refresh_failures += 1
                self.last_refresh_error = \
                    f"{type(exc).__name__}: {exc}"
            raise
        self._set_entries(list(self._base_overrides) + list(entries))
        with self._lock:
            self.last_refresh_wall = time.time()
            self.last_refresh_error = None

    def refresh_stats(self) -> dict:
        """Provider freshness for /debug/executor."""
        import time
        with self._lock:
            last = self.last_refresh_wall
            return {
                "provider": self._provider is not None,
                "entries": len(self.config_overrides),
                "refresh_failures": self.refresh_failures,
                "last_refresh_age_s":
                    round(time.time() - last, 3)
                    if last is not None else None,
                "last_refresh_error": self.last_refresh_error,
            }

    def _set_entries(self, entries: list[str]) -> None:
        et = self.entry_type
        with self._lock:
            self.config_overrides = tuple(entries)
            if et == "STRINGS":
                self._strings = frozenset(entries)
            elif et == "CASE_INSENSITIVE_STRINGS":
                self._strings = frozenset(e.lower() for e in entries)
            elif et == "IP_ADDRESSES":
                self._nets = [ipaddress.ip_network(e, strict=False)
                              for e in entries]
            elif et == "REGEX":
                self._regexes = [re.compile(e) for e in entries]
            else:
                raise AdapterError(f"unknown entry_type {et}")

    def _member(self, value: str) -> bool:
        et = self.entry_type
        with self._lock:
            if et == "STRINGS":
                return value in self._strings
            if et == "CASE_INSENSITIVE_STRINGS":
                return value.lower() in self._strings
            if et == "IP_ADDRESSES":
                try:
                    addr = ipaddress.ip_address(value)
                except ValueError:
                    return False
                return any(addr in net for net in self._nets)
            return any(r.search(value) for r in self._regexes)

    def handle_check(self, template: str,
                     instance: Mapping[str, Any]) -> CheckResult:
        value = instance.get("value")
        if isinstance(value, bytes):
            value = str(ipaddress.ip_address(
                value[-4:] if len(value) == 16 and
                value[:12] == b"\x00" * 10 + b"\xff\xff" else value))
        member = self._member(str(value))
        ok = member != self.blacklist
        return CheckResult(
            status_code=OK if ok else (
                PERMISSION_DENIED if self.blacklist else NOT_FOUND),
            status_message="" if ok else f"{value} rejected",
            valid_duration_s=self.caching_ttl_s,
            valid_use_count=self.caching_use_count)


class ListBuilder(Builder):
    def validate(self) -> list[str]:
        errs = []
        et = self.config.get("entry_type", "STRINGS")
        if et not in ("STRINGS", "CASE_INSENSITIVE_STRINGS",
                      "IP_ADDRESSES", "REGEX"):
            errs.append(f"unknown entry_type {et}")
        if et == "REGEX":
            for e in self.config.get("overrides", ()):
                try:
                    re.compile(e)
                except re.error as exc:
                    errs.append(f"bad regex {e!r}: {exc}")
        if et == "IP_ADDRESSES":
            for e in self.config.get("overrides", ()):
                try:
                    ipaddress.ip_network(e, strict=False)
                except ValueError as exc:
                    errs.append(f"bad CIDR {e!r}: {exc}")
        return errs

    def build(self) -> Handler:
        return ListHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="list",
    supported_templates=("listentry",),
    builder=ListBuilder,
    description="white/blacklist over strings/IP-nets/regex with "
                "refreshable providers"))
