"""Gated stubs for SaaS-backed adapters.

Reference adapters whose backends are external Google/Circonus services
(mixer/adapter/{circonus,stackdriver,servicecontrol}, ~12,400 LoC of
mostly API-client plumbing). This build has zero network egress, so
these validate config and register in the inventory — keeping configs
portable — but their handlers raise AdapterUnavailable until an
exporter seam is injected (`transport` config key).
"""
from __future__ import annotations

from typing import Any, Callable, Mapping, Sequence

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (AdapterUnavailable, Builder, CheckResult,
                                    Env, Handler, Info, QuotaArgs,
                                    QuotaResult)


class _TransportHandler(Handler):
    """Forwards instances to an injected `transport` callable; without
    one, every call raises AdapterUnavailable."""

    def __init__(self, name: str, config: Mapping[str, Any]):
        self._name = name
        self._transport: Callable[[str, str, Any], Any] | None = \
            config.get("transport")

    def _send(self, kind: str, template: str, payload: Any) -> Any:
        if self._transport is None:
            raise AdapterUnavailable(
                f"{self._name}: SaaS backend not wired (inject `transport`)")
        return self._transport(kind, template, payload)

    def handle_check(self, template: str,
                     instance: Mapping[str, Any]) -> CheckResult:
        result = self._send("check", template, instance)
        return result if isinstance(result, CheckResult) else CheckResult()

    def handle_report(self, template: str,
                      instances: Sequence[Mapping[str, Any]]) -> None:
        self._send("report", template, instances)

    def handle_quota(self, template: str, instance: Mapping[str, Any],
                     args: QuotaArgs) -> QuotaResult:
        result = self._send("quota", template, (instance, args))
        return result if isinstance(result, QuotaResult) else \
            QuotaResult(granted_amount=args.quota_amount)


def _stub(name: str, templates: tuple[str, ...], desc: str) -> Info:
    class _B(Builder):
        def build(self) -> Handler:
            return _TransportHandler(name, self.config)
    _B.__name__ = f"{name.capitalize()}Builder"
    return adapter_registry.register(Info(
        name=name, supported_templates=templates, builder=_B,
        description=desc))


CIRCONUS = _stub("circonus", ("metric",),
                 "metrics to circonus (gated: needs transport)")
STACKDRIVER = _stub("stackdriver", ("metric", "logentry", "tracespan"),
                    "metrics/logs/traces to GCP (gated: needs transport)")
SERVICECONTROL = _stub("servicecontrol",
                       ("metric", "logentry", "quota", "apikey"),
                       "GCP service control (gated: needs transport)")
