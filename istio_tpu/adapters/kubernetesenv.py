"""kubernetesenv — the ATTRIBUTE_GENERATOR adapter: pod metadata.

Reference: mixer/adapter/kubernetesenv (2,613 LoC): a pod-informer
cache keyed by pod UID/IP fills source/destination workload attributes
(pod name, namespace, labels, service account, host IP) during
Preprocess (dispatcher.go:285 → ProcessGenAttrs). The pod cache is a
pluggable `PodSource`: `StaticPodSource` (dict/YAML-file backed) for
hermetic runs, and `InformerPodSource` — a live list+watch cache over
the in-process kube API (istio_tpu/kube/fake.py), the analog of the
reference's cacheController (kubernetesenv/cache.go) — when the
adapter runs against a cluster. The attribute-production contract is
identical for both.
"""
from __future__ import annotations

import threading
from typing import Any, Mapping

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import Builder, Env, Handler, Info

# output attribute suffixes produced per prefix (source/destination/origin)
_OUTPUTS = ("pod_name", "namespace", "labels", "service_account_name",
            "pod_ip", "host_ip", "service")


class StaticPodSource:
    """Pod metadata lookup by `uid` (kubernetes://<pod>.<ns>) or ip."""

    def __init__(self, pods: Mapping[str, Mapping[str, Any]] | None = None):
        self._lock = threading.Lock()
        self._pods = dict(pods or {})
        self._by_ip = {p["pod_ip"]: p for p in self._pods.values()
                       if "pod_ip" in p}

    def update(self, pods: Mapping[str, Mapping[str, Any]]) -> None:
        with self._lock:
            self._pods = dict(pods)
            self._by_ip = {p["pod_ip"]: p for p in self._pods.values()
                           if "pod_ip" in p}

    def by_uid(self, uid: str) -> Mapping[str, Any] | None:
        with self._lock:
            return self._pods.get(uid)

    def by_ip(self, ip: str) -> Mapping[str, Any] | None:
        with self._lock:
            return self._by_ip.get(ip)


class InformerPodSource:
    """Live pod cache over the in-process kube API server.

    kubernetesenv/cache.go's controller role: list+watch Pods, keep
    uid- and ip-keyed indexes current, and answer lookups from the
    local cache (never the API server) on the request path. The
    canonical workload "service" attribute is derived from the `app`
    label (kubernetesenv's canonical-service resolution order:
    explicit annotation → app label → pod name prefix).
    """

    def __init__(self, cluster) -> None:
        self._lock = threading.Lock()
        self._pods: dict[str, dict[str, Any]] = {}     # "<name>.<ns>" →
        self._by_ip: dict[str, dict[str, Any]] = {}
        self._cluster = cluster
        cluster.watch("Pod", self._on_event)

    def close(self) -> None:
        """Deregister from the cluster — handlers are rebuilt per
        config signature and stale informers must not keep indexing."""
        self._cluster.unwatch("Pod", self._on_event)

    @staticmethod
    def _to_entry(obj: Mapping[str, Any]) -> dict[str, Any]:
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        status = obj.get("status") or {}
        labels = dict(meta.get("labels") or {})
        entry: dict[str, Any] = {
            "pod_name": str(meta.get("name", "")),
            "namespace": str(meta.get("namespace", "")),
            "labels": labels,
        }
        if spec.get("serviceAccountName"):
            entry["service_account_name"] = str(spec["serviceAccountName"])
        if status.get("podIP"):
            entry["pod_ip"] = str(status["podIP"])
        if status.get("hostIP"):
            entry["host_ip"] = str(status["hostIP"])
        service = labels.get("app") or str(meta.get("name", ""))
        if service:
            entry["service"] = str(service)
        return entry

    def _on_event(self, ev) -> None:
        meta = ev.obj.get("metadata") or {}
        uid = f"{meta.get('name', '')}.{meta.get('namespace', '')}"
        with self._lock:
            old = self._pods.pop(uid, None)
            if old is not None and "pod_ip" in old:
                self._by_ip.pop(old["pod_ip"], None)
            if ev.type != "DELETED":
                entry = self._to_entry(ev.obj)
                self._pods[uid] = entry
                if "pod_ip" in entry:
                    self._by_ip[entry["pod_ip"]] = entry

    def by_uid(self, uid: str) -> Mapping[str, Any] | None:
        with self._lock:
            return self._pods.get(uid)

    def by_ip(self, ip: str) -> Mapping[str, Any] | None:
        with self._lock:
            return self._by_ip.get(ip)


class KubernetesEnvHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env):
        if config.get("pod_source") is not None:
            self.source = config["pod_source"]
        elif config.get("cluster") is not None:
            self.source = InformerPodSource(config["cluster"])
        else:
            self.source = StaticPodSource(config.get("pods", {}))

    def close(self) -> None:
        source_close = getattr(self.source, "close", None)
        if source_close is not None:
            source_close()

    def generate_attributes(self, template: str,
                            instance: Mapping[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for prefix in ("source", "destination", "origin"):
            pod = None
            uid = instance.get(f"{prefix}_uid")
            if uid:
                pod = self.source.by_uid(str(uid).removeprefix(
                    "kubernetes://"))
            if pod is None:
                ip = instance.get(f"{prefix}_ip")
                if ip is not None:
                    import ipaddress
                    if isinstance(ip, bytes):
                        ip = str(ipaddress.ip_address(
                            ip[-4:] if len(ip) == 16 and
                            ip[:12] == b"\x00" * 10 + b"\xff\xff" else ip))
                    pod = self.source.by_ip(str(ip))
            if pod is None:
                continue
            for key in _OUTPUTS:
                if key in pod:
                    out[f"{prefix}_{key}"] = pod[key]
        return out


class KubernetesEnvBuilder(Builder):
    def build(self) -> Handler:
        return KubernetesEnvHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="kubernetesenv",
    supported_templates=("kubernetes",),
    builder=KubernetesEnvBuilder,
    description="pod-metadata attribute generator (APA)"))
