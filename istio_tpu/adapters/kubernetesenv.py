"""kubernetesenv — the ATTRIBUTE_GENERATOR adapter: pod metadata.

Reference: mixer/adapter/kubernetesenv (2,613 LoC): a pod-informer
cache keyed by pod UID/IP fills source/destination workload attributes
(pod name, namespace, labels, service account, host IP) during
Preprocess (dispatcher.go:285 → ProcessGenAttrs). This build runs with
no k8s API server, so the pod cache is a pluggable `PodSource`:
`StaticPodSource` (dict/YAML-file backed, used by tests and hermetic
runs) with the informer variant left as an integration seam — the
attribute-production contract is identical.
"""
from __future__ import annotations

import threading
from typing import Any, Mapping

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import Builder, Env, Handler, Info

# output attribute suffixes produced per prefix (source/destination/origin)
_OUTPUTS = ("pod_name", "namespace", "labels", "service_account_name",
            "pod_ip", "host_ip", "service")


class StaticPodSource:
    """Pod metadata lookup by `uid` (kubernetes://<pod>.<ns>) or ip."""

    def __init__(self, pods: Mapping[str, Mapping[str, Any]] | None = None):
        self._lock = threading.Lock()
        self._pods = dict(pods or {})
        self._by_ip = {p["pod_ip"]: p for p in self._pods.values()
                       if "pod_ip" in p}

    def update(self, pods: Mapping[str, Mapping[str, Any]]) -> None:
        with self._lock:
            self._pods = dict(pods)
            self._by_ip = {p["pod_ip"]: p for p in self._pods.values()
                           if "pod_ip" in p}

    def by_uid(self, uid: str) -> Mapping[str, Any] | None:
        with self._lock:
            return self._pods.get(uid)

    def by_ip(self, ip: str) -> Mapping[str, Any] | None:
        with self._lock:
            return self._by_ip.get(ip)


class KubernetesEnvHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env):
        self.source: StaticPodSource = config.get("pod_source") \
            or StaticPodSource(config.get("pods", {}))

    def generate_attributes(self, template: str,
                            instance: Mapping[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for prefix in ("source", "destination", "origin"):
            pod = None
            uid = instance.get(f"{prefix}_uid")
            if uid:
                pod = self.source.by_uid(str(uid).removeprefix(
                    "kubernetes://"))
            if pod is None:
                ip = instance.get(f"{prefix}_ip")
                if ip is not None:
                    import ipaddress
                    if isinstance(ip, bytes):
                        ip = str(ipaddress.ip_address(
                            ip[-4:] if len(ip) == 16 and
                            ip[:12] == b"\x00" * 10 + b"\xff\xff" else ip))
                    pod = self.source.by_ip(str(ip))
            if pod is None:
                continue
            for key in _OUTPUTS:
                if key in pod:
                    out[f"{prefix}_{key}"] = pod[key]
        return out


class KubernetesEnvBuilder(Builder):
    def build(self) -> Handler:
        return KubernetesEnvHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="kubernetesenv",
    supported_templates=("kubernetes",),
    builder=KubernetesEnvBuilder,
    description="pod-metadata attribute generator (APA)"))
