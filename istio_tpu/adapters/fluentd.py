"""fluentd — logentry instances to a fluentd daemon.

Reference: mixer/adapter/fluentd (796 LoC, fluent-logger-golang): sends
[tag, timestamp, record] events with the Fluentd Forward protocol
(msgpack over TCP). No msgpack library is baked into this image, so a
minimal encoder for the value shapes we emit (str/bytes/int/float/bool/
None/map/array/datetime→float secs) is included; it implements the
msgpack spec subset the forward protocol needs.
"""
from __future__ import annotations

import datetime
import socket
import struct
import threading
from typing import Any, Mapping, Sequence

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import Builder, Env, Handler, Info


def msgpack_encode(v: Any) -> bytes:
    """Minimal msgpack encoder (spec: msgpack/spec.md fixint/str/map…)."""
    if v is None:
        return b"\xc0"
    if isinstance(v, bool):
        return b"\xc3" if v else b"\xc2"
    if isinstance(v, int):
        if 0 <= v < 128:
            return struct.pack("B", v)
        if -32 <= v < 0:
            return struct.pack("b", v)
        if -(1 << 31) <= v < (1 << 31):
            return b"\xd2" + struct.pack(">i", v)
        return b"\xd3" + struct.pack(">q", v)
    if isinstance(v, float):
        return b"\xcb" + struct.pack(">d", v)
    if isinstance(v, datetime.datetime):
        return msgpack_encode(v.timestamp())
    if isinstance(v, datetime.timedelta):
        return msgpack_encode(v.total_seconds())
    if isinstance(v, bytes):
        return b"\xc4" + struct.pack("B", len(v)) + v if len(v) < 256 \
            else b"\xc5" + struct.pack(">H", len(v)) + v
    if isinstance(v, str):
        raw = v.encode("utf-8")
        if len(raw) < 32:
            return struct.pack("B", 0xa0 | len(raw)) + raw
        if len(raw) < 256:
            return b"\xd9" + struct.pack("B", len(raw)) + raw
        return b"\xda" + struct.pack(">H", len(raw)) + raw
    if isinstance(v, Mapping):
        items = list(v.items())
        if len(items) < 16:
            head = struct.pack("B", 0x80 | len(items))
        else:
            head = b"\xde" + struct.pack(">H", len(items))
        return head + b"".join(msgpack_encode(str(k)) + msgpack_encode(x)
                               for k, x in items)
    if isinstance(v, (list, tuple)):
        if len(v) < 16:
            head = struct.pack("B", 0x90 | len(v))
        else:
            head = b"\xdc" + struct.pack(">H", len(v))
        return head + b"".join(msgpack_encode(x) for x in v)
    return msgpack_encode(str(v))


class FluentdHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env,
                 sock: socket.socket | None = None):
        self.address = (config.get("address", "127.0.0.1"),
                        int(config.get("port", 24224)))
        self._env = env
        self._lock = threading.Lock()
        self._sock = sock
        self._connect_failed = False

    def _send(self, payload: bytes) -> None:
        with self._lock:
            if self._sock is None:
                try:
                    self._sock = socket.create_connection(self.address,
                                                          timeout=1.0)
                except OSError as exc:
                    if not self._connect_failed:
                        self._env.logger.warning(
                            "fluentd connect failed: %s", exc)
                        self._connect_failed = True
                    return
            try:
                self._sock.sendall(payload)
            except OSError as exc:
                self._env.logger.warning("fluentd send failed: %s", exc)
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def handle_report(self, template: str,
                      instances: Sequence[Mapping[str, Any]]) -> None:
        for inst in instances:
            tag = str(inst.get("name", "istio"))
            ts = inst.get("timestamp")
            secs = ts.timestamp() if isinstance(ts, datetime.datetime) \
                else datetime.datetime.now(datetime.timezone.utc).timestamp()
            record = {"severity": inst.get("severity", "DEFAULT"),
                      **(inst.get("variables", {}) or {})}
            event = [tag, int(secs), record]
            self._send(msgpack_encode(event))

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None


class FluentdBuilder(Builder):
    def build(self) -> Handler:
        return FluentdHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="fluentd",
    supported_templates=("logentry",),
    builder=FluentdBuilder,
    description="logentry to fluentd (forward protocol, msgpack/TCP)"))
