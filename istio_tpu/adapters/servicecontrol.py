"""servicecontrol — Google Service Control check/report/quota.

Reference: mixer/adapter/servicecontrol (~3,000 LoC):
  * apikey check (checkprocessor.go): empty key/operation →
    INVALID_ARGUMENT; consumer id is `api_key:<key>`; Check responses
    are cached per (google service, consumer, operation) with the
    configured expiration; HTTP status + the first CheckError map to
    rpc codes (utils.go toRPCCode / serviceControlErrorToRPCCode).
  * report (reportprocessor.go + reportbuilder.go + metrics.go): each
    instance becomes one Operation (uuid id, RFC3339 start/end) with
    MetricValueSets from the supported-metric table — label generator
    functions per /protocol, /response_code, /response_code_class,
    /status_code, /credential_id — plus an endpoints_log entry whose
    severity is ERROR for response codes ≥400 (error cause AUTH for
    401/403, APPLICATION otherwise); sends are scheduled off the
    request path (env.ScheduleWork, reportprocessor.go:60).
  * quota (quotaprocessor.go): AllocateQuota with quota mode NORMAL or
    BEST_EFFORT, granted amount read back from the
    serviceruntime allocation-result metric.

The processors are implemented natively; the network client is an
injectable `transport(method, service, payload) -> response dict`
(`:check`, `:report`, `:allocateQuota`), absent in this zero-egress
image — without it, check/quota fail closed (UNAVAILABLE) and reports
buffer until close.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Callable, Mapping, Sequence

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (Builder, CheckResult, Env, Handler,
                                    Info, QuotaArgs, QuotaResult)
from istio_tpu.utils.cache import TTLCache

OK, INVALID_ARGUMENT, NOT_FOUND = 0, 3, 5
PERMISSION_DENIED, RESOURCE_EXHAUSTED = 7, 8
FAILED_PRECONDITION, UNIMPLEMENTED = 9, 12
INTERNAL, UNAVAILABLE, UNAUTHENTICATED = 13, 14, 16
ALREADY_EXISTS, CANCELLED, DEADLINE_EXCEEDED, UNKNOWN = 6, 1, 4, 2

_HTTP_TO_RPC = {200: OK, 400: INVALID_ARGUMENT, 401: UNAUTHENTICATED,
                403: PERMISSION_DENIED, 404: NOT_FOUND,
                409: ALREADY_EXISTS, 429: RESOURCE_EXHAUSTED,
                499: CANCELLED, 500: INTERNAL, 501: UNIMPLEMENTED,
                503: UNAVAILABLE, 504: DEADLINE_EXCEEDED}

_SC_ERROR_TO_RPC = {
    "NOT_FOUND": NOT_FOUND,
    "PERMISSION_DENIED": PERMISSION_DENIED,
    "SECURITY_POLICY_VIOLATED": PERMISSION_DENIED,
    "RESOURCE_EXHAUSTED": RESOURCE_EXHAUSTED,
    "BUDGET_EXCEEDED": RESOURCE_EXHAUSTED,
    "LOAD_SHEDDING": RESOURCE_EXHAUSTED,
    "ABUSER_DETECTED": PERMISSION_DENIED,
    "API_KEY_INVALID": INVALID_ARGUMENT,
    "API_KEY_EXPIRED": INVALID_ARGUMENT,
    "SERVICE_NOT_ACTIVATED": PERMISSION_DENIED,
    "PROJECT_DELETED": PERMISSION_DENIED,
    "PROJECT_INVALID": INVALID_ARGUMENT,
    "BILLING_DISABLED": PERMISSION_DENIED,
}

_ALLOCATION_RESULT_METRIC = \
    "serviceruntime.googleapis.com/api/consumer/quota_used_count"


def http_to_rpc(code: int) -> int:
    """utils.go toRPCCode."""
    if code in _HTTP_TO_RPC:
        return _HTTP_TO_RPC[code]
    if 200 <= code <= 300:
        return OK
    if 400 <= code <= 500:
        return FAILED_PRECONDITION
    return UNKNOWN


def consumer_id(api_key: str) -> str:
    return f"api_key:{api_key}"


def _rfc3339(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


# ---------------------------------------------------------------------------
# report building (reportbuilder.go + metrics.go)
# ---------------------------------------------------------------------------

_ERROR_TYPES = ["0xx", "1xx", "2xx", "3xx", "4xx",
                "5xx", "6xx", "7xx", "8xx", "9xx"]


def _labels_for(inst: Mapping[str, Any],
                wanted: Sequence[str]) -> dict[str, str] | None:
    """Label generator table (reportbuilder.go:84-138). Returns None
    when a wanted label cannot be produced (metric skipped)."""
    out: dict[str, str] = {}
    code = int(inst.get("response_code", 0))
    for label in wanted:
        if label == "/credential_id":
            key = str(inst.get("api_key", ""))
            if not key:
                return None
            out[label] = "apiKey:" + key
        elif label == "/protocol":
            proto = str(inst.get("api_protocol", ""))
            if not proto:
                return None
            out[label] = proto
        elif label == "/response_code":
            out[label] = str(code)
        elif label == "/response_code_class":
            if not 0 <= code < 1000:
                return None
            out[label] = _ERROR_TYPES[code // 100]
        elif label == "/status_code":
            out[label] = str(http_to_rpc(code))
        else:
            return None
    return out


# (name, value kind, label set) — metrics.go supportedMetrics
SUPPORTED_METRICS: tuple[tuple[str, str, tuple[str, ...]], ...] = (
    ("serviceruntime.googleapis.com/api/producer/request_count",
     "count", ("/protocol", "/response_code", "/response_code_class",
               "/status_code")),
    ("serviceruntime.googleapis.com/api/producer/backend_latencies",
     "latency", ()),
    ("serviceruntime.googleapis.com/api/producer/request_sizes",
     "size", ()),
    ("serviceruntime.googleapis.com/api/producer/by_consumer/request_count",
     "count", ("/credential_id", "/protocol", "/response_code",
               "/response_code_class", "/status_code")),
    ("serviceruntime.googleapis.com/api/consumer/request_count",
     "count", ("/credential_id", "/protocol", "/response_code",
               "/response_code_class", "/status_code")),
    ("serviceruntime.googleapis.com/api/consumer/backend_latencies",
     "latency", ("/credential_id",)),
)


def _latency_s(inst: Mapping[str, Any]) -> float | None:
    """Template field `response_latency` (DURATION → timedelta from the
    instance builder); plain seconds also accepted."""
    latency = inst.get("response_latency", inst.get("request_latency_s"))
    if latency is None:
        return None
    if hasattr(latency, "total_seconds"):
        return latency.total_seconds()
    return float(latency)


def _epoch_s(value: Any, default: float | None = None) -> float:
    """Template TIMESTAMP fields arrive as datetime; floats accepted."""
    if value is None:
        return time.time() if default is None else default
    if hasattr(value, "timestamp"):
        return value.timestamp()
    return float(value)


def _metric_value(kind: str, inst: Mapping[str, Any]) -> dict | None:
    if kind == "count":
        return {"int64Value": 1}
    if kind == "latency":
        latency = _latency_s(inst)
        if latency is None:
            return None
        # ESP time distribution: 29 exponential buckets, growth 2, scale 1e-6
        return {"distributionValue": _dist_value(
            latency, buckets=29, growth=2.0, scale=1e-6)}
    if kind == "size":
        size = inst.get("request_bytes", inst.get("request_size"))
        if size is None:
            return None
        return {"distributionValue": _dist_value(
            float(size), buckets=8, growth=10.0, scale=1.0)}
    return None


def _dist_value(value: float, buckets: int, growth: float,
                scale: float) -> dict:
    """distValueBuilder.go: one-sample exponential distribution with
    ESP's bucket parameters."""
    import math
    counts = [0] * (buckets + 2)
    if value >= scale:
        idx = min(1 + int(math.log(value / scale, growth)), buckets + 1)
    else:
        idx = 0
    counts[idx] = 1
    return {"count": 1, "minimum": value, "maximum": value, "mean": value,
            "sumOfSquaredDeviation": 0.0,
            "exponentialBuckets": {"numFiniteBuckets": buckets,
                                   "growthFactor": growth, "scale": scale},
            "bucketCounts": counts}


def build_operation(inst: Mapping[str, Any]) -> dict:
    """reportprocessor.go initializeOperation + reportBuilder.build.
    Field names follow the servicecontrolreport template
    (templates/builtin.py; template.proto:51-65): request_method/
    request_path/request_bytes/response_bytes/response_latency."""
    start = _epoch_s(inst.get("request_time"))
    end = _epoch_s(inst.get("response_time"), default=start)
    op: dict[str, Any] = {
        "operationId": str(uuid.uuid4()),
        "operationName": str(inst.get("api_operation", "")),
        "consumerId": consumer_id(str(inst["api_key"]))
        if inst.get("api_key") else "",
        "startTime": _rfc3339(start),
        "endTime": _rfc3339(end),
        "metricValueSets": [],
        "logEntries": [],
    }
    for name, kind, wanted in SUPPORTED_METRICS:
        labels = _labels_for(inst, wanted)
        if labels is None:
            continue
        value = _metric_value(kind, inst)
        if value is None:
            continue
        if labels:
            value = {**value, "labels": labels}
        op["metricValueSets"].append(
            {"metricName": name, "metricValues": [value]})

    # endpoints_log entry (reportbuilder.go logPayload); template →
    # payload key mapping: request_path→url, request_method→
    # http_method, request_bytes→request_size_in_bytes
    code = int(inst.get("response_code", 0))
    severity = "ERROR" if code >= 400 else "INFO"
    payload: dict[str, Any] = {}
    for key, src in (("url", "request_path"),
                     ("api_name", "api_service"),
                     ("api_version", "api_version"),
                     ("api_operation", "api_operation"),
                     ("api_key", "api_key"),
                     ("http_method", "request_method"),
                     ("request_size_in_bytes", "request_bytes"),
                     ("response_size_in_bytes", "response_bytes"),
                     ("location", "location"),
                     ("log_message", "log_message")):
        if inst.get(src):
            payload[key] = inst[src]
    payload["http_response_code"] = code
    payload["timestamp"] = _rfc3339(end)
    latency = _latency_s(inst)
    if latency is not None:
        payload["request_latency_in_ms"] = int(latency * 1000)
    if code >= 400:
        payload["error_cause"] = ("AUTH" if code in (401, 403)
                                  else "APPLICATION")
    op["logEntries"].append({"name": "endpoints_log",
                             "severity": severity,
                             "structPayload": payload})
    return op


# ---------------------------------------------------------------------------
# handler
# ---------------------------------------------------------------------------

class ServiceControlHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env):
        self.env = env
        self.transport: Callable[[str, str, Any], Any] | None = \
            config.get("transport")
        runtime = dict(config.get("runtime_config") or {})
        expiration = float(runtime.get("check_result_expiration_s", 60.0))
        self.check_expiration = expiration
        self._cache = TTLCache(
            ttl_seconds=expiration,
            capacity=int(runtime.get("check_cache_size", 10_000)))
        # mesh service name → {google_service_name, quotas: {name: cfg}}
        self.services: dict[str, dict] = {}
        for setting in config.get("service_configs", ()):
            entry = {"google_service_name":
                     str(setting.get("google_service_name", "")),
                     "quotas": {str(q.get("name")): dict(q)
                                for q in setting.get("quotas", ())}}
            self.services[str(setting.get("mesh_service_name", ""))] = entry
        self.default_service = next(iter(self.services.values()), None)
        self._lock = threading.Lock()
        self._pending_reports: list[tuple[str, dict]] = []

    def _service_for(self, inst: Mapping[str, Any]) -> dict | None:
        """Route by the template's api_service field (handler.go keys
        its serviceConfigIndex by mesh service name; the dispatcher
        here carries it on the instance)."""
        mesh = str(inst.get("api_service", "")
                   or inst.get("mesh_service", ""))
        return self.services.get(mesh) or self.default_service

    def _call(self, method: str, service: str, payload: Any) -> Any:
        if self.transport is None:
            raise ConnectionError(
                "servicecontrol: no egress in this build; inject "
                "`transport` to reach the Service Control API")
        return self.transport(method, service, payload)

    # -- apikey check (checkprocessor.go ProcessCheck) --

    def handle_check(self, template: str,
                     instance: Mapping[str, Any]) -> CheckResult:
        api_key = str(instance.get("api_key", ""))
        operation = str(instance.get("api_operation", ""))
        if not api_key or not operation:
            return self._result(
                INVALID_ARGUMENT,
                "api key and api operation must not be empty")
        svc = self._service_for(instance)
        if svc is None:
            return self._result(FAILED_PRECONDITION,
                                "no service_configs configured")
        google = svc["google_service_name"]
        cid = consumer_id(api_key)
        key = (google, cid, operation)
        response = self._cache.get(key)
        if response is None:
            request = {"operation": {
                "operationId": str(uuid.uuid4()),
                "operationName": operation,
                "consumerId": cid,
                "startTime": _rfc3339(
                    float(instance.get("timestamp", time.time())))}}
            try:
                response = self._call(":check", google, request)
            except Exception as exc:
                # fail closed like the reference (PERMISSION_DENIED on
                # client error, checkprocessor.go:63-66) — but surface
                # transport-missing as UNAVAILABLE
                code = UNAVAILABLE if isinstance(exc, ConnectionError) \
                    else PERMISSION_DENIED
                return self._result(code, str(exc))
            self._cache.set(key, response)
        return self._response_to_result(response)

    def _response_to_result(self, response: Mapping[str, Any]) -> CheckResult:
        http_status = int(response.get("httpStatusCode", 200))
        if http_status != 200:
            return self._result(http_to_rpc(http_status),
                                f"HTTP {http_status}")
        errors = response.get("checkErrors") or ()
        if errors:
            first = errors[0]
            code = str(first.get("code", "UNKNOWN"))
            return self._result(
                _SC_ERROR_TO_RPC.get(code, UNKNOWN),
                f"{code}: {first.get('detail', '')}")
        return self._result(OK, "")

    def _result(self, code: int, message: str) -> CheckResult:
        return CheckResult(status_code=code, status_message=message,
                           valid_duration_s=self.check_expiration,
                           valid_use_count=2**31 - 1)

    # -- report (reportprocessor.go ProcessReport) --

    def handle_report(self, template: str,
                      instances: Sequence[Mapping[str, Any]]) -> None:
        for inst in instances:
            svc = self._service_for(inst)
            if svc is None:
                continue
            op = build_operation(inst)
            if not op["metricValueSets"] and not op["logEntries"]:
                continue
            google = svc["google_service_name"]
            if self.transport is None:
                # buffer for a late-bound transport (set_transport);
                # bounded — oldest dropped first
                with self._lock:
                    self._pending_reports.append((google, op))
                    del self._pending_reports[:-1000]
                continue
            self.env.schedule_work(
                lambda g=google, o=op: self._send_report(g, o))

    def set_transport(self,
                      transport: Callable[[str, str, Any], Any]) -> None:
        """Late-bind the network client (e.g. once platform credentials
        resolve) and drain reports buffered while offline."""
        self.transport = transport
        with self._lock:
            pending, self._pending_reports = self._pending_reports, []
        for google, op in pending:
            self.env.schedule_work(
                lambda g=google, o=op: self._send_report(g, o))

    def _send_report(self, google: str, op: dict) -> None:
        try:
            self._call(":report", google, {"operations": [op]})
        except Exception:
            self.env.logger.exception("servicecontrol report failed")

    # -- quota (quotaprocessor.go ProcessQuota) --

    def handle_quota(self, template: str, instance: Mapping[str, Any],
                     args: QuotaArgs) -> QuotaResult:
        svc = self._service_for(instance)
        quota_name = str(instance.get("name", ""))
        quota_cfg = (svc or {}).get("quotas", {}).get(quota_name)
        if svc is None or quota_cfg is None:
            return QuotaResult(status_code=INVALID_ARGUMENT,
                               status_message=f"unknown quota name: "
                                              f"{quota_name}",
                               valid_duration_s=60.0)
        expiration = float(quota_cfg.get("expiration_s", 60.0))
        dims = dict(instance.get("dimensions") or {})
        api_key = str(dims.get("api_key", ""))
        operation = str(dims.get("api_operation", ""))
        if not api_key or not operation:
            return QuotaResult(
                status_code=INVALID_ARGUMENT,
                status_message="dimensions api_key/api_operation required",
                valid_duration_s=expiration)
        metric = str(quota_cfg.get("google_quota_metric_name", "")) \
            or quota_name
        request = {"allocateOperation": {
            "operationId": str(uuid.uuid4()),
            "methodName": operation,
            "consumerId": consumer_id(api_key),
            "quotaMetrics": [{"metricName": metric,
                              "metricValues":
                                  [{"int64Value": args.quota_amount}]}],
            "quotaMode": "BEST_EFFORT" if args.best_effort else "NORMAL"}}
        try:
            response = self._call(
                ":allocateQuota", svc["google_service_name"], request)
        except Exception as exc:
            return QuotaResult(status_code=UNAVAILABLE,
                               status_message=str(exc),
                               valid_duration_s=expiration)
        errors = response.get("allocateErrors") or ()
        if errors:
            first = errors[0]
            code = str(first.get("code", ""))
            granted = 0 if code == "RESOURCE_EXHAUSTED" \
                else args.quota_amount
            status = RESOURCE_EXHAUSTED if granted == 0 else OK
            return QuotaResult(granted_amount=granted, status_code=status,
                               status_message=str(first.get("detail", "")),
                               valid_duration_s=expiration)
        granted = args.quota_amount
        for mvs in response.get("quotaMetrics") or ():
            if mvs.get("metricName") == _ALLOCATION_RESULT_METRIC:
                for value in mvs.get("metricValues") or ():
                    labels = value.get("labels") or {}
                    if labels.get("/quota_name") == metric:
                        granted = int(value.get("int64Value", granted))
                        break
        return QuotaResult(granted_amount=granted,
                           valid_duration_s=expiration)

    def close(self) -> None:
        if self.transport is not None:
            with self._lock:
                pending, self._pending_reports = self._pending_reports, []
            for google, op in pending:
                self._send_report(google, op)


class ServiceControlBuilder(Builder):
    def validate(self) -> list[str]:
        errs: list[str] = []
        settings = self.config.get("service_configs", ())
        for setting in settings:
            if not setting.get("mesh_service_name"):
                errs.append("service_configs: mesh_service_name required")
            if not setting.get("google_service_name"):
                errs.append("service_configs: google_service_name required")
        runtime = self.config.get("runtime_config") or {}
        if float(runtime.get("check_result_expiration_s", 60.0)) <= 0:
            errs.append("runtime_config.check_result_expiration_s: must "
                        "be positive")
        return errs

    def build(self) -> Handler:
        return ServiceControlHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="servicecontrol",
    supported_templates=("apikey", "quota", "servicecontrolreport",
                         "metric", "logentry"),
    builder=ServiceControlBuilder,
    description="Google Service Control check/report/quota"))
