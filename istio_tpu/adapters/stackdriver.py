"""stackdriver — metrics/logs/traces to Google Cloud operations.

Reference: mixer/adapter/stackdriver — three sub-handlers sharing one
adapter entry (stackdriver.go):
  * metric/metric.go: HandleMetric converts instances to monitoring
    TimeSeries (custom.googleapis.com/<name> type, per-config kind and
    value type, distribution values bucketed by linear/exponential/
    explicit BucketsDefinition with under+overflow buckets,
    distribution.go:26-150), defaulting the monitored resource to
    `global` (metric.go:218-228); a buffered client merges same-series
    points per push window — DELTA munged to CUMULATIVE with a ≥1µs
    interval (merge.go:36-56) — and pushes on a ticker
    (bufferedClient.go, default interval 1m, metric.go:146-149).
  * log/log.go: HandleLogEntry maps instances to logging entries with
    severity parsing, label extraction and the HttpRequestMapping
    (log.go:119-215).
  * tracespan: span conversion (same shape as utils/tracing.py spans).

The translation/merge/bucketing logic is implemented natively below;
the one network hop (CreateTimeSeries / WriteLogEntries RPCs) is an
injectable `transport(method, payload)`, absent in this zero-egress
image.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Mapping, Sequence

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (AdapterUnavailable, Builder, Env,
                                    Handler, Info)

GAUGE, DELTA, CUMULATIVE = "GAUGE", "DELTA", "CUMULATIVE"

_SEVERITIES = ("DEFAULT", "DEBUG", "INFO", "NOTICE", "WARNING", "ERROR",
               "CRITICAL", "ALERT", "EMERGENCY")


class _Missing(dict):
    def __missing__(self, key):
        return ""


def _safe_format(template: str, variables: Mapping[str, Any]) -> str:
    """Template expansion that never throws on a missing variable or a
    malformed template — one bad log config must not fail the report
    call (log.go tolerates partial entries)."""
    try:
        return template.format_map(_Missing(variables))
    except (ValueError, IndexError):
        return template


# ---------------------------------------------------------------------------
# distribution bucketing (metric/distribution.go)
# ---------------------------------------------------------------------------

def bucket_count(buckets: Mapping[str, Any]) -> int:
    """Total bucket slots incl. underflow + overflow."""
    if "linear" in buckets:
        return int(buckets["linear"]["num_finite_buckets"]) + 2
    if "exponential" in buckets:
        return int(buckets["exponential"]["num_finite_buckets"]) + 2
    if "explicit" in buckets:
        return len(buckets["explicit"]["bounds"]) + 1
    return 0


def bucket_index(value: float, buckets: Mapping[str, Any]) -> int:
    """Index of the bucket `value` falls into (0 = underflow,
    last = overflow) — distribution.go index()."""
    if "linear" in buckets:
        lin = buckets["linear"]
        offset, width = float(lin["offset"]), float(lin["width"])
        n = int(lin["num_finite_buckets"])
        if value < offset:
            return 0
        i = int((value - offset) // width) + 1
        return min(i, n + 1)
    if "exponential" in buckets:
        ex = buckets["exponential"]
        scale, growth = float(ex["scale"]), float(ex["growth_factor"])
        n = int(ex["num_finite_buckets"])
        if value < scale:
            return 0
        i = 1 + int(math.log(value / scale, growth))
        return min(i, n + 1)
    if "explicit" in buckets:
        bounds = [float(b) for b in buckets["explicit"]["bounds"]]
        for i, bound in enumerate(bounds):
            if value < bound:
                return i
        return len(bounds)
    return 0


def to_distribution(value: float, buckets: Mapping[str, Any]) -> dict:
    counts = [0] * bucket_count(buckets)
    if counts:
        counts[bucket_index(value, buckets)] = 1
    return {"count": 1, "bucketOptions": dict(buckets),
            "bucketCounts": counts}


# ---------------------------------------------------------------------------
# time-series building + merging (metric/metric.go + merge.go)
# ---------------------------------------------------------------------------

def metric_type(name: str) -> str:
    return f"custom.googleapis.com/{name}"


def _series_key(ts: Mapping[str, Any]) -> tuple:
    metric = ts["metric"]
    res = ts.get("resource", {})
    return (metric["type"],
            tuple(sorted((metric.get("labels") or {}).items())),
            res.get("type", ""),
            tuple(sorted((res.get("labels") or {}).items())))


def merge_series(series: Sequence[Mapping[str, Any]]) -> list[dict]:
    """One point per series per push window: group by (metric,
    resource), sum mergeable values, widen the interval. DELTA becomes
    CUMULATIVE with end > start by ≥1µs (merge.go:36-56: stackdriver
    rejects DELTA custom metrics and zero-width cumulative windows)."""
    grouped: dict[tuple, list[dict]] = {}
    for ts in series:
        ts = {**ts}
        if ts.get("metricKind") in (DELTA, CUMULATIVE):
            pt = ts["points"][0]
            iv = pt["interval"]
            if iv["endTime"] <= iv["startTime"]:
                iv = {**iv, "endTime": iv["startTime"] + 1e-6}
                ts["points"] = [{**pt, "interval": iv}]
            ts["metricKind"] = CUMULATIVE
        grouped.setdefault(_series_key(ts), []).append(ts)

    out = []
    for group in grouped.values():
        cur = group[0]
        if cur.get("metricKind") == GAUGE:
            # gauge: last write wins, no additive merge
            out.append(group[-1])
            continue
        point = dict(cur["points"][0])
        start = point["interval"]["startTime"]
        end = point["interval"]["endTime"]
        for ts in group[1:]:
            nxt = ts["points"][0]
            point["value"] = _merge_value(point["value"], nxt["value"])
            start = min(start, nxt["interval"]["startTime"])
            end = max(end, nxt["interval"]["endTime"])
        point["interval"] = {"startTime": start, "endTime": end}
        out.append({**cur, "points": [point]})
    return out


def _merge_value(a: Mapping[str, Any], b: Mapping[str, Any]) -> dict:
    if "int64Value" in a:
        return {"int64Value": a["int64Value"] + b.get("int64Value", 0)}
    if "doubleValue" in a:
        return {"doubleValue": a["doubleValue"] + b.get("doubleValue", 0.0)}
    if "distributionValue" in a:
        da, db = a["distributionValue"], b["distributionValue"]
        counts = [x + y for x, y in
                  zip(da["bucketCounts"], db["bucketCounts"])]
        return {"distributionValue": {
            "count": da["count"] + db["count"],
            "bucketOptions": da["bucketOptions"],
            "bucketCounts": counts}}
    return dict(a)                 # bool/string: last write wins


class _BufferedPusher:
    """bufferedClient.go: accumulate under a lock, merge + push on the
    ticker; Close drains."""

    def __init__(self, env: Env, method: str,
                 transport: Callable[[str, Any], Any] | None,
                 interval_s: float, merge=None):
        self.env = env
        self.method = method
        self.transport = transport
        self.merge = merge
        self._lock = threading.Lock()
        self._buf: list[dict] = []
        self._stop = threading.Event()
        self._ticker = threading.Thread(
            target=self._run, args=(max(interval_s, 0.05),), daemon=True,
            name=f"stackdriver-{method}")
        self._ticker.start()

    def record(self, items: Sequence[Mapping[str, Any]]) -> None:
        with self._lock:
            self._buf.extend(dict(i) for i in items)

    def _run(self, interval: float) -> None:
        while not self._stop.wait(interval):
            try:
                self.flush()
            except AdapterUnavailable:
                pass               # keep buffering; drain on close
            except Exception:
                self.env.logger.exception("stackdriver push failed")

    def flush(self) -> None:
        if self.transport is None:
            with self._lock:
                pending = len(self._buf)
            if pending:
                raise AdapterUnavailable(
                    "stackdriver: no egress in this build; inject "
                    "`transport` to push")
            return
        with self._lock:
            batch, self._buf = self._buf, []
        if batch:
            if self.merge is not None:
                batch = self.merge(batch)
            self.transport(self.method, batch)

    def close(self) -> None:
        self._stop.set()
        self._ticker.join(timeout=2.0)
        try:
            self.flush()
        except AdapterUnavailable:
            pass


class StackdriverHandler(Handler):
    def __init__(self, config: Mapping[str, Any], env: Env):
        self.env = env
        self.project = str(config.get("project_id", ""))
        self.metric_info: dict[str, dict] = {
            str(k): dict(v)
            for k, v in (config.get("metric_info") or {}).items()}
        self.log_info: dict[str, dict] = {
            str(k): dict(v)
            for k, v in (config.get("log_info") or {}).items()}
        transport = config.get("transport")
        interval = float(config.get("push_interval_s", 60.0))
        self._metrics = _BufferedPusher(env, "monitoring.createTimeSeries",
                                        transport, interval,
                                        merge=merge_series)
        self._logs = _BufferedPusher(env, "logging.writeLogEntries",
                                     transport, interval)
        self._traces = _BufferedPusher(env, "cloudtrace.batchWriteSpans",
                                       transport, interval)
        self.now = config.get("now", time.time)

    # -- metrics (metric/metric.go HandleMetric) --

    def _typed_value(self, value: Any, info: Mapping[str, Any]) -> dict:
        if info.get("value") == "DISTRIBUTION":
            return {"distributionValue":
                    to_distribution(float(value), info.get("buckets", {}))}
        if isinstance(value, bool):
            return {"boolValue": value}
        if isinstance(value, int):
            return {"int64Value": value}
        if isinstance(value, float):
            return {"doubleValue": value}
        return {"stringValue": str(value)}

    def handle_report(self, template: str,
                      instances: Sequence[Mapping[str, Any]]) -> None:
        if template == "metric":
            self._handle_metrics(instances)
        elif template == "logentry":
            self._handle_logs(instances)
        elif template == "tracespan":
            self._handle_traces(instances)

    def _handle_metrics(self, instances) -> None:
        now = self.now()
        data = []
        for inst in instances:
            name = str(inst.get("name", ""))
            info = self.metric_info.get(name)
            if info is None:
                continue           # not configured → cannot publish
            resource = ({"type": inst["monitored_resource_type"],
                         "labels": {
                             str(k): str(v) for k, v in
                             (inst.get("monitored_resource_dimensions")
                              or {}).items()}}
                        if inst.get("monitored_resource_type")
                        else {"type": "global",
                              "labels": {"project_id": self.project}})
            data.append({
                "metric": {"type": metric_type(name),
                           "labels": {str(k): str(v) for k, v in
                                      (inst.get("dimensions")
                                       or {}).items()}},
                "metricKind": info.get("kind", GAUGE),
                "valueType": info.get("value", "INT64"),
                "resource": resource,
                "points": [{"interval": {"startTime": now,
                                         "endTime": now},
                            "value": self._typed_value(
                                inst.get("value"), info)}]})
        if data:
            self._metrics.record(data)

    # -- logs (log/log.go HandleLogEntry) --

    def _handle_logs(self, instances) -> None:
        entries = []
        for inst in instances:
            name = str(inst.get("name", "istio"))
            info = self.log_info.get(name, {})
            variables = dict(inst.get("variables") or {})
            severity = str(inst.get("severity", "DEFAULT")).upper()
            if severity not in _SEVERITIES:
                severity = "DEFAULT"
            entry: dict[str, Any] = {
                "logName": f"projects/{self.project}/logs/{name}",
                "timestamp": inst.get("timestamp", self.now()),
                "severity": severity,
                "labels": {str(k): str(v) for k, v in variables.items()},
            }
            payload_tmpl = info.get("payload_template")
            if payload_tmpl:
                entry["textPayload"] = _safe_format(str(payload_tmpl),
                                                    variables)
            else:
                entry["jsonPayload"] = variables
            req_map = info.get("http_mapping")
            if req_map:
                entry["httpRequest"] = {
                    dst: variables[src]
                    for dst, src in req_map.items() if src in variables}
            entries.append(entry)
        if entries:
            self._logs.record(entries)

    # -- traces (tracespan template over the shared span shape) --

    def _handle_traces(self, instances) -> None:
        spans = []
        for inst in instances:
            spans.append({
                "name": (f"projects/{self.project}/traces/"
                         f"{inst.get('trace_id', '')}/spans/"
                         f"{inst.get('span_id', '')}"),
                "spanId": inst.get("span_id", ""),
                "parentSpanId": inst.get("parent_span_id", ""),
                "displayName": inst.get("span_name", ""),
                "startTime": inst.get("start_time"),
                "endTime": inst.get("end_time"),
                "attributes": dict(inst.get("span_tags") or {}),
            })
        if spans:
            self._traces.record(spans)

    def close(self) -> None:
        self._metrics.close()
        self._logs.close()
        self._traces.close()


class StackdriverBuilder(Builder):
    def validate(self) -> list[str]:
        errs: list[str] = []
        if not self.config.get("project_id"):
            errs.append("project_id: required")
        for name, info in (self.config.get("metric_info") or {}).items():
            kind = info.get("kind", GAUGE)
            if kind not in (GAUGE, DELTA, CUMULATIVE):
                errs.append(f"metric_info[{name}].kind: {kind!r}")
            if info.get("value") == "DISTRIBUTION" \
                    and bucket_count(info.get("buckets", {})) == 0:
                errs.append(f"metric_info[{name}]: distribution needs "
                            "linear/exponential/explicit buckets")
        return errs

    def build(self) -> Handler:
        return StackdriverHandler(self.config, self.env)


INFO = adapter_registry.register(Info(
    name="stackdriver",
    supported_templates=("metric", "logentry", "tracespan"),
    builder=StackdriverBuilder,
    description="metrics/logs/traces → Google Cloud operations suite"))
