"""denier — unconditionally deny checks/quotas with a configured status.

Reference: mixer/adapter/denier/denier.go (617 LoC): returns the
configured status for checknothing/listentry checks and zero grant for
quota. This is the adapter the PolicyEngine fuses on device as
`DenySpec`; this host implementation serves the generic dispatcher path
and is the semantics oracle for the fused one.
"""
from __future__ import annotations

from typing import Any, Mapping, Sequence

from istio_tpu.adapters.registry import adapter_registry
from istio_tpu.adapters.sdk import (Builder, CheckResult, Env, Handler, Info,
                                    QuotaArgs, QuotaResult)
from istio_tpu.models.policy_engine import PERMISSION_DENIED


class DenierHandler(Handler):
    def __init__(self, config: Mapping[str, Any]):
        self.status_code = int(config.get("status_code", PERMISSION_DENIED))
        self.status_message = str(config.get("status_message", "denied"))
        self.valid_duration_s = float(config.get("valid_duration_s", 5.0))
        self.valid_use_count = int(config.get("valid_use_count", 10_000))

    def handle_check(self, template: str,
                     instance: Mapping[str, Any]) -> CheckResult:
        return CheckResult(status_code=self.status_code,
                           status_message=self.status_message,
                           valid_duration_s=self.valid_duration_s,
                           valid_use_count=self.valid_use_count)

    def handle_quota(self, template: str, instance: Mapping[str, Any],
                     args: QuotaArgs) -> QuotaResult:
        return QuotaResult(granted_amount=0,
                           valid_duration_s=self.valid_duration_s,
                           status_code=self.status_code,
                           status_message=self.status_message)


class DenierBuilder(Builder):
    def validate(self) -> list[str]:
        errs = []
        if not isinstance(self.config.get("status_code",
                                          PERMISSION_DENIED), int):
            errs.append("status_code must be an integer rpc code")
        return errs

    def build(self) -> Handler:
        return DenierHandler(self.config)


INFO = adapter_registry.register(Info(
    name="denier",
    supported_templates=("checknothing", "listentry", "quota"),
    builder=DenierBuilder,
    description="static deny for check/listentry/quota",
    default_config={"status_code": PERMISSION_DENIED}))
