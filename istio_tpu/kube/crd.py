"""CRD-backed config sources.

CrdStore       — the mixer store over cluster CRDs
                 (mixer/pkg/config/crd/store.go: Init lists every
                 registered kind, Watch streams changes into the
                 runtime controller's event queue).
KubeConfigStore — pilot's ConfigStore over cluster CRDs
                 (pilot/pkg/config/kube/crd/client.go + controller.go:
                 informer cache + handler fan-out; istioctl writes
                 through the same client).
"""
from __future__ import annotations

from typing import Any, Callable, Mapping

from istio_tpu.kube.fake import FakeKubeCluster, WatchEvent
from istio_tpu.pilot.model import (Config, ConfigMeta, ConfigStore,
                                   IstioConfigTypes)
from istio_tpu.runtime.store import Event, Store, Validator

# the mixer config kinds served as CRDs (crd/store.go criteria — the
# runtime watches these; SnapshotBuilder consumes the same names)
ISTIO_CRD_KINDS = ("attributemanifest", "handler", "instance", "rule",
                   "servicerole", "servicerolebinding")


class CrdStore(Store):
    """Mixer store fed by cluster watches. Read path + watch only —
    config writes flow through the cluster (kubectl in the reference),
    land here as watch events, and fan out to the runtime controller."""

    def __init__(self, cluster: FakeKubeCluster,
                 validator: Validator | None = None,
                 kinds: tuple[str, ...] = ISTIO_CRD_KINDS):
        super().__init__(validator)
        self.cluster = cluster
        for kind in kinds:
            cluster.watch(kind, self._on_event)

    def _on_event(self, ev: WatchEvent) -> None:
        key = (ev.kind, ev.namespace, ev.name)
        value = None if ev.type == "DELETED" \
            else dict(ev.obj.get("spec") or {})
        self.apply_events([Event(key, value)])


class KubeConfigStore(ConfigStore):
    """Pilot ConfigStore over cluster CRDs with an informer-style local
    cache and change-handler fan-out (crd/{client,controller}.go)."""

    def __init__(self, cluster: FakeKubeCluster,
                 schemas: Mapping[str, Any] | None = None):
        self.cluster = cluster
        self.schemas = dict(schemas or IstioConfigTypes)
        self._cache: dict[tuple[str, str, str], Config] = {}
        self._handlers: list[Callable[[Config, str], None]] = []
        for typ in self.schemas:
            cluster.watch(typ, self._on_event)

    def register_handler(self, fn: Callable[[Config, str], None]) -> None:
        self._handlers.append(fn)

    @staticmethod
    def _to_config(obj: Mapping[str, Any]) -> Config:
        meta = obj.get("metadata") or {}
        return Config(meta=ConfigMeta(
            type=str(obj.get("kind", "")),
            name=str(meta.get("name", "")),
            namespace=str(meta.get("namespace", "")),
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
            resource_version=str(meta.get("resourceVersion", ""))),
            spec=dict(obj.get("spec") or {}))

    def _on_event(self, ev: WatchEvent) -> None:
        config = self._to_config(ev.obj)
        key = (config.meta.type, config.meta.namespace, config.meta.name)
        event = {"ADDED": "add", "MODIFIED": "update",
                 "DELETED": "delete"}[ev.type]
        if ev.type == "DELETED":
            self._cache.pop(key, None)
        else:
            self._cache[key] = config
        for fn in list(self._handlers):
            fn(config, event)

    # -- ConfigStore reads (cache) --

    def get(self, typ: str, name: str, namespace: str = "") -> Config | None:
        return self._cache.get((typ, namespace, name))

    def list(self, typ: str, namespace: str | None = None) -> list[Config]:
        return sorted(
            (c for (t, ns, _), c in self._cache.items()
             if t == typ and (namespace is None or ns == namespace)),
            key=lambda c: (c.meta.namespace, c.meta.name))

    # -- ConfigStore writes (through the cluster, like istioctl) --

    def _validate(self, config: Config) -> None:
        schema = self.schemas.get(config.meta.type)
        if schema is None:
            raise KeyError(f"unknown config type {config.meta.type}")
        schema.validate(config.spec)

    def _to_obj(self, config: Config) -> dict:
        return {"kind": config.meta.type,
                "metadata": {"name": config.meta.name,
                             "namespace": config.meta.namespace,
                             "labels": dict(config.meta.labels),
                             "annotations": dict(config.meta.annotations)},
                "spec": dict(config.spec)}

    def create(self, config: Config) -> None:
        self._validate(config)
        self.cluster.create(self._to_obj(config))

    def update(self, config: Config) -> None:
        self._validate(config)
        self.cluster.update(self._to_obj(config))

    def delete(self, typ: str, name: str, namespace: str = "") -> None:
        self.cluster.delete(typ, namespace, name)
