"""Kube service registry — ServiceDiscovery over cluster objects.

Reference: pilot/pkg/serviceregistry/kube/{controller,conversion}.go —
informer caches over Services/Endpoints/Pods, converted to the abstract
model on read: hostname `<name>.<ns>.svc.<domain>`, port protocols from
the port-name prefix convention (http-, http2-, grpc-, tcp-, udp-,
mongo-, redis-; bare names default TCP like conversion.go), instance
labels and service accounts joined from the pod backing each endpoint
address.
"""
from __future__ import annotations

import threading
from typing import Any, Mapping, Sequence

from istio_tpu.kube.fake import FakeKubeCluster, WatchEvent
from istio_tpu.pilot.model import (NetworkEndpoint, Port, Service,
                                   ServiceInstance)
from istio_tpu.pilot.registry import Handler, ServiceDiscovery

_PROTO_BY_PREFIX = {"http": "HTTP", "http2": "HTTP2", "grpc": "GRPC",
                    "https": "HTTPS", "tcp": "TCP", "udp": "UDP",
                    "mongo": "MONGO", "redis": "REDIS"}


def protocol_from_port_name(name: str) -> str:
    """kube/conversion.go ConvertProtocol: '<proto>[-suffix]'."""
    prefix = name.split("-", 1)[0].lower()
    return _PROTO_BY_PREFIX.get(prefix, "TCP")


class KubeServiceRegistry(ServiceDiscovery):
    def __init__(self, cluster: FakeKubeCluster,
                 domain: str = "cluster.local"):
        self.cluster = cluster
        self.domain = domain
        self._lock = threading.Lock()
        self._services: dict[str, Service] = {}        # hostname → svc
        self._endpoints: dict[str, Mapping[str, Any]] = {}
        self._pods_by_ip: dict[str, Mapping[str, Any]] = {}
        self._svc_handlers: list[Handler] = []
        cluster.watch("Service", self._on_service)
        cluster.watch("Endpoints", self._on_endpoints)
        cluster.watch("Pod", self._on_pod)

    # -- conversion (kube/conversion.go) --

    def _hostname(self, name: str, namespace: str) -> str:
        return f"{name}.{namespace or 'default'}.svc.{self.domain}"

    def _to_service(self, obj: Mapping[str, Any]) -> Service:
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        ports = tuple(
            Port(name=str(p.get("name") or p.get("port")),
                 port=int(p.get("port")),
                 protocol=protocol_from_port_name(str(p.get("name", ""))))
            for p in spec.get("ports") or ())
        return Service(
            hostname=self._hostname(meta.get("name", ""),
                                    meta.get("namespace", "")),
            address=str(spec.get("clusterIP", "0.0.0.0") or "0.0.0.0"),
            ports=ports,
            external_name=str(spec.get("externalName", "") or ""))

    # -- watch handlers (informer cache updates) --

    def _on_service(self, ev: WatchEvent) -> None:
        svc = self._to_service(ev.obj)
        with self._lock:
            if ev.type == "DELETED":
                self._services.pop(svc.hostname, None)
            else:
                self._services[svc.hostname] = svc
        event = "delete" if ev.type == "DELETED" else "add"
        for fn in list(self._svc_handlers):
            fn(svc, event)

    def _on_endpoints(self, ev: WatchEvent) -> None:
        host = self._hostname(ev.name, ev.namespace)
        with self._lock:
            if ev.type == "DELETED":
                self._endpoints.pop(host, None)
            else:
                self._endpoints[host] = ev.obj
            svc = self._services.get(host)
        if svc is not None:
            for fn in list(self._svc_handlers):
                fn(svc, "update")

    def _on_pod(self, ev: WatchEvent) -> None:
        ip = str((ev.obj.get("status") or {}).get("podIP", ""))
        if not ip:
            return
        with self._lock:
            if ev.type == "DELETED":
                self._pods_by_ip.pop(ip, None)
            else:
                self._pods_by_ip[ip] = ev.obj

    # -- ServiceDiscovery reads --

    def services(self) -> list[Service]:
        with self._lock:
            return sorted(self._services.values(),
                          key=lambda s: s.hostname)

    def get_service(self, hostname: str) -> Service | None:
        with self._lock:
            return self._services.get(hostname)

    def _pod_of(self, address: str) -> Mapping[str, Any] | None:
        return self._pods_by_ip.get(address)

    def _sa_of(self, address: str, namespace: str) -> str:
        pod = self._pod_of(address)
        if pod is None:
            return ""
        sa = str((pod.get("spec") or {}).get("serviceAccountName", ""))
        if not sa:
            return ""
        return (f"spiffe://{self.domain}/ns/{namespace or 'default'}"
                f"/sa/{sa}")

    def _service_instances(self, svc: Service) -> list[ServiceInstance]:
        eps = self._endpoints.get(svc.hostname)
        if eps is None:
            return []
        out = []
        namespace = svc.namespace
        for subset in (eps.get("subsets") or ()):
            port_by_name = {str(p.get("name") or p.get("port")): p
                            for p in subset.get("ports") or ()}
            for addr in (subset.get("addresses") or ()):
                ip = str(addr.get("ip", ""))
                pod = self._pod_of(ip)
                labels = dict(((pod or {}).get("metadata") or {})
                              .get("labels") or {})
                for sp in svc.ports:
                    ep_port = port_by_name.get(sp.name)
                    if ep_port is None and len(port_by_name) == 1:
                        ep_port = next(iter(port_by_name.values()))
                    if ep_port is None:
                        continue
                    out.append(ServiceInstance(
                        endpoint=NetworkEndpoint(
                            address=ip,
                            port=int(ep_port.get("port", sp.port)),
                            service_port=sp),
                        service=svc, labels=labels,
                        service_account=self._sa_of(ip, namespace)))
        return out

    def instances(self, hostname: str, ports: Sequence[str] = (),
                  labels: Mapping[str, str] | None = None
                  ) -> list[ServiceInstance]:
        with self._lock:
            svc = self._services.get(hostname)
            if svc is None:
                return []
            out = []
            for inst in self._service_instances(svc):
                if ports and inst.endpoint.service_port.name not in ports:
                    continue
                if labels and any(inst.labels.get(k) != v
                                  for k, v in labels.items()):
                    continue
                out.append(inst)
            return out

    def host_instances(self, addrs: set[str]) -> list[ServiceInstance]:
        with self._lock:
            out = []
            for svc in self._services.values():
                out.extend(i for i in self._service_instances(svc)
                           if i.endpoint.address in addrs)
            return out

    def get_istio_service_accounts(self, hostname: str,
                                   ports: Sequence[str]) -> list[str]:
        """service.go:259 ServiceAccounts: accounts of the instances
        backing the service."""
        return sorted({i.service_account
                       for i in self.instances(hostname, ports)
                       if i.service_account})

    def append_service_handler(self, fn: Handler) -> None:
        self._svc_handlers.append(fn)
