"""FakeKubeCluster — an in-process API-server double.

Scope: exactly the API semantics the L2 adapters consume —
  * typed objects {apiVersion?, kind, metadata{name, namespace, labels,
    resourceVersion, uid}, spec/...} stored per (kind, ns, name);
  * monotonically increasing resourceVersion on every mutation;
  * list + watch per kind: a watcher first receives the current state
    as ADDED events (the informer's initial list) and then live
    ADDED/MODIFIED/DELETED events, synchronously on the mutator's
    thread (deterministic tests; real informers add a queue, which the
    consumers here already tolerate);
  * mutating-admission hooks (the MutatingAdmissionWebhook role —
    sidecar injection) run first and may replace the object;
  * validating-admission hooks invoked before create/update commits
    (pilot/pkg/kube/admit/admit.go's ValidatingAdmissionWebhook role) —
    a hook raising AdmissionDenied rejects the write.
"""
from __future__ import annotations

import copy
import dataclasses
import logging
import threading
from typing import Any, Callable, Mapping

__all__ = ["AdmissionDenied", "AlreadyExists", "FakeKubeCluster",
           "WatchEvent"]

log = logging.getLogger("istio_tpu.kube")


class AdmissionDenied(ValueError):
    """Raised by an admission hook to reject a write."""


class AlreadyExists(ValueError):
    """create() of an object that is already stored."""


@dataclasses.dataclass(frozen=True)
class WatchEvent:
    type: str              # ADDED | MODIFIED | DELETED
    obj: Mapping[str, Any]

    @property
    def kind(self) -> str:
        return str(self.obj.get("kind", ""))

    @property
    def name(self) -> str:
        return str(self.obj.get("metadata", {}).get("name", ""))

    @property
    def namespace(self) -> str:
        return str(self.obj.get("metadata", {}).get("namespace", ""))


WatchHandler = Callable[[WatchEvent], None]
AdmissionHook = Callable[[str, Mapping[str, Any]], None]  # (verb, obj)
# (verb, obj) → replacement obj or None (unchanged)
MutatingHook = Callable[[str, Mapping[str, Any]],
                        "Mapping[str, Any] | None"]


class FakeKubeCluster:
    def __init__(self) -> None:
        self._objs: dict[tuple[str, str, str], dict] = {}
        self._rv = 0
        self._uid = 0
        self._watchers: dict[str, list[WatchHandler]] = {}
        self._admission: list[tuple[frozenset | None, AdmissionHook]] = []
        self._mutating: list[tuple[frozenset | None, MutatingHook]] = []
        self._lock = threading.RLock()

    # -- admission --

    def register_admission(self, hook: AdmissionHook,
                           kinds: tuple[str, ...] | None = None) -> None:
        """Validating hook for `kinds` (None = all); runs pre-commit."""
        self._admission.append(
            (frozenset(kinds) if kinds is not None else None, hook))

    def register_mutating(self, hook: "MutatingHook",
                          kinds: tuple[str, ...] | None = None) -> None:
        """Mutating hook (the MutatingAdmissionWebhook role — sidecar
        injection): runs BEFORE validation, may return a replacement
        object (None = leave unchanged)."""
        self._mutating.append(
            (frozenset(kinds) if kinds is not None else None, hook))

    def _mutate(self, verb: str,
                obj: Mapping[str, Any]) -> Mapping[str, Any]:
        kind = str(obj.get("kind", ""))
        for kinds, hook in self._mutating:
            if kinds is None or kind in kinds:
                replaced = hook(verb, obj)
                if replaced is not None:
                    obj = replaced
        return obj

    def _admit(self, verb: str, obj: Mapping[str, Any]) -> None:
        kind = str(obj.get("kind", ""))
        for kinds, hook in self._admission:
            if kinds is None or kind in kinds:
                hook(verb, obj)

    # -- writes --

    def _key(self, obj: Mapping[str, Any]) -> tuple[str, str, str]:
        meta = obj.get("metadata") or {}
        kind = str(obj.get("kind", ""))
        if not kind or not meta.get("name"):
            raise ValueError("object needs kind + metadata.name")
        return (kind, str(meta.get("namespace", "")), str(meta["name"]))

    def create(self, obj: Mapping[str, Any]) -> dict:
        obj = self._mutate("CREATE", obj)
        self._admit("CREATE", obj)
        with self._lock:
            key = self._key(obj)
            if key in self._objs:
                raise AlreadyExists(f"{key} already exists")
            stored = self._commit(key, obj)
            self._notify(WatchEvent("ADDED", stored))
        return copy.deepcopy(stored)

    def update(self, obj: Mapping[str, Any]) -> dict:
        obj = self._mutate("UPDATE", obj)
        self._admit("UPDATE", obj)
        with self._lock:
            key = self._key(obj)
            if key not in self._objs:
                raise KeyError(key)
            stored = self._commit(key, obj,
                                  uid=self._objs[key]["metadata"]["uid"])
            self._notify(WatchEvent("MODIFIED", stored))
        return copy.deepcopy(stored)

    def apply(self, obj: Mapping[str, Any]) -> dict:
        """create-or-update convenience."""
        try:
            return self.create(obj)
        except AlreadyExists:
            return self.update(obj)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            stored = self._objs.pop((kind, namespace, name), None)
            if stored is not None:
                self._notify(WatchEvent("DELETED", stored))

    def _commit(self, key, obj: Mapping[str, Any],
                uid: str | None = None) -> dict:
        # deep copy in: a real API server serializes, so later caller
        # mutations must not alias stored state
        stored = copy.deepcopy(dict(obj))
        meta = dict(stored.get("metadata") or {})
        self._rv += 1
        if uid is None:
            self._uid += 1
            uid = f"uid-{self._uid}"
        meta["resourceVersion"] = str(self._rv)
        meta["uid"] = uid
        meta.setdefault("namespace", "")
        stored["metadata"] = meta
        self._objs[key] = stored
        return stored

    # -- reads (deep copies: consumers must not corrupt cluster state) --

    def get(self, kind: str, namespace: str, name: str) -> dict | None:
        with self._lock:
            obj = self._objs.get((kind, namespace, name))
            return copy.deepcopy(obj) if obj is not None else None

    def list(self, kind: str, namespace: str | None = None) -> list[dict]:
        with self._lock:
            return [copy.deepcopy(o)
                    for (k, ns, _), o in sorted(self._objs.items())
                    if k == kind and (namespace is None or ns == namespace)]

    # -- watch --

    def watch(self, kind: str, handler: WatchHandler,
              replay: bool = True) -> None:
        """list+watch: replay current state as ADDED, then stream.
        Replay + registration happen under the cluster lock, so no
        event between them is lost (mutators notify under the same
        lock; it is reentrant, so handlers may read the cluster)."""
        with self._lock:
            if replay:
                for (k, _, _), obj in sorted(self._objs.items()):
                    if k == kind:
                        self._safe_call(handler,
                                        WatchEvent("ADDED",
                                                   copy.deepcopy(obj)))
            self._watchers.setdefault(kind, []).append(handler)

    def unwatch(self, kind: str, handler: WatchHandler) -> None:
        """Deregister a watcher (informer shutdown); unknown handlers
        are ignored."""
        with self._lock:
            handlers = self._watchers.get(kind)
            if handlers and handler in handlers:
                handlers.remove(handler)

    def _notify(self, event: WatchEvent) -> None:
        for handler in list(self._watchers.get(event.kind, ())):
            self._safe_call(handler, dataclasses.replace(
                event, obj=copy.deepcopy(event.obj)))

    @staticmethod
    def _safe_call(handler: WatchHandler, event: WatchEvent) -> None:
        """Watcher isolation (informers never poison each other or the
        writer; same stance as runtime/store.py's delivery thread)."""
        try:
            handler(event)
        except Exception:
            log.exception("kube watch handler failed on %s %s/%s",
                          event.kind, event.namespace, event.name)
