"""Ingress controller — k8s Ingress → istio ingress-rule configs.

Reference: pilot/pkg/config/kube/ingress/{controller,conversion,
status}.go — watch Ingress resources, decompose each (host, path,
backend) tuple into one `ingress-rule` config named `<ingress>-<i>-<j>`,
keep the target config store in sync, and write the ingress gateway's
load-balancer address back into each watched resource's
status.loadBalancer (IngressStatusSyncer — kubectl and cloud
controllers read it to learn where traffic actually lands).

The emitted rules land in a pilot ConfigStore; the envoy config
generator's ingress route builder consumes them (pilot/routes.py).
"""
from __future__ import annotations

import ipaddress
import logging
from typing import Any, Mapping

from istio_tpu.kube.fake import FakeKubeCluster, WatchEvent
from istio_tpu.pilot.model import Config, ConfigMeta, ConfigStore

log = logging.getLogger("istio_tpu.kube.ingress")


def _backend_service(backend: Mapping[str, Any], namespace: str,
                     domain: str) -> tuple[str, Any]:
    name = str(backend.get("serviceName", ""))
    port = backend.get("servicePort", 80)
    host = f"{name}.{namespace or 'default'}.svc.{domain}"
    return host, port


class IngressController:
    def __init__(self, cluster: FakeKubeCluster, store: ConfigStore,
                 domain: str = "cluster.local",
                 ingress_class: str = "istio"):
        self.cluster = cluster
        self.store = store
        self.domain = domain
        self.ingress_class = ingress_class
        self._emitted: dict[tuple[str, str], list[str]] = {}
        cluster.watch("Ingress", self._on_event)

    def _should_process(self, obj: Mapping[str, Any]) -> bool:
        """conversion.go class check: kubernetes.io/ingress.class."""
        annotations = (obj.get("metadata") or {}).get("annotations") or {}
        cls = annotations.get("kubernetes.io/ingress.class")
        return cls is None or cls == self.ingress_class

    def _on_event(self, ev: WatchEvent) -> None:
        key = (ev.namespace, ev.name)
        # drop previously emitted rules for this ingress, then re-emit
        for rule_name in self._emitted.pop(key, []):
            self.store.delete("ingress-rule", rule_name, ev.namespace)
        if ev.type == "DELETED" or not self._should_process(ev.obj):
            return
        emitted = []
        for config in self._convert(ev.obj):
            self.store.create(config)
            emitted.append(config.meta.name)
        self._emitted[key] = emitted

    def _convert(self, obj: Mapping[str, Any]) -> list[Config]:
        """conversion.go ConvertIngress: one rule per (host, path)."""
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        namespace = str(meta.get("namespace", ""))
        name = str(meta.get("name", ""))
        out: list[Config] = []

        def rule(i: int, j: int, host: str, path: str,
                 backend: Mapping[str, Any]) -> Config:
            dest, port = _backend_service(backend, namespace, self.domain)
            spec_out: dict[str, Any] = {
                "destination": {"service": dest},
                "port": port,
            }
            if host:
                spec_out["match"] = {"request": {"headers": {
                    "authority": {"exact": host}}}}
            if path:
                kind = "prefix" if path.endswith("*") else "exact"
                value = path.rstrip("*") if kind == "prefix" else path
                spec_out.setdefault("match", {}).setdefault(
                    "request", {}).setdefault("headers", {})["uri"] = {
                        kind: value}
            return Config(meta=ConfigMeta(
                type="ingress-rule", name=f"{name}-{i}-{j}",
                namespace=namespace), spec=spec_out)

        default = spec.get("backend")
        if default:
            out.append(rule(0, 0, "", "", default))
        for i, r in enumerate(spec.get("rules") or (), start=1):
            host = str(r.get("host", "") or "")
            paths = ((r.get("http") or {}).get("paths")) or ()
            for j, p in enumerate(paths):
                out.append(rule(i, j, host, str(p.get("path", "") or ""),
                                p.get("backend") or {}))
        return out


class IngressStatusSyncer:
    """status.go analog — the part this module used to declare
    omitted: write the ingress gateway's external address into
    status.loadBalancer.ingress of every watched Ingress resource the
    mesh class owns. An IP address writes the `ip` field, anything
    else `hostname` (status.go's shape). Idempotent by comparison: a
    resource whose status already matches is left untouched — which
    is also what terminates the watch → update → watch loop this
    syncer rides (updates re-notify watchers, including itself)."""

    def __init__(self, cluster: FakeKubeCluster, address: str,
                 ingress_class: str = "istio"):
        self.cluster = cluster
        self.address = str(address)
        self.ingress_class = ingress_class
        cluster.watch("Ingress", self._on_event)

    def _desired(self) -> list[dict]:
        try:
            ipaddress.ip_address(self.address)
            key = "ip"
        except ValueError:
            key = "hostname"
        return [{key: self.address}]

    def _should_process(self, obj: Mapping[str, Any]) -> bool:
        annotations = (obj.get("metadata") or {}) \
            .get("annotations") or {}
        cls = annotations.get("kubernetes.io/ingress.class")
        return cls is None or cls == self.ingress_class

    def _on_event(self, ev: WatchEvent) -> None:
        if ev.type == "DELETED" or not self._should_process(ev.obj):
            return
        current = (((ev.obj.get("status") or {})
                    .get("loadBalancer") or {}).get("ingress")) or []
        desired = self._desired()
        if current == desired:
            return
        updated = dict(ev.obj)
        # merge, never replace: sibling status fields another
        # controller wrote (conditions etc.) must survive the patch
        # (status.go touches only the loadBalancer field)
        status = dict(updated.get("status") or {})
        lb = dict(status.get("loadBalancer") or {})
        lb["ingress"] = desired
        status["loadBalancer"] = lb
        updated["status"] = status
        try:
            self.cluster.update(updated)
        except Exception:   # conflict/raced delete: next event retries
            log.exception("ingress status write failed for %s/%s",
                          ev.namespace, ev.name)
