"""ServiceAccount → workload-cert secret controller over the cluster.

Reference: security/pkg/pki/ca/controller/secret.go — watch
ServiceAccounts; for each, mint a key + CA-signed SPIFFE cert and store
an `istio.io/key-and-cert` Secret named `istio.<sa>.<ns>`; delete the
secret when the SA goes away. This binds the platform-agnostic
SecretController (security/ca.py) to the kube watch + Secret storage.
"""
from __future__ import annotations

import base64

from istio_tpu.kube.fake import FakeKubeCluster, WatchEvent
from istio_tpu.security.ca import CertificateAuthority, SecretController


class ServiceAccountSecretController:
    def __init__(self, cluster: FakeKubeCluster,
                 ca: CertificateAuthority,
                 trust_domain: str = "cluster.local"):
        self.cluster = cluster
        self._bundles: dict = {}
        self._inner = SecretController(ca, self._bundles,
                                       trust_domain=trust_domain)
        cluster.watch("ServiceAccount", self._on_event)

    def _on_event(self, ev: WatchEvent) -> None:
        ns = ev.namespace or "default"
        if ev.type == "DELETED":
            self._inner.on_service_account(ns, ev.name, event="delete")
            self.cluster.delete(
                "Secret", ns, SecretController.secret_name(ns, ev.name))
            return
        self._inner.on_service_account(ns, ev.name)
        name = SecretController.secret_name(ns, ev.name)
        bundle = self._bundles[name]
        self.cluster.apply({
            "kind": "Secret",
            "metadata": {"name": name, "namespace": ns,
                         "annotations": {
                             "istio.io/identity": bundle["identity"]}},
            "type": bundle["type"],
            "data": {k: base64.b64encode(v).decode("ascii")
                     for k, v in bundle.items()
                     if isinstance(v, bytes)},
        })
