"""Kubernetes-shaped platform layer (SURVEY §2 L2), hermetic by design.

The reference's L2 is informer machinery over a live API server:
mixer/pkg/config/crd/store.go (config watch), pilot/pkg/serviceregistry/
kube/controller.go (service discovery), pilot/pkg/config/kube/crd/
client.go (pilot config), pilot/pkg/config/kube/ingress/ and
pilot/pkg/kube/admit/admit.go. This image has no cluster, so the same
contracts are implemented over `FakeKubeCluster` — an in-process API
server double with typed objects, resourceVersions, list+watch, and
validating-admission hooks — exactly the fake the reference's own unit
tests run against (k8s.io/client-go/testing fixtures).
"""
from istio_tpu.kube.fake import AdmissionDenied, FakeKubeCluster, WatchEvent
from istio_tpu.kube.crd import CrdStore, KubeConfigStore, ISTIO_CRD_KINDS
from istio_tpu.kube.registry import KubeServiceRegistry
from istio_tpu.kube.ingress import (IngressController,
                                    IngressStatusSyncer)
from istio_tpu.kube.admission import (register_analysis_admission,
                                      register_istio_admission)

__all__ = [
    "AdmissionDenied", "FakeKubeCluster", "WatchEvent",
    "CrdStore", "KubeConfigStore", "ISTIO_CRD_KINDS",
    "KubeServiceRegistry", "IngressController",
    "IngressStatusSyncer",
    "register_istio_admission", "register_analysis_admission",
]

try:
    # the SA-secret controller needs the PKI stack (`cryptography`);
    # containers without it keep the rest of the kube layer — config
    # watch, registries, admission (incl. the snapshot analyzer hook)
    from istio_tpu.kube.secrets import ServiceAccountSecretController
    __all__.append("ServiceAccountSecretController")
except ImportError:  # pragma: no cover - dependency-gated
    pass
