"""Validating admission for istio config kinds.

Reference: pilot/pkg/kube/admit/admit.go (ValidatingAdmissionWebhook
over pilot's schema validators) + mixer/pkg/config/crd/admit — bad
config is rejected at write time, before any controller sees it.
"""
from __future__ import annotations

from typing import Any, Mapping

from istio_tpu.expr.checker import TypeError_
from istio_tpu.expr.parser import ParseError, parse
from istio_tpu.kube.crd import ISTIO_CRD_KINDS
from istio_tpu.kube.fake import AdmissionDenied, FakeKubeCluster
from istio_tpu.pilot.inject import InjectParams, inject_pod
from istio_tpu.pilot.model import IstioConfigTypes, ValidationError


def _validate_pilot_kind(verb: str, obj: Mapping[str, Any]) -> None:
    schema = IstioConfigTypes[str(obj.get("kind"))]
    try:
        schema.validate(dict(obj.get("spec") or {}))
    except ValidationError as exc:
        raise AdmissionDenied(str(exc)) from exc


def _validate_mixer_kind(verb: str, obj: Mapping[str, Any]) -> None:
    """Structural checks on mixer kinds — the deep cross-resource
    validation (unknown handlers etc.) stays in SnapshotBuilder, which
    tolerates and reports; admission catches what is locally provable:
    rule match expressions must at least parse."""
    kind = str(obj.get("kind"))
    spec = dict(obj.get("spec") or {})
    if kind == "rule":
        match = str(spec.get("match", "") or "")
        if match:
            try:
                parse(match)
            except (ParseError, TypeError_) as exc:
                raise AdmissionDenied(
                    f"rule match does not parse: {exc}") from exc
        for action in spec.get("actions") or ():
            if not action.get("handler"):
                raise AdmissionDenied("rule action missing handler")
    elif kind == "handler":
        if not (spec.get("adapter") or spec.get("compiledAdapter")):
            raise AdmissionDenied("handler missing adapter")
    elif kind == "instance":
        if not (spec.get("template") or spec.get("compiledTemplate")):
            raise AdmissionDenied("instance missing template")


def register_istio_admission(cluster: FakeKubeCluster) -> None:
    """Install pilot + mixer validators on the cluster."""
    cluster.register_admission(_validate_pilot_kind,
                               kinds=tuple(IstioConfigTypes))
    cluster.register_admission(_validate_mixer_kind,
                               kinds=ISTIO_CRD_KINDS)


def register_sidecar_injector(cluster: FakeKubeCluster,
                              params: "InjectParams | None" = None,
                              namespaces: "tuple[str, ...] | None" = None
                              ) -> None:
    """The MutatingAdmissionWebhook role (pilot/pkg/kube/inject/
    webhook.go): pods created on the cluster get the sidecar + init
    containers injected per the annotation policy before commit.
    `namespaces` limits injection (None = all). CREATE only — real
    injection webhooks never fire on pod updates (a pod's container
    list is immutable)."""
    p = params or InjectParams()

    def mutate(verb: str, obj):
        if verb != "CREATE":
            return None
        if namespaces is not None:
            ns = str((obj.get("metadata") or {}).get("namespace", ""))
            if ns not in namespaces:
                return None
        return inject_pod(p, obj)

    cluster.register_mutating(mutate, kinds=("Pod",))
