"""Validating admission for istio config kinds.

Reference: pilot/pkg/kube/admit/admit.go (ValidatingAdmissionWebhook
over pilot's schema validators) + mixer/pkg/config/crd/admit — bad
config is rejected at write time, before any controller sees it.
Beyond the reference's per-object schema checks, the snapshot
analyzer hook (`register_analysis_admission`) runs the whole-snapshot
static verification from istio_tpu/analysis on the PROSPECTIVE store
(current CRD state + the incoming object) and rejects writes that
introduce ERROR-severity findings — shadowed rules, ALLOW/DENY
conflicts, type errors, NFA budget explosions — before any controller
compiles them toward the device.
"""
from __future__ import annotations

from typing import Any, Mapping

from istio_tpu.expr.checker import TypeError_
from istio_tpu.expr.parser import ParseError, parse
from istio_tpu.kube.crd import ISTIO_CRD_KINDS
from istio_tpu.kube.fake import AdmissionDenied, FakeKubeCluster
from istio_tpu.pilot.inject import InjectParams, inject_pod
from istio_tpu.pilot.model import IstioConfigTypes, ValidationError


def _validate_pilot_kind(verb: str, obj: Mapping[str, Any]) -> None:
    schema = IstioConfigTypes[str(obj.get("kind"))]
    try:
        schema.validate(dict(obj.get("spec") or {}))
    except ValidationError as exc:
        raise AdmissionDenied(str(exc)) from exc


def _validate_mixer_kind(verb: str, obj: Mapping[str, Any]) -> None:
    """Structural checks on mixer kinds — the deep cross-resource
    validation (unknown handlers etc.) stays in SnapshotBuilder, which
    tolerates and reports; admission catches what is locally provable:
    rule match expressions must at least parse."""
    kind = str(obj.get("kind"))
    spec = dict(obj.get("spec") or {})
    if kind == "rule":
        match = str(spec.get("match", "") or "")
        if match:
            try:
                parse(match)
            except (ParseError, TypeError_) as exc:
                raise AdmissionDenied(
                    f"rule match does not parse: {exc}") from exc
        for action in spec.get("actions") or ():
            if not action.get("handler"):
                raise AdmissionDenied("rule action missing handler")
    elif kind == "handler":
        if not (spec.get("adapter") or spec.get("compiledAdapter")):
            raise AdmissionDenied("handler missing adapter")
    elif kind == "instance":
        if not (spec.get("template") or spec.get("compiledTemplate")):
            raise AdmissionDenied("instance missing template")


def register_istio_admission(cluster: FakeKubeCluster) -> None:
    """Install pilot + mixer validators on the cluster."""
    cluster.register_admission(_validate_pilot_kind,
                               kinds=tuple(IstioConfigTypes))
    cluster.register_admission(_validate_mixer_kind,
                               kinds=ISTIO_CRD_KINDS)


def _store_from_cluster(cluster: FakeKubeCluster,
                        extra: Mapping[str, Any] | None = None):
    """Materialize the cluster's istio CRD objects (plus one incoming
    object, key-overriding) as a MemStore the SnapshotBuilder reads."""
    from istio_tpu.runtime.store import MemStore

    store = MemStore()
    for kind in ISTIO_CRD_KINDS:
        for obj in cluster.list(kind):
            meta = obj.get("metadata") or {}
            store.set((kind, str(meta.get("namespace", "")),
                       str(meta.get("name", ""))),
                      dict(obj.get("spec") or {}))
    if extra is not None:
        meta = extra.get("metadata") or {}
        store.set((str(extra.get("kind")),
                   str(meta.get("namespace", "")),
                   str(meta.get("name", ""))),
                  dict(extra.get("spec") or {}))
    return store


def register_analysis_admission(cluster: FakeKubeCluster,
                                default_manifest: Mapping[str, Any]
                                | None = None,
                                kinds: tuple[str, ...] = ("rule",),
                                pair_budget: int = 50_000) -> None:
    """Install the snapshot analyzer as a validating webhook.

    On every rule CREATE/UPDATE the PROSPECTIVE snapshot (current CRD
    state + the incoming object) is built and statically verified
    (istio_tpu/analysis); the write is denied when it introduces NEW
    ERROR-severity findings relative to the current state — so a
    shadowed rule, an ALLOW/DENY conflict, an ill-typed match or an
    NFA-budget explosion never reaches a controller. Pre-existing
    findings never block unrelated writes (delta semantics), and
    cross-resource "unknown refs" stay soft (creation order must keep
    working)."""
    from istio_tpu.analysis import analyze_store

    def _key(f) -> tuple:
        # message participates: config-error findings carry rules=()
        # and would otherwise collapse to one key, letting a NEW bad
        # rule ride in behind any pre-existing config error
        return (f.code, f.rules, f.message)

    # before-report memo keyed on the cluster's mutation counter: the
    # current-state analysis only changes when a write LANDS, so
    # applying N rules costs N analyses, not 2N (the before/after pair
    # re-analyzed the identical state on every admission otherwise)
    memo: dict[str, Any] = {}

    def validate(verb: str, obj: Mapping[str, Any]) -> None:
        if verb not in ("CREATE", "UPDATE"):
            return
        rv = getattr(cluster, "_rv", None)
        if rv is None or memo.get("rv") != rv:
            memo["report"] = analyze_store(
                _store_from_cluster(cluster),
                default_manifest=default_manifest,
                pair_budget=pair_budget)
            memo["rv"] = rv
        before = memo["report"]
        after = analyze_store(
            _store_from_cluster(cluster, extra=obj),
            default_manifest=default_manifest, pair_budget=pair_budget)
        seen = {_key(f) for f in before.errors}
        fresh = [f for f in after.errors if _key(f) not in seen]
        if fresh:
            lead = fresh[0]
            raise AdmissionDenied(
                f"snapshot analysis: {lead.code}: {lead.message}"
                + (f" (+{len(fresh) - 1} more)" if len(fresh) > 1
                   else ""))

    cluster.register_admission(validate, kinds=kinds)


def register_canary_admission(cluster: FakeKubeCluster,
                              corpus_fn,
                              default_manifest: Mapping[str, Any]
                              | None = None,
                              kinds: tuple[str, ...] = ("rule",
                                                        "handler",
                                                        "instance"),
                              max_divergence_rate: float = 0.0,
                              waivers: tuple[str, ...] = (),
                              buckets: tuple[int, ...] = (),
                              replay_limit: int = 1024,
                              identity_attr: str =
                              "destination.service") -> None:
    """Install the config canary's DYNAMIC replay check as a
    validating webhook, next to the static analysis admission.

    `kinds` defaults to every mixer kind that can flip a served
    decision — a handler doc edit (a denier's TTL, a list's overrides)
    diverges just as hard as a rule edit. DELETEs bypass this hook by
    FakeKubeCluster construction (delete() runs no admission), same as
    the static analysis admission; the Controller gate still catches a
    divergent post-delete snapshot. `identity_attr` must match the
    serving ServerArgs.identity_attr the corpus was recorded under —
    namespace visibility during replay follows it.

    On every covered CREATE/UPDATE the PROSPECTIVE snapshot (current
    CRD state + the incoming object) is compiled to a FusedPlan and the
    recorded live corpus (`corpus_fn()` → list[CanaryEntry]; typically
    a live runtime's `canary.recorder.corpus`, or `canary.load_corpus`
    over a saved file) is shadow-replayed through it. The write is
    denied when it introduces FRESH diverging rules relative to the
    current state — delta semantics like the analysis admission, so
    creation order keeps working: while the store is half-built the
    recorded corpus legitimately diverges, and only a write that makes
    a NEW rule flip recorded decisions (beyond `max_divergence_rate`
    of replayed rows) is rejected, with the typed CanaryRejected as
    the cause."""
    from istio_tpu.canary import (CanaryRejected, diff_decisions,
                                  replay_entries)
    from istio_tpu.runtime.config import SnapshotBuilder

    def _report(store, entries):
        from istio_tpu.runtime.fused import build_fused_plan
        snap = SnapshotBuilder(default_manifest).build(store)
        plan = build_fused_plan(snap, rule_telemetry=False)
        if plan is None:
            # zero-rule snapshot: everything checks OK. Diff against
            # the shared synthetic allow-everything replay so the
            # BEFORE baseline still names which recorded decisions a
            # rule-less store fails to reproduce — creation order then
            # admits each base rule (its divergence was already
            # "seen") while a genuinely fresh flip still registers as
            # new.
            from istio_tpu.canary.replay import allow_everything_replay
            replay = allow_everything_replay(len(entries))
        else:
            replay = replay_entries(snap, plan, entries,
                                    buckets=buckets,
                                    identity_attr=identity_attr)
        return diff_decisions(entries, replay, waivers=waivers)

    memo: dict[str, Any] = {}

    def validate(verb: str, obj: Mapping[str, Any]) -> None:
        if verb not in ("CREATE", "UPDATE"):
            return
        entries = list(corpus_fn() or ())[-replay_limit:]
        if not entries:
            return          # nothing recorded: nothing to judge
        rv = getattr(cluster, "_rv", None)
        # corpus fingerprint: a live ring at capacity keeps a constant
        # length while its CONTENT rotates under traffic — the memoed
        # 'before' must be diffed against the same rows, or rotated-in
        # divergences get misattributed to the incoming write
        fp = (len(entries), entries[0].t, entries[-1].t)
        if rv is None or memo.get("rv") != rv or \
                memo.get("fp") != fp:
            memo["before"] = _report(_store_from_cluster(cluster),
                                     entries)
            memo["rv"] = rv
            memo["fp"] = fp
        before = memo["before"]
        after = _report(_store_from_cluster(cluster, extra=obj),
                        entries)
        seen = set(before.per_rule)
        fresh = [r for r in after.diverging_rules() if r not in seen]
        fresh_rows = sum(after.per_rule[r]["total"] for r in fresh)
        rate = fresh_rows / max(after.n_rows, 1)
        if not fresh or rate <= max_divergence_rate:
            # admitted: the prospective state becomes the current one
            # at commit (FakeKubeCluster bumps _rv by 1), so this
            # `after` report IS the next write's `before` — seeding
            # the memo halves admission cost on ordered creates
            memo["before"] = after
            memo["rv"] = (rv or 0) + 1
            memo["fp"] = fp
            return
        rej = CanaryRejected(
            f"canary replay: {obj.get('kind')} "
            f"{(obj.get('metadata') or {}).get('name')} flips "
            f"{fresh_rows}/{after.n_rows} recorded live decisions "
            f"(rate {rate:.4f} > {max_divergence_rate}) — fresh "
            f"diverging rules: {', '.join(fresh[:5])}", after)
        raise AdmissionDenied(str(rej)) from rej

    cluster.register_admission(validate, kinds=kinds)


def register_sidecar_injector(cluster: FakeKubeCluster,
                              params: "InjectParams | None" = None,
                              namespaces: "tuple[str, ...] | None" = None
                              ) -> None:
    """The MutatingAdmissionWebhook role (pilot/pkg/kube/inject/
    webhook.go): pods created on the cluster get the sidecar + init
    containers injected per the annotation policy before commit.
    `namespaces` limits injection (None = all). CREATE only — real
    injection webhooks never fire on pod updates (a pod's container
    list is immutable)."""
    p = params or InjectParams()

    def mutate(verb: str, obj):
        if verb != "CREATE":
            return None
        if namespaces is not None:
            ns = str((obj.get("metadata") or {}).get("namespace", ""))
            if ns not in namespaces:
                return None
        return inject_pod(p, obj)

    cluster.register_mutating(mutate, kinds=("Pod",))
