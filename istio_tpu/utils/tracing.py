"""Control-plane tracing (reference: pkg/tracing/config.go:87-135
Configure — zipkin HTTP / log-only span reporters composed and wired
into the servers). Spans are zipkin-v2-shaped dicts; reporters are
pluggable: log_reporter (the reference's LogTraceSpans option),
MemoryReporter (tests), and ZipkinReporter — the v2 wire format
(JSON array POSTed to /api/v2/spans) over an injectable transport
(this image has no egress; tests drive a local HTTP sink).

The serving pipeline emits per-BATCH stage spans (queue-wait /
tensorize / device / overlay — runtime/dispatcher.py), so a served
check's latency is decomposable the way the reference's interceptor
chain makes its RPCs (mixer/pkg/server/server.go).
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import threading
import time
import urllib.request
import uuid
from typing import Any, Callable

log = logging.getLogger("istio_tpu.tracing")

Reporter = Callable[[dict], None]


def log_reporter(span: dict) -> None:
    log.info("span %s/%s %s %.3fms", span.get("traceId"),
             span.get("id"), span.get("name"),
             span.get("duration", 0) / 1000.0)


class MemoryReporter:
    def __init__(self) -> None:
        self.spans: list[dict] = []
        self._lock = threading.Lock()

    def __call__(self, span: dict) -> None:
        with self._lock:
            self.spans.append(span)


class RingReporter:
    """Bounded ring of the most recent finished spans — the backing
    store of the introspect server's /debug/traces endpoint (ControlZ's
    recent-activity role). Dropping the oldest under load is the
    point: introspection must never grow without bound."""

    def __init__(self, capacity: int = 256):
        import collections
        self._buf: "collections.deque[dict]" = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self.dropped = 0
        self._closed = False

    def __call__(self, span: dict) -> None:
        with self._lock:
            if self._closed:   # detached ring still in a live chain
                return
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(span)

    def snapshot(self, limit: int = 0) -> list[dict]:
        """Most-recent-last copy (capped at `limit` when > 0),
        ordered by span START time. The deque holds FINISH order —
        children land before their parents, and once the ring wraps a
        long-lived root can sit after spans that started (and
        finished) much later, so finish order is not chronological.
        Sorting by (timestamp, id) makes the view stable and
        chronological under wrap-around; the limit keeps the NEWEST
        spans, applied after the sort."""
        with self._lock:
            out = list(self._buf)
        out.sort(key=lambda s: (s.get("timestamp", 0),
                                str(s.get("id", ""))))
        return out[-limit:] if limit else out


def enable_ring(capacity: int = 256) -> RingReporter:
    """Attach a RingReporter to the GLOBAL tracer: composed with the
    existing reporter when one is configured, or installed as the sole
    reporter on the noop tracer (turning span recording ON — the
    introspect server wants recent spans even when no zipkin/log
    reporter is wired). A later configure() replaces the global tracer
    and detaches the ring; re-enable after reconfiguring. Undo with
    disable_ring(ring) — a closed introspect server must not leave
    span construction on the hot path."""
    global _global
    ring = RingReporter(capacity)
    prev = _global
    if prev.reporter is None:
        tracer = Tracer(service_name=prev.service_name, reporter=ring)
    else:
        tracer = Tracer(service_name=prev.service_name,
                        reporter=composite_reporter(ring,
                                                    prev.reporter))
    # restore tokens for disable_ring: the back-pointer chain lets a
    # later disable unwind past rings closed out of order. configure()
    # installs a tracer with no _ring back-pointer, so a newer owner's
    # stack is never unwound.
    ring._installed_over = prev
    tracer._ring = ring
    _global = tracer
    return ring


def disable_ring(ring: RingReporter) -> None:
    """Detach a ring installed by enable_ring: mark it closed (it may
    still sit inside a LIVE composite — a later-installed ring's
    chain) and unwind the global tracer past every tracer whose
    installing ring is closed. Handles non-LIFO close order: closing
    the last introspect server walks back past earlier-closed rings,
    so no dead ring is left constructing spans on the hot path. No-op
    when configure()/another owner has replaced the tracer."""
    global _global
    ring._closed = True
    while True:
        owner = getattr(_global, "_ring", None)
        if owner is None or not owner._closed:
            return
        _global = owner._installed_over


def parent_from_traceparent(header: str | None) -> dict | None:
    """W3C `traceparent` header → a parent-span dict usable as the
    `parent` of span()/start_span(), so server-side rpc.check roots
    (and every exemplar trace id hanging off them) join the CLIENT'S
    trace. Format (https://www.w3.org/TR/trace-context/):
    `version-traceid(32 hex)-parentid(16 hex)-flags`; malformed or
    all-zero ids return None and the caller self-generates ids as
    before."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    _ver, trace_id, span_id = parts[0], parts[1].lower(), \
        parts[2].lower()
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return {"traceId": trace_id, "id": span_id}


def _http_post_json(url: str, payload: bytes,
                    timeout_s: float = 5.0) -> int:
    req = urllib.request.Request(
        url, data=payload, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return r.status


class ZipkinReporter:
    """zipkin-v2 HTTP reporter: spans buffer and flush as a JSON array
    to `url` (POST /api/v2/spans — the wire format
    zipkin.NewHTTPTransport speaks in pkg/tracing/config.go:99).

    `post` is injectable (default urllib); flushing happens on a
    background thread every `flush_interval_s` or `max_batch` spans,
    and close() drains. Failures drop the batch with a log line —
    tracing must never stall serving."""

    def __init__(self, url: str,
                 post: Callable[[str, bytes], Any] | None = None,
                 flush_interval_s: float = 1.0, max_batch: int = 100):
        self.url = url
        self._post = post or _http_post_json
        self._buf: list[dict] = []
        self._lock = threading.Lock()
        self._closed = False
        self._interval = flush_interval_s
        self._max = max_batch
        self._wake = threading.Condition(self._lock)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="zipkin-reporter")
        self._thread.start()

    def __call__(self, span: dict) -> None:
        with self._lock:
            if self._closed:
                return
            self._buf.append(span)
            if len(self._buf) >= self._max:
                self._wake.notify()

    def _run(self) -> None:
        while True:
            with self._lock:
                self._wake.wait(timeout=self._interval)
                batch, self._buf = self._buf, []
                closed = self._closed
            if batch:
                try:
                    self._post(self.url, json.dumps(batch).encode())
                except Exception as exc:
                    log.warning("zipkin flush of %d spans failed: %s",
                                len(batch), exc)
            if closed:
                return

    def flush(self) -> None:
        with self._lock:
            self._wake.notify()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._wake.notify()
        self._thread.join(timeout=self._interval + 6)


def composite_reporter(*reporters: Reporter) -> Reporter:
    """jaeger.NewCompositeReporter analog (config.go:120)."""
    def report(span: dict) -> None:
        for r in reporters:
            try:
                r(span)
            except Exception:
                log.exception("span reporter failed")
    return report


@dataclasses.dataclass
class Tracer:
    service_name: str = "istio-tpu"
    reporter: Reporter | None = log_reporter   # None → disabled (noop)
    _local: threading.local = dataclasses.field(
        default_factory=threading.local)

    def _current(self) -> dict | None:
        return getattr(self._local, "span", None)

    # start_span/finish_span are the ONLY span-construction and
    # report sites; span() and emit() are thin wrappers (one place to
    # change the span shape, one place that guards the reporter).

    def start_span(self, name: str, parent: dict | None = None,
                   **tags: Any) -> dict | None:
        """Detached open span — for code that cannot hold a `with`
        block (asyncio handlers: a thread-local span held across an
        await would leak onto interleaved tasks). Does NOT touch the
        thread-local stack; pass the dict around explicitly
        (span(parent=...), finish_span). None when tracing is off."""
        if self.reporter is None:
            return None
        span = {
            "traceId": parent["traceId"] if parent
            else uuid.uuid4().hex[:16],
            "id": uuid.uuid4().hex[:16],
            "name": name,
            "localEndpoint": {"serviceName": self.service_name},
            "timestamp": int(time.time() * 1e6),
            "tags": {k: str(v) for k, v in tags.items()},
            "_t0": time.perf_counter(),
        }
        if parent:
            span["parentId"] = parent["id"]
        return span

    def finish_span(self, span: dict | None, **tags: Any) -> None:
        """Close + report a start_span() span (None-safe). Duration is
        measured from the open timestamp unless the span already
        carries one (emit's backdated intervals)."""
        if span is None or self.reporter is None:
            return
        t0 = span.pop("_t0", None)
        if t0 is not None and "duration" not in span:
            span["duration"] = int((time.perf_counter() - t0) * 1e6)
        if tags:
            span["tags"].update(
                {k: str(v) for k, v in tags.items()})
        try:
            self.reporter(span)
        except Exception:
            log.exception("span reporter failed")

    @contextlib.contextmanager
    def span(self, name: str, parent: dict | None = None, **tags: Any):
        """`parent` overrides the thread-local parent — cross-thread
        attribution (the batcher parenting its serve.batch span under
        the API layer's rpc.check root, which lives on the handler
        thread)."""
        if self.reporter is None:   # disabled: zero hot-path work
            yield None
            return
        prev = self._current()      # this THREAD's restore point —
        if parent is None:          # distinct from the LINK parent,
            parent = prev           # which may come from another
        span = self.start_span(name, parent=parent, **tags)
        self._local.span = span
        try:
            yield span
        except Exception as exc:
            span["tags"]["error"] = str(exc)
            raise
        finally:
            self._local.span = prev
            self.finish_span(span)

    def emit(self, name: str, duration_s: float, **tags: Any) -> None:
        """Fire-and-forget span for an already-measured interval —
        exception-safe instrumentation of code that cannot nest in a
        `with` block (multiple exits, hot paths)."""
        span = self.start_span(name, parent=self._current(), **tags)
        if span is None:
            return
        span["timestamp"] = int((time.time() - duration_s) * 1e6)
        span["duration"] = int(duration_s * 1e6)
        self.finish_span(span)


# -- global tracer (pkg/tracing's ot.SetGlobalTracer side effect) -----

NOOP_TRACER = Tracer(reporter=None)
_global = NOOP_TRACER
_closers: list = []


def configure(service_name: str, zipkin_url: str = "",
              log_spans: bool = False,
              post: Callable[[str, bytes], Any] | None = None) -> Tracer:
    """pkg/tracing/config.go:87 Configure: compose zipkin/log
    reporters (none configured → noop tracer), install globally.
    Reconfiguring closes the reporters it replaces (the reference's
    io.Closer contract) — otherwise every reload leaks a flush
    thread."""
    global _global
    for c in _closers:
        try:
            c.close()
        except Exception:
            log.exception("reporter close failed")
    _closers.clear()
    reporters: list[Reporter] = []
    if zipkin_url:
        zr = ZipkinReporter(zipkin_url, post=post)
        _closers.append(zr)
        reporters.append(zr)
    if log_spans:
        reporters.append(log_reporter)
    if not reporters:
        tracer = Tracer(service_name=service_name, reporter=None)
    elif len(reporters) == 1:
        tracer = Tracer(service_name=service_name,
                        reporter=reporters[0])
    else:
        tracer = Tracer(service_name=service_name,
                        reporter=composite_reporter(*reporters))
    _global = tracer
    return tracer


def get_tracer() -> Tracer:
    return _global


def capture(service_name: str = "capture"):
    """Temporarily swap in a MemoryReporter-backed tracer →
    (reporter, restore_fn). The bench uses it to decompose served
    latency into the pipeline's stage spans (queue-wait / tensorize /
    device / overlay) without a zipkin endpoint."""
    global _global
    prev = _global
    mem = MemoryReporter()
    _global = Tracer(service_name=service_name, reporter=mem)

    def restore() -> None:
        global _global
        _global = prev
    return mem, restore


def shutdown() -> None:
    global _global
    for c in _closers:
        try:
            c.close()
        except Exception:
            log.exception("reporter close failed")
    _closers.clear()
    _global = NOOP_TRACER
