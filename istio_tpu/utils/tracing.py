"""Control-plane tracing (reference: pkg/tracing/config.go:87
Configure — zipkin HTTP / log-only span reporters wired into gRPC
servers). Spans are zipkin-v2-shaped dicts; reporters are pluggable:
LogReporter (the reference's log-span option) and MemoryReporter
(tests). A zipkin HTTP reporter is a seam — this image has no egress.
"""
from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
import time
import uuid
from typing import Any, Callable

log = logging.getLogger("istio_tpu.tracing")

Reporter = Callable[[dict], None]


def log_reporter(span: dict) -> None:
    log.info("span %s/%s %s %.3fms", span.get("traceId"),
             span.get("id"), span.get("name"),
             span.get("duration", 0) / 1000.0)


class MemoryReporter:
    def __init__(self) -> None:
        self.spans: list[dict] = []
        self._lock = threading.Lock()

    def __call__(self, span: dict) -> None:
        with self._lock:
            self.spans.append(span)


@dataclasses.dataclass
class Tracer:
    service_name: str = "istio-tpu"
    reporter: Reporter = log_reporter
    _local: threading.local = dataclasses.field(
        default_factory=threading.local)

    def _current(self) -> dict | None:
        return getattr(self._local, "span", None)

    @contextlib.contextmanager
    def span(self, name: str, **tags: Any):
        parent = self._current()
        span = {
            "traceId": parent["traceId"] if parent
            else uuid.uuid4().hex[:16],
            "id": uuid.uuid4().hex[:16],
            "name": name,
            "localEndpoint": {"serviceName": self.service_name},
            "timestamp": int(time.time() * 1e6),
            "tags": {k: str(v) for k, v in tags.items()},
        }
        if parent:
            span["parentId"] = parent["id"]
        self._local.span = span
        t0 = time.perf_counter()
        try:
            yield span
        except Exception as exc:
            span["tags"]["error"] = str(exc)
            raise
        finally:
            span["duration"] = int((time.perf_counter() - t0) * 1e6)
            self._local.span = parent
            try:
                self.reporter(span)
            except Exception:
                log.exception("span reporter failed")
