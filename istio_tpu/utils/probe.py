"""File-touch liveness/readiness probes.

Role of the reference's pkg/probe (probe.go:30 Probe, controller.go:33
Controller): components register probes, a controller periodically writes a
file whose mtime freshness is the health signal; an external checker (k8s
exec probe) validates mtime staleness.
"""
from __future__ import annotations

import os
import threading
import time


class Probe:
    """A named health condition owned by one component."""

    def __init__(self, name: str = ""):
        self.name = name
        self._available: bool = False
        self._err: str = ""
        self._lock = threading.Lock()

    def set_available(self, err: str | None = None) -> None:
        with self._lock:
            self._available = err is None
            self._err = err or ""

    def is_available(self) -> tuple[bool, str]:
        with self._lock:
            return self._available, self._err


class ProbeController:
    """Aggregates probes; while ALL are available, keeps touching `path`
    every `interval` seconds."""

    def __init__(self, path: str, interval: float = 1.0):
        self.path = path
        self.interval = interval
        self._probes: list[Probe] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def register(self, probe: Probe) -> None:
        self._probes.append(probe)

    def status(self) -> tuple[bool, str]:
        for p in self._probes:
            ok, err = p.is_available()
            if not ok:
                return False, f"{p.name}: {err or 'unavailable'}"
        return True, ""

    def _tick(self) -> None:
        ok, _ = self.status()
        if ok:
            with open(self.path, "a"):
                os.utime(self.path, None)

    def start(self) -> None:
        def loop() -> None:
            while not self._stop.wait(self.interval):
                self._tick()
        self._tick()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)


def probe_fresh(path: str, max_age_seconds: float) -> bool:
    """External checker: is the probe file fresh? (reference: probe client.go)"""
    try:
        return (time.time() - os.stat(path).st_mtime) <= max_age_seconds
    except OSError:
        return False
