"""Build-info stamp (role of reference pkg/version/version.go)."""
from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class BuildInfo:
    version: str = "0.1.0"
    git_revision: str = os.environ.get("ISTIO_TPU_GIT_REV", "unknown")
    golden: str = "istio-ref-v0.4"  # reference parity anchor

    def long_form(self) -> str:
        return f"istio_tpu {self.version} (rev {self.git_revision}, parity {self.golden})"


BUILD_INFO = BuildInfo()
