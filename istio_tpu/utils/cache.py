"""Expiring caches (role of reference pkg/cache/{lruCache,ttlCache}.go)."""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Hashable


class LRUCache:
    """Bounded LRU cache, thread-safe. Tracks hit/miss stats like the
    reference's cache.Stats."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._capacity = capacity
        self._data: OrderedDict[Hashable, Any] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: Hashable, default: Any = None) -> Any:
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                self.hits += 1
                return self._data[key]
            self.misses += 1
            return default

    def set(self, key: Hashable, value: Any) -> None:
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
            self._data[key] = value
            if len(self._data) > self._capacity:
                self._data.popitem(last=False)
                self.evictions += 1

    def remove(self, key: Hashable) -> None:
        with self._lock:
            self._data.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)


class TTLCache:
    """Cache whose entries expire after a fixed TTL; expired entries are
    dropped lazily on access and by an optional sweep."""

    def __init__(self, ttl_seconds: float, capacity: int = 0,
                 clock: Any = time.monotonic):
        self._ttl = ttl_seconds
        self._capacity = capacity  # 0 = unbounded
        self._clock = clock
        self._data: OrderedDict[Hashable, tuple[float, Any]] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: Hashable, default: Any = None) -> Any:
        now = self._clock()
        with self._lock:
            item = self._data.get(key)
            if item is None or item[0] < now:
                if item is not None:
                    del self._data[key]
                self.misses += 1
                return default
            self.hits += 1
            return item[1]

    def set(self, key: Hashable, value: Any, ttl: float | None = None) -> None:
        exp = self._clock() + (ttl if ttl is not None else self._ttl)
        with self._lock:
            self._data[key] = (exp, value)
            self._data.move_to_end(key)
            if self._capacity and len(self._data) > self._capacity:
                self._data.popitem(last=False)

    def sweep(self) -> int:
        now = self._clock()
        with self._lock:
            dead = [k for k, (exp, _) in self._data.items() if exp < now]
            for k in dead:
                del self._data[k]
            return len(dead)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)
