"""Scoped structured logging.

Role of the reference's zap-backed ``pkg/log`` (pkg/log/log.go:20-25,
pkg/log/config.go): named scopes, level control per scope, optional JSON
output. Built on stdlib logging so it composes with anything.
"""
from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any

_FORMAT = "%(asctime)s\t%(levelname)s\t%(name)s\t%(message)s"
_configured = False


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload: dict[str, Any] = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
            "level": record.levelname.lower(),
            "scope": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload)


def configure_logging(level: str = "info", as_json: bool = False,
                      output_paths: list[str] | None = None) -> None:
    """Configure the root 'istio_tpu' logger (reference: log.Configure,
    pkg/log/config.go)."""
    global _configured
    root = logging.getLogger("istio_tpu")
    root.handlers.clear()
    handlers: list[logging.Handler] = []
    for path in output_paths or ["stderr"]:
        if path == "stderr":
            handlers.append(logging.StreamHandler(sys.stderr))
        elif path == "stdout":
            handlers.append(logging.StreamHandler(sys.stdout))
        else:
            handlers.append(logging.FileHandler(path))
    fmt: logging.Formatter = JSONFormatter() if as_json else logging.Formatter(_FORMAT)
    for h in handlers:
        h.setFormatter(fmt)
        root.addHandler(h)
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.propagate = False
    _configured = True


def scope(name: str) -> logging.Logger:
    """Return a named logging scope, e.g. scope('runtime')."""
    if not _configured:
        configure_logging()
    return logging.getLogger(f"istio_tpu.{name}")
