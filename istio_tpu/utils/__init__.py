"""Shared substrate (reference: pkg/log, pkg/probe, pkg/cache, pkg/version)."""

from istio_tpu.utils.log import scope, configure_logging
from istio_tpu.utils.cache import LRUCache, TTLCache
from istio_tpu.utils.metrics import (Counter, Gauge, Histogram, Registry,
                                     SlidingWindow, default_registry)
from istio_tpu.utils.probe import Probe, ProbeController, probe_fresh
from istio_tpu.utils.version import BUILD_INFO

__all__ = [
    "scope", "configure_logging", "LRUCache", "TTLCache",
    "Counter", "Gauge", "Histogram", "Registry", "SlidingWindow",
    "default_registry",
    "Probe", "ProbeController", "probe_fresh", "BUILD_INFO",
]
