"""Self-metrics: counters / gauges / histograms with Prometheus text
exposition.

Role of the reference's Prometheus self-monitoring (mixer/pkg/runtime/
monitor.go:34-88, pilot discovery.go:53-113). Host-side only — device-side
perf comes from the bench harness.
"""
from __future__ import annotations

import bisect
import collections
import threading
from typing import Iterable

_DEFAULT_BUCKETS = (
    1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


def _label_key(labels: dict[str, str] | None) -> tuple:
    return tuple(sorted((labels or {}).items()))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


def quantile_from_counts(buckets: tuple[float, ...],
                         counts: list[int], n: int, q: float) -> float:
    """Quantile (bucket upper bound) from a per-bucket count vector —
    shared by Histogram.quantile and delta-window readers that
    subtract two Histogram.state() snapshots."""
    if not counts or n <= 0:
        return 0.0
    target = q * n
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            return buckets[i] if i < len(buckets) else float("inf")
    return float("inf")


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def expose(self) -> Iterable[str]:
        if self.help:
            yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        with self._lock:
            snapshot = sorted(self._values.items())
        for key, v in snapshot:
            yield f"{self.name}{_fmt_labels(key)} {v}"


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value

    def value(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def expose(self) -> Iterable[str]:
        if self.help:
            yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        with self._lock:
            snapshot = sorted(self._values.items())
        for key, v in snapshot:
            yield f"{self.name}{_fmt_labels(key)} {v}"


class Histogram:
    def __init__(self, name: str, help_: str = "",
                 buckets: tuple[float, ...] = _DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self._buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sum: dict[tuple, float] = {}
        self._n: dict[tuple, int] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        idx = bisect.bisect_left(self._buckets, value)
        with self._lock:
            if key not in self._counts:
                self._counts[key] = [0] * (len(self._buckets) + 1)
                self._sum[key] = 0.0
                self._n[key] = 0
            self._counts[key][idx] += 1
            self._sum[key] += value
            self._n[key] += 1

    @property
    def buckets(self) -> tuple[float, ...]:
        return self._buckets

    def count(self, **labels: str) -> int:
        return self._n.get(_label_key(labels), 0)

    def sum(self, **labels: str) -> float:
        return self._sum.get(_label_key(labels), 0.0)

    def label_sets(self) -> list[dict]:
        with self._lock:
            return [dict(k) for k in self._counts]

    def state(self, **labels: str) -> tuple[list[int], float, int]:
        """(per-bucket counts copy, sum, n) for one label set — the
        subtraction token for windowed readings: two states taken
        around a phase delta to that phase's own histogram (histograms
        are process-lifetime cumulative by design)."""
        key = _label_key(labels)
        with self._lock:
            counts = list(self._counts.get(key, ()))
            return (counts, self._sum.get(key, 0.0),
                    self._n.get(key, 0))

    def quantile(self, q: float, **labels: str) -> float:
        """Approximate quantile from bucket counts (upper bound of the
        bucket containing the q-th observation)."""
        counts, _, n = self.state(**labels)
        return quantile_from_counts(self._buckets, counts, n, q)

    def expose(self) -> Iterable[str]:
        if self.help:
            yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            items = sorted((k, list(v), self._sum[k], self._n[k])
                           for k, v in self._counts.items())
        if not items:
            # exposition conformance: a histogram with no observations
            # must still emit its full zero series (_bucket ladder with
            # le="+Inf", _sum, _count) — scrapers treat a bare # TYPE
            # line with no samples as a malformed family
            items = [((), [0] * (len(self._buckets) + 1), 0.0, 0)]
        for key, counts, total, n in items:
            cum = 0
            for i, c in enumerate(counts[:-1]):
                cum += c
                lk = dict(key)
                lk["le"] = repr(self._buckets[i])
                yield f"{self.name}_bucket{_fmt_labels(_label_key(lk))} {cum}"
            lk = dict(key)
            lk["le"] = "+Inf"
            yield f"{self.name}_bucket{_fmt_labels(_label_key(lk))} {n}"
            yield f"{self.name}_sum{_fmt_labels(key)} {total}"
            yield f"{self.name}_count{_fmt_labels(key)} {n}"


class SlidingWindow:
    """Rolling window over the last `capacity` observations with exact
    quantiles computed on read (the live-p99 counterpart of Histogram's
    bucket-bounded quantile()). observe() is hot-path cheap (deque
    append under a lock); quantile() sorts a snapshot and is meant for
    scrape-rate readers (the introspect server, bench scrapes)."""

    def __init__(self, capacity: int = 4096):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._buf: collections.deque[float] = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self._total = 0

    def observe(self, value: float) -> None:
        with self._lock:
            self._buf.append(value)
            self._total += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    @property
    def total(self) -> int:
        """Observations ever seen (not just the ones still windowed)."""
        with self._lock:
            return self._total

    def reset(self) -> None:
        with self._lock:
            self._buf.clear()

    def quantile(self, q: float) -> float:
        qs = self.quantiles((q,))
        return qs[0]

    def quantiles(self, qs: Iterable[float]) -> list[float]:
        """Exact quantiles over the current window (one sort for all of
        them); empty window → zeros."""
        with self._lock:
            data = sorted(self._buf)
        if not data:
            return [0.0 for _ in qs]
        n = len(data)
        return [data[min(int(q * n), n - 1)] for q in qs]


class Registry:
    """Collects metrics for a /metrics endpoint (reference: mixer
    monitoring server on :9093, mixer/pkg/server/monitoring.go)."""

    def __init__(self) -> None:
        self._metrics: list[Counter | Gauge | Histogram] = []
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        m = Counter(name, help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def gauge(self, name: str, help_: str = "") -> Gauge:
        m = Gauge(name, help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def histogram(self, name: str, help_: str = "",
                  buckets: tuple[float, ...] = _DEFAULT_BUCKETS) -> Histogram:
        m = Histogram(name, help_, buckets)
        with self._lock:
            self._metrics.append(m)
        return m

    def expose_text(self) -> str:
        lines: list[str] = []
        with self._lock:
            for m in self._metrics:
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"


default_registry = Registry()
