"""Self-metrics: counters / gauges / histograms with Prometheus text
exposition.

Role of the reference's Prometheus self-monitoring (mixer/pkg/runtime/
monitor.go:34-88, pilot discovery.go:53-113). Host-side only — device-side
perf comes from the bench harness.
"""
from __future__ import annotations

import bisect
import threading
from typing import Iterable

_DEFAULT_BUCKETS = (
    1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


def _label_key(labels: dict[str, str] | None) -> tuple:
    return tuple(sorted((labels or {}).items()))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def expose(self) -> Iterable[str]:
        yield f"# TYPE {self.name} counter"
        with self._lock:
            snapshot = sorted(self._values.items())
        for key, v in snapshot:
            yield f"{self.name}{_fmt_labels(key)} {v}"


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value

    def value(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def expose(self) -> Iterable[str]:
        yield f"# TYPE {self.name} gauge"
        with self._lock:
            snapshot = sorted(self._values.items())
        for key, v in snapshot:
            yield f"{self.name}{_fmt_labels(key)} {v}"


class Histogram:
    def __init__(self, name: str, help_: str = "",
                 buckets: tuple[float, ...] = _DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self._buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sum: dict[tuple, float] = {}
        self._n: dict[tuple, int] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        idx = bisect.bisect_left(self._buckets, value)
        with self._lock:
            if key not in self._counts:
                self._counts[key] = [0] * (len(self._buckets) + 1)
                self._sum[key] = 0.0
                self._n[key] = 0
            self._counts[key][idx] += 1
            self._sum[key] += value
            self._n[key] += 1

    def quantile(self, q: float, **labels: str) -> float:
        """Approximate quantile from bucket counts (upper bound of the
        bucket containing the q-th observation)."""
        key = _label_key(labels)
        counts = self._counts.get(key)
        if not counts or self._n[key] == 0:
            return 0.0
        target = q * self._n[key]
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                return self._buckets[i] if i < len(self._buckets) else float("inf")
        return float("inf")

    def expose(self) -> Iterable[str]:
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            items = sorted((k, list(v), self._sum[k], self._n[k])
                           for k, v in self._counts.items())
        for key, counts, total, n in items:
            cum = 0
            for i, c in enumerate(counts[:-1]):
                cum += c
                lk = dict(key)
                lk["le"] = repr(self._buckets[i])
                yield f"{self.name}_bucket{_fmt_labels(_label_key(lk))} {cum}"
            lk = dict(key)
            lk["le"] = "+Inf"
            yield f"{self.name}_bucket{_fmt_labels(_label_key(lk))} {n}"
            yield f"{self.name}_sum{_fmt_labels(key)} {total}"
            yield f"{self.name}_count{_fmt_labels(key)} {n}"


class Registry:
    """Collects metrics for a /metrics endpoint (reference: mixer
    monitoring server on :9093, mixer/pkg/server/monitoring.go)."""

    def __init__(self) -> None:
        self._metrics: list[Counter | Gauge | Histogram] = []
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        m = Counter(name, help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def gauge(self, name: str, help_: str = "") -> Gauge:
        m = Gauge(name, help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def histogram(self, name: str, help_: str = "",
                  buckets: tuple[float, ...] = _DEFAULT_BUCKETS) -> Histogram:
        m = Histogram(name, help_, buckets)
        with self._lock:
            self._metrics.append(m)
        return m

    def expose_text(self) -> str:
        lines: list[str] = []
        with self._lock:
            for m in self._metrics:
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"


default_registry = Registry()
