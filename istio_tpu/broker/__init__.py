"""Broker — Open Service Broker API v2 skeleton (reference: broker/,
SURVEY.md §2.8, 3,371 LoC embryonic): catalog listing plus service
instance/binding CRUD over a config store, served as OSB v2 REST.
"""
from istio_tpu.broker.model import (BrokerConfigStore, Catalog,
                                    Service, ServiceBinding,
                                    ServiceInstance, ServicePlan)
from istio_tpu.broker.server import BrokerServer

__all__ = ["BrokerServer", "BrokerConfigStore", "Catalog", "Service",
           "ServicePlan", "ServiceInstance", "ServiceBinding"]
