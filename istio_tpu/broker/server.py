"""OSB v2 REST server.

Reference: broker/pkg/server/broker.go:37 CreateServer,
controller.go:41 Catalog, model/osb/* (catalog/service/servicePlan/
serviceInstance/serviceBinding shapes). Endpoints (OSB v2):

    GET    /v2/catalog
    PUT    /v2/service_instances/{id}
    GET    /v2/service_instances/{id}
    DELETE /v2/service_instances/{id}
    PUT    /v2/service_instances/{id}/service_bindings/{bid}
    DELETE /v2/service_instances/{id}/service_bindings/{bid}
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Mapping

log = logging.getLogger("istio_tpu.broker")


class BrokerServer:
    def __init__(self, services: list[Mapping[str, Any]] | None = None,
                 config_store=None):
        """Catalog sources, either of:
          * `config_store`: a BrokerConfigStore (broker/model.py) over
            the CRD/runtime config registry — service-class +
            service-plan kinds build the catalog per controller.go:48;
          * `services`: a literal catalog list (tests/CLI fixtures).
        Instances/bindings are typed OSB records (model.py
        ServiceInstance/ServiceBinding) persisted back into the config
        store when one is given."""
        from istio_tpu.broker.model import BrokerConfigStore

        self.config: BrokerConfigStore | None = config_store
        self._static_services = list(services or [])
        self._instances: dict[str, Any] = {}
        self._bindings: dict[tuple[str, str], Any] = {}
        self._lock = threading.Lock()
        self._server: ThreadingHTTPServer | None = None
        if config_store is not None:
            # rehydrate persisted instances/bindings — a restarted
            # broker must keep serving (and correctly 409/200-ing)
            # records provisioned by its predecessor
            from istio_tpu.broker.model import (ServiceBinding,
                                                ServiceInstance)
            for (_, _, name), spec in config_store.store.list(
                    "service-instance").items():
                self._instances[name] = ServiceInstance.from_request(
                    name, spec)
            for (_, _, name), spec in config_store.store.list(
                    "service-binding").items():
                iid = str(spec.get("service_instance_id", ""))
                bid = str(spec.get("id", ""))
                self._bindings[(iid, bid)] = ServiceBinding(
                    id=bid,
                    service_id=str(spec.get("service_id", "")),
                    app_id=str(spec.get("app_id", "")),
                    service_plan_id=str(
                        spec.get("service_plan_id", "")),
                    service_instance_id=iid)

    # -- operations (controller.go) --

    def get_catalog(self) -> dict:
        if self.config is not None:
            return self.config.catalog().to_wire()
        return {"services": self._static_services}

    def _known_services(self) -> set[str]:
        return {s["id"] for s in self.get_catalog()["services"]}

    def provision(self, instance_id: str, body: Mapping[str, Any]
                  ) -> tuple[int, dict]:
        from istio_tpu.broker.model import ServiceInstance

        inst = ServiceInstance.from_request(instance_id, body)
        with self._lock:
            prev = self._instances.get(instance_id)
            if prev is not None:
                if prev.to_wire() == inst.to_wire():
                    return 200, prev.provision_response()
                return 409, {"description": "instance exists"}
            if inst.service_id not in self._known_services():
                return 400, {"description": "unknown service_id"}
            self._instances[instance_id] = inst
            if self.config is not None:
                self.config.store.set(
                    ("service-instance", "", instance_id),
                    inst.to_wire())
        return 201, inst.provision_response()

    def deprovision(self, instance_id: str) -> tuple[int, dict]:
        with self._lock:
            if instance_id not in self._instances:
                return 410, {}
            del self._instances[instance_id]
            if self.config is not None:
                self.config.store.delete(
                    ("service-instance", "", instance_id))
            for key in [k for k in self._bindings
                        if k[0] == instance_id]:
                del self._bindings[key]
                if self.config is not None:
                    self.config.store.delete(
                        ("service-binding", "", f"{key[0]}.{key[1]}"))
        return 200, {}

    def bind(self, instance_id: str, binding_id: str,
             body: Mapping[str, Any]) -> tuple[int, dict]:
        from istio_tpu.broker.model import ServiceBinding

        with self._lock:
            if instance_id not in self._instances:
                return 404, {"description": "no such instance"}
            binding = ServiceBinding.from_request(instance_id,
                                                 binding_id, body)
            self._bindings[(instance_id, binding_id)] = binding
            if self.config is not None:
                self.config.store.set(
                    ("service-binding", "",
                     f"{instance_id}.{binding_id}"), binding.to_wire())
        return 201, binding.bind_response()

    def unbind(self, instance_id: str, binding_id: str) -> tuple[int, dict]:
        with self._lock:
            if (instance_id, binding_id) not in self._bindings:
                return 410, {}
            del self._bindings[(instance_id, binding_id)]
            if self.config is not None:
                self.config.store.delete(
                    ("service-binding", "",
                     f"{instance_id}.{binding_id}"))
        return 200, {}

    # -- HTTP --

    def start(self, address: str = "127.0.0.1", port: int = 0) -> int:
        broker = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                log.debug("broker: " + fmt, *args)

            def _reply(self, code: int, body: dict) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0) or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                parts = [p for p in self.path.split("/") if p]
                if parts == ["v2", "catalog"]:
                    self._reply(200, broker.get_catalog())
                elif len(parts) == 3 and parts[:2] == \
                        ["v2", "service_instances"]:
                    inst = broker._instances.get(parts[2])
                    self._reply(200 if inst else 404,
                                inst.to_wire() if inst else {})
                else:
                    self._reply(404, {})

            def do_PUT(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) == 3 and parts[:2] == \
                        ["v2", "service_instances"]:
                    self._reply(*broker.provision(parts[2], self._body()))
                elif len(parts) == 5 and parts[3] == "service_bindings":
                    self._reply(*broker.bind(parts[2], parts[4],
                                             self._body()))
                else:
                    self._reply(404, {})

            def do_DELETE(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) == 3 and parts[:2] == \
                        ["v2", "service_instances"]:
                    self._reply(*broker.deprovision(parts[2]))
                elif len(parts) == 5 and parts[3] == "service_bindings":
                    self._reply(*broker.unbind(parts[2], parts[4]))
                else:
                    self._reply(404, {})

        self._server = ThreadingHTTPServer((address, port), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="broker").start()
        self.port = self._server.server_address[1]
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
