"""OSB v2 REST server.

Reference: broker/pkg/server/broker.go:37 CreateServer,
controller.go:41 Catalog, model/osb/* (catalog/service/servicePlan/
serviceInstance/serviceBinding shapes). Endpoints (OSB v2):

    GET    /v2/catalog
    PUT    /v2/service_instances/{id}
    GET    /v2/service_instances/{id}
    DELETE /v2/service_instances/{id}
    PUT    /v2/service_instances/{id}/service_bindings/{bid}
    DELETE /v2/service_instances/{id}/service_bindings/{bid}
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Mapping

log = logging.getLogger("istio_tpu.broker")


class BrokerServer:
    def __init__(self, services: list[Mapping[str, Any]] | None = None):
        """`services` is the catalog: [{id, name, description, plans:
        [{id, name, description}], bindable}] (osb/catalog.go)."""
        self.catalog = {"services": list(services or [])}
        self._instances: dict[str, dict] = {}
        self._bindings: dict[tuple[str, str], dict] = {}
        self._lock = threading.Lock()
        self._server: ThreadingHTTPServer | None = None

    # -- operations (controller.go) --

    def get_catalog(self) -> dict:
        return self.catalog

    def provision(self, instance_id: str, body: Mapping[str, Any]
                  ) -> tuple[int, dict]:
        with self._lock:
            if instance_id in self._instances:
                if self._instances[instance_id] == dict(body):
                    return 200, {}
                return 409, {"description": "instance exists"}
            known = {s["id"] for s in self.catalog["services"]}
            if body.get("service_id") not in known:
                return 400, {"description": "unknown service_id"}
            self._instances[instance_id] = dict(body)
        return 201, {}

    def deprovision(self, instance_id: str) -> tuple[int, dict]:
        with self._lock:
            if instance_id not in self._instances:
                return 410, {}
            del self._instances[instance_id]
            for key in [k for k in self._bindings
                        if k[0] == instance_id]:
                del self._bindings[key]
        return 200, {}

    def bind(self, instance_id: str, binding_id: str,
             body: Mapping[str, Any]) -> tuple[int, dict]:
        with self._lock:
            if instance_id not in self._instances:
                return 404, {"description": "no such instance"}
            self._bindings[(instance_id, binding_id)] = dict(body)
        return 201, {"credentials": {}}

    def unbind(self, instance_id: str, binding_id: str) -> tuple[int, dict]:
        with self._lock:
            if (instance_id, binding_id) not in self._bindings:
                return 410, {}
            del self._bindings[(instance_id, binding_id)]
        return 200, {}

    # -- HTTP --

    def start(self, address: str = "127.0.0.1", port: int = 0) -> int:
        broker = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                log.debug("broker: " + fmt, *args)

            def _reply(self, code: int, body: dict) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0) or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                parts = [p for p in self.path.split("/") if p]
                if parts == ["v2", "catalog"]:
                    self._reply(200, broker.get_catalog())
                elif len(parts) == 3 and parts[:2] == \
                        ["v2", "service_instances"]:
                    inst = broker._instances.get(parts[2])
                    self._reply(200 if inst else 404, inst or {})
                else:
                    self._reply(404, {})

            def do_PUT(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) == 3 and parts[:2] == \
                        ["v2", "service_instances"]:
                    self._reply(*broker.provision(parts[2], self._body()))
                elif len(parts) == 5 and parts[3] == "service_bindings":
                    self._reply(*broker.bind(parts[2], parts[4],
                                             self._body()))
                else:
                    self._reply(404, {})

            def do_DELETE(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) == 3 and parts[:2] == \
                        ["v2", "service_instances"]:
                    self._reply(*broker.deprovision(parts[2]))
                elif len(parts) == 5 and parts[3] == "service_bindings":
                    self._reply(*broker.unbind(parts[2], parts[4]))
                else:
                    self._reply(404, {})

        self._server = ThreadingHTTPServer((address, port), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="broker").start()
        self.port = self._server.server_address[1]
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
