"""Broker data model: OSB v2 types + the broker config store.

References:
  * `broker/pkg/model/osb/{catalog,service,servicePlan,serviceInstance,
    serviceBinding}.go` — the wire dataclasses with their exact JSON
    field names (the OSB v2 contract with cloud-controller clients);
  * `broker/pkg/model/config/{schema,store}.go` — the config schema
    pair (service-class / service-plan, group config.istio.io,
    version v1alpha2, DNS-1123 names) and the BrokerConfigStore
    adapter over the generic config registry. Here the generic
    registry is the SAME runtime Store the mixer/pilot layers use
    (runtime/store.py MemStore / kube CRD store), so broker config
    rides etcd/CRDs exactly like every other kind.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Mapping

from istio_tpu.runtime.store import Store

# config.istio.io/v1alpha2 (model/config/store.go:106-111)
ISTIO_API_GROUP = "config.istio.io"
ISTIO_API_VERSION = "v1alpha2"
KIND_SERVICE_CLASS = "service-class"
KIND_SERVICE_PLAN = "service-plan"

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_MAX_LABEL = 63


class BrokerConfigError(ValueError):
    """Schema validation failure (model/config/schema.go Validate)."""


def validate_config_name(name: str) -> None:
    """DNS-1123 label rule (schema.go dns1123LabelRex)."""
    if len(name) > _MAX_LABEL or not _DNS1123.match(name):
        raise BrokerConfigError(f"invalid config name {name!r} "
                                "(must be a DNS-1123 label)")


# ---------------------------------------------------------------------------
# OSB wire types (osb/*.go — field names are the OSB v2 contract)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ServicePlan:
    """osb/servicePlan.go ServicePlan."""
    name: str = ""
    id: str = ""
    description: str = ""
    metadata: Any = None
    free: bool = False

    def to_wire(self) -> dict:
        out = {"name": self.name, "id": self.id,
               "description": self.description}
        if self.metadata is not None:
            out["metadata"] = self.metadata
        if self.free:
            out["free"] = self.free
        return out

    @classmethod
    def from_config(cls, spec: Mapping[str, Any]) -> "ServicePlan":
        """osb/servicePlan.go NewServicePlan: reads the nested
        `plan` CatalogPlan entry."""
        p = spec.get("plan") or {}
        return cls(name=str(p.get("name", "")), id=str(p.get("id", "")),
                   description=str(p.get("description", "")))


@dataclasses.dataclass
class Service:
    """osb/service.go Service."""
    name: str = ""
    id: str = ""
    description: str = ""
    bindable: bool = False
    plan_updateable: bool = False
    tags: tuple = ()
    requires: tuple = ()
    metadata: Any = None
    plans: list = dataclasses.field(default_factory=list)
    dashboard_client: Any = None

    def add_plan(self, plan: ServicePlan) -> None:
        self.plans.append(plan)

    def to_wire(self) -> dict:
        out = {"name": self.name, "id": self.id,
               "description": self.description,
               "bindable": self.bindable,
               "plans": [p.to_wire() for p in self.plans],
               "dashboard_client": self.dashboard_client}
        if self.plan_updateable:
            out["plan_updateable"] = True
        if self.tags:
            out["tags"] = list(self.tags)
        if self.requires:
            out["requires"] = list(self.requires)
        if self.metadata is not None:
            out["metadata"] = self.metadata
        return out

    @classmethod
    def from_config(cls, spec: Mapping[str, Any]) -> "Service":
        """osb/service.go NewService: reads the `entry` CatalogEntry."""
        e = spec.get("entry") or {}
        return cls(name=str(e.get("name", "")), id=str(e.get("id", "")),
                   description=str(e.get("description", "")))


@dataclasses.dataclass
class Catalog:
    """osb/catalog.go Catalog."""
    services: list = dataclasses.field(default_factory=list)

    def add_service(self, service: Service) -> None:
        self.services.append(service)

    def to_wire(self) -> dict:
        return {"services": [s.to_wire() for s in self.services]}


@dataclasses.dataclass
class LastOperation:
    """osb/serviceInstance.go LastOperation."""
    state: str = ""
    description: str = ""
    async_poll_interval_seconds: int = 0

    def to_wire(self) -> dict:
        out = {"state": self.state, "description": self.description}
        if self.async_poll_interval_seconds:
            out["async_poll_interval_seconds"] = \
                self.async_poll_interval_seconds
        return out


@dataclasses.dataclass
class ServiceInstance:
    """osb/serviceInstance.go ServiceInstance."""
    id: str = ""
    dashboard_url: str = ""
    internal_id: str = ""
    service_id: str = ""
    plan_id: str = ""
    organization_guid: str = ""
    space_guid: str = ""
    last_operation: LastOperation | None = None
    parameters: Any = None

    @classmethod
    def from_request(cls, instance_id: str,
                     body: Mapping[str, Any]) -> "ServiceInstance":
        return cls(id=instance_id,
                   service_id=str(body.get("service_id", "")),
                   plan_id=str(body.get("plan_id", "")),
                   organization_guid=str(
                       body.get("organization_guid", "")),
                   space_guid=str(body.get("space_guid", "")),
                   parameters=body.get("parameters"))

    def to_wire(self) -> dict:
        out = {"id": self.id, "dashboard_url": self.dashboard_url,
               "service_id": self.service_id, "plan_id": self.plan_id,
               "organization_guid": self.organization_guid,
               "space_guid": self.space_guid}
        if self.internal_id:
            out["internalId"] = self.internal_id
        if self.last_operation is not None:
            out["last_operation"] = self.last_operation.to_wire()
        if self.parameters is not None:
            out["parameters"] = self.parameters
        return out

    def provision_response(self) -> dict:
        """osb/serviceInstance.go CreateServiceInstanceResponse."""
        out = {"dashboard_url": self.dashboard_url}
        if self.last_operation is not None:
            out["last_operation"] = self.last_operation.to_wire()
        return out


@dataclasses.dataclass
class ServiceBinding:
    """osb/serviceBinding.go ServiceBinding."""
    id: str = ""
    service_id: str = ""
    app_id: str = ""
    service_plan_id: str = ""
    private_key: str = ""
    service_instance_id: str = ""

    @classmethod
    def from_request(cls, instance_id: str, binding_id: str,
                     body: Mapping[str, Any]) -> "ServiceBinding":
        return cls(id=binding_id,
                   service_id=str(body.get("service_id", "")),
                   app_id=str(body.get("app_guid",
                                       body.get("app_id", ""))),
                   service_plan_id=str(body.get("plan_id", "")),
                   service_instance_id=instance_id)

    def to_wire(self) -> dict:
        return {"id": self.id, "service_id": self.service_id,
                "app_id": self.app_id,
                "service_plan_id": self.service_plan_id,
                "private_key": self.private_key,
                "service_instance_id": self.service_instance_id}

    def bind_response(self, credentials: Any = None) -> dict:
        """osb/serviceBinding.go CreateServiceBindingResponse."""
        return {"credentials": credentials or {}}


# ---------------------------------------------------------------------------
# Broker config store (model/config/store.go BrokerConfigStore)
# ---------------------------------------------------------------------------

def validate_service_class(spec: Mapping[str, Any]) -> None:
    e = spec.get("entry") or {}
    if not e.get("name") or not e.get("id"):
        raise BrokerConfigError("service-class: entry.name and "
                                "entry.id are required")


def validate_service_plan(spec: Mapping[str, Any]) -> None:
    p = spec.get("plan") or {}
    if not p.get("name") or not p.get("id"):
        raise BrokerConfigError("service-plan: plan.name and plan.id "
                                "are required")
    svcs = spec.get("services")
    if svcs is not None and not isinstance(svcs, (list, tuple)):
        raise BrokerConfigError("service-plan: services must be a list")


_VALIDATORS = {KIND_SERVICE_CLASS: validate_service_class,
               KIND_SERVICE_PLAN: validate_service_plan}


class BrokerConfigStore:
    """Typed accessors over the generic runtime Store
    (model/config/store.go MakeBrokerConfigStore). Keys are
    (kind, namespace, name); `set` validates against the kind schema
    like schema.go Validate."""

    def __init__(self, store: Store):
        self.store = store

    def set(self, kind: str, namespace: str, name: str,
            spec: Mapping[str, Any]) -> None:
        if kind not in _VALIDATORS:
            raise BrokerConfigError(f"unknown broker kind {kind!r}")
        validate_config_name(name)
        _VALIDATORS[kind](spec)
        self.store.set((kind, namespace, name), dict(spec))

    def service_classes(self) -> dict[str, Mapping[str, Any]]:
        return {f"{k[1]}/{k[2]}": v
                for k, v in self.store.list(KIND_SERVICE_CLASS).items()}

    def service_plans(self) -> dict[str, Mapping[str, Any]]:
        return {f"{k[1]}/{k[2]}": v
                for k, v in self.store.list(KIND_SERVICE_PLAN).items()}

    def service_plans_by_service(self, service_key: str
                                 ) -> dict[str, Mapping[str, Any]]:
        """Plans whose `services` list names the class key
        (store.go ServicePlansByService)."""
        out = {}
        for key, plan in self.service_plans().items():
            for s in plan.get("services") or ():
                if s == service_key or s == service_key.split("/")[-1]:
                    out[key] = plan
                    break
        return out

    def catalog(self) -> Catalog:
        """controller.go:48 — classes + their plans → OSB catalog."""
        cat = Catalog()
        for key, cls_spec in sorted(self.service_classes().items()):
            svc = Service.from_config(cls_spec)
            for _, plan_spec in sorted(
                    self.service_plans_by_service(key).items()):
                svc.add_plan(ServicePlan.from_config(plan_spec))
            cat.add_service(svc)
        return cat
