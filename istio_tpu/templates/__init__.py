"""Template framework + the built-in template inventory.

Role of the reference's mixer/pkg/template + mixer/template/* (SURVEY.md
§2.4): a template defines the typed schema of instances handed to
adapters, how instance fields are inferred/type-checked against the
attribute vocabulary, and how a config instance (field → expression) is
materialized per request.

The reference generates ~5,500 LoC of Go per-template plumbing
(template.gen.go) with a codegen tool; here templates are declarative
`TemplateInfo` records + one generic evaluator (framework.py) — Python
metaprogramming replaces codegen (SURVEY.md §7 layer 5).

Inventory (reference mixer/template/<name>/template.proto):
  apikey, authorization, checknothing, listentry, logentry, metric,
  quota, reportnothing, tracespan.
"""
from istio_tpu.templates.framework import (InstanceBuilder, TemplateError,
                                           TemplateInfo, Variety, registry)
from istio_tpu.templates import builtin as _builtin  # registers inventory

__all__ = ["TemplateInfo", "Variety", "InstanceBuilder", "TemplateError",
           "registry"]
