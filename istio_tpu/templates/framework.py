"""Generic template machinery.

One `TemplateInfo` per template replaces the reference's generated
InferTypeFn/SetTypeFn/ProcessXxxFn triple (mixer/template/
template.gen.go, framework types mixer/pkg/template/template.go:35-110):

  * `infer_types`  — type-check an instance config's field expressions
    against the attribute vocabulary, producing the inferred instance
    type handed to adapter builders (reference InferTypeFn).
  * `InstanceBuilder` — compile an instance config's expressions once,
    then materialize an Instance per attribute bag (reference
    ProcessCheckFn/ProcessReportFn instance construction; evaluation
    errors abort the instance exactly like errorpath.go).

Field schemas support scalar expression fields, expression maps
(`dimensions`, `labels`), and nested sub-messages (authorization's
Subject/Action). `value_type` fields are dynamically typed: their
declared type is V.VALUE (any) and the INFERRED type is recorded, which
is exactly how the reference's metric/quota templates carry
value/dimension types to adapters.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Mapping

from istio_tpu.attribute.bag import Bag
from istio_tpu.attribute.types import ValueType
from istio_tpu.expr.checker import (AttributeDescriptorFinder, DEFAULT_FUNCS,
                                    TypeError_, eval_type)
from istio_tpu.expr.oracle import EvalError, OracleProgram
from istio_tpu.expr.parser import ParseError, parse

V = ValueType


class Variety(enum.Enum):
    """mixer/pkg/adapter TemplateVariety."""
    CHECK = "TEMPLATE_VARIETY_CHECK"
    REPORT = "TEMPLATE_VARIETY_REPORT"
    QUOTA = "TEMPLATE_VARIETY_QUOTA"
    ATTRIBUTE_GENERATOR = "TEMPLATE_VARIETY_ATTRIBUTE_GENERATOR"


class TemplateError(ValueError):
    """Instance config does not satisfy the template schema."""


@dataclasses.dataclass(frozen=True)
class Field:
    """One instance field: a fixed expected type, V.UNSPECIFIED for
    dynamic (value_type) fields, or a map/submessage marker."""
    name: str
    type: ValueType | None = None     # None → submessage or expr-map
    expr_map: bool = False            # map[string]expr (dimensions/labels)
    submessage: tuple["Field", ...] | None = None
    required: bool = False
    default: Any = None
    # additional accepted expression types beyond `type` (e.g.
    # listentry.value accepts IP_ADDRESS so IP lists can check
    # `source.ip` directly — the wire carries IPs as bytes and the
    # list adapter normalizes them, list_adapter.handle_check)
    accepts: tuple[ValueType, ...] = ()


@dataclasses.dataclass(frozen=True)
class TemplateInfo:
    """Declarative template descriptor (reference template.Info)."""
    name: str
    variety: Variety
    fields: tuple[Field, ...]
    description: str = ""

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)


class _Registry:
    def __init__(self) -> None:
        self._by_name: dict[str, TemplateInfo] = {}

    def register(self, info: TemplateInfo) -> TemplateInfo:
        self._by_name[info.name] = info
        return info

    def get(self, name: str) -> TemplateInfo:
        info = self._by_name.get(name)
        if info is None:
            raise TemplateError(f"unknown template: {name}")
        return info

    def names(self) -> list[str]:
        return sorted(self._by_name)


registry = _Registry()


# ---------------------------------------------------------------------------
# Type inference (reference InferTypeFn)
# ---------------------------------------------------------------------------

def infer_types(info: TemplateInfo, params: Mapping[str, Any],
                finder: AttributeDescriptorFinder) -> dict[str, Any]:
    """Validate `params` (field → expression text / nested dict) against
    the template schema; returns the inferred type structure (field →
    ValueType | {key → ValueType} | nested dict) that adapter builders
    receive (reference SetTypeFn payload)."""
    inferred: dict[str, Any] = {}
    unknown = set(params) - {f.name for f in info.fields}
    if unknown:
        raise TemplateError(
            f"template {info.name}: unknown fields {sorted(unknown)}")
    for f in info.fields:
        raw = params.get(f.name, None)
        if raw is None:
            if f.required:
                raise TemplateError(
                    f"template {info.name}: missing required field {f.name}")
            continue
        try:
            if f.submessage is not None:
                if not isinstance(raw, Mapping):
                    raise TemplateError(
                        f"{info.name}.{f.name}: expected a message")
                sub = TemplateInfo(name=f"{info.name}.{f.name}",
                                   variety=info.variety, fields=f.submessage)
                inferred[f.name] = infer_types(sub, raw, finder)
            elif f.expr_map:
                if not isinstance(raw, Mapping):
                    raise TemplateError(
                        f"{info.name}.{f.name}: expected map of expressions")
                inferred[f.name] = {
                    k: eval_type(parse(v), finder, DEFAULT_FUNCS)
                    for k, v in raw.items()}
            else:
                t = eval_type(parse(raw), finder, DEFAULT_FUNCS)
                if f.type is not V.UNSPECIFIED and t != f.type \
                        and t not in f.accepts:
                    raise TemplateError(
                        f"{info.name}.{f.name}: expression '{raw}' has type "
                        f"{t.name}, expected {f.type.name}")
                inferred[f.name] = t
        except (ParseError, TypeError_) as exc:
            raise TemplateError(
                f"{info.name}.{f.name}: {exc}") from exc
    return inferred


# ---------------------------------------------------------------------------
# Instance construction (reference ProcessXxxFn instance build half)
# ---------------------------------------------------------------------------

def plain_attr_ref(ast) -> Any | None:
    """attr name / (map, const key) if the expression is a bare
    attribute reference; None otherwise. The fused serving plan uses
    this to decide whether an instance field can become a device slot
    read (runtime/fused.py)."""
    if ast.var is not None:
        return ast.var.name
    f = ast.fn
    if (f is not None and f.name == "INDEX" and f.args[0].var is not None
            and f.args[1].const_ is not None):
        return (f.args[0].var.name, f.args[1].const_.value)
    return None


def _collect_attrs(e, out: set) -> None:
    """Attribute names + (map, const-key) pairs an expression reads."""
    if e.var is not None:
        out.add(e.var.name)
        return
    f = e.fn
    if f is None:
        return
    if (f.name == "INDEX" and f.args[0].var is not None
            and f.args[1].const_ is not None):
        out.add(f.args[0].var.name)
        out.add((f.args[0].var.name, f.args[1].const_.value))
        return
    if f.target is not None:
        _collect_attrs(f.target, out)
    for a in f.args:
        _collect_attrs(a, out)


class InstanceBuilder:
    """Compiles one instance config's expressions; `build(bag)` →
    instance dict. Evaluation failure raises EvalError (the dispatcher
    converts it to the adapter-skipping error path, errorpath.go)."""

    def __init__(self, info: TemplateInfo, name: str,
                 params: Mapping[str, Any],
                 finder: AttributeDescriptorFinder):
        self.info = info
        self.name = name
        self.inferred = infer_types(info, params, finder)
        # attributes (incl. (map, key) pairs) this instance's field
        # expressions read — feeds ReferencedAttributes (protoBag.go:117)
        self.referenced_attrs: set = set()
        self._plan = self._compile(info.fields, params, finder)

    def _compile(self, fields: tuple[Field, ...], params: Mapping[str, Any],
                 finder: AttributeDescriptorFinder) -> list[tuple]:
        plan: list[tuple] = []
        for f in fields:
            raw = params.get(f.name, None)
            if raw is None:
                if f.default is not None:
                    plan.append((f.name, "const", f.default))
                continue
            if f.submessage is not None:
                plan.append((f.name, "sub",
                             self._compile(f.submessage, raw, finder)))
            elif f.expr_map:
                progs = {k: OracleProgram(v, finder)
                         for k, v in raw.items()}
                for p in progs.values():
                    _collect_attrs(p.ast, self.referenced_attrs)
                plan.append((f.name, "map", progs))
            else:
                prog = OracleProgram(raw, finder)
                _collect_attrs(prog.ast, self.referenced_attrs)
                plan.append((f.name, "expr", prog))
        return plan

    def build(self, bag: Bag) -> dict[str, Any]:
        return self._run(self._plan, bag)

    def expr_tree(self) -> dict[str, Any]:
        """{field: Expression | {key: Expression} | nested dict} — the
        instance's raw expression ASTs. The rbac device lowering
        (compiler/rbac_lower.py) substitutes these into synthesized
        pseudo-rule predicates; constants are omitted (they never error
        and the lowering folds them separately)."""
        def walk(plan: list[tuple]) -> dict[str, Any]:
            out: dict[str, Any] = {}
            for fname, kind, payload in plan:
                if kind == "sub":
                    out[fname] = walk(payload)
                elif kind == "map":
                    out[fname] = {k: p.ast for k, p in payload.items()}
                elif kind == "expr":
                    out[fname] = payload.ast
            return out
        return walk(self._plan)

    def compiled_plan(self) -> list[tuple]:
        """The compiled field plan [(field, kind, payload)] with kind ∈
        const/sub/map/expr — read by the REPORT device lowering
        (runtime/report_lower.py) to compile each field expression into
        the fused step while keeping const/submessage/map structure."""
        return self._plan

    def value_attr_ref(self) -> Any | None:
        """attr name / (map, key) when the instance's `value` field is a
        bare attribute read — the fusability probe shared by the layout
        builder (runtime/config.py derived columns) and the fused plan
        (runtime/fused.py slot check); None otherwise."""
        prog = next((payload for fname, kind, payload in self._plan
                     if fname == "value" and kind == "expr"), None)
        return plain_attr_ref(prog.ast) if prog is not None else None

    def _run(self, plan: list[tuple], bag: Bag) -> dict[str, Any]:
        out: dict[str, Any] = {"name": self.name}
        for fname, kind, payload in plan:
            if kind == "const":
                out[fname] = payload
            elif kind == "sub":
                sub = self._run(payload, bag)
                sub.pop("name", None)
                out[fname] = sub
            elif kind == "map":
                out[fname] = {k: p.evaluate(bag)
                              for k, p in payload.items()}
            else:
                out[fname] = payload.evaluate(bag)
        return out
