"""The built-in template inventory.

Schemas transcribed from the reference's template protos
(mixer/template/<name>/template.proto); field sets and varieties match
1:1 so adapter configs written for the reference translate directly.
"""
from __future__ import annotations

from istio_tpu.attribute.types import ValueType as V
from istio_tpu.templates.framework import (Field, TemplateInfo, Variety,
                                           registry)

# mixer/template/checknothing/template.proto — empty check instance
CHECKNOTHING = registry.register(TemplateInfo(
    name="checknothing", variety=Variety.CHECK, fields=(),
    description="carries no data; precondition-only checks"))

# mixer/template/reportnothing/template.proto
REPORTNOTHING = registry.register(TemplateInfo(
    name="reportnothing", variety=Variety.REPORT, fields=(),
    description="carries no data; signal-only reports"))

# mixer/template/listentry/template.proto:25 — one string value.
# IP_ADDRESS additionally accepted: the wire carries IPs as bytes, the
# list adapter normalizes them (list_adapter.handle_check), and the
# fused engine lowers CIDR membership over those bytes on device.
LISTENTRY = registry.register(TemplateInfo(
    name="listentry", variety=Variety.CHECK,
    fields=(Field("value", V.STRING, required=True,
                  accepts=(V.IP_ADDRESS,)),),
    description="membership check of one value against a list adapter"))

# mixer/template/quota/template.proto — dimensions map
QUOTA = registry.register(TemplateInfo(
    name="quota", variety=Variety.QUOTA,
    fields=(Field("dimensions", expr_map=True),),
    description="quota allocation with dedup dimensions"))

# mixer/template/apikey/template.proto — api/key attributes
APIKEY = registry.register(TemplateInfo(
    name="apikey", variety=Variety.CHECK,
    fields=(Field("api", V.STRING),
            Field("api_version", V.STRING),
            Field("api_operation", V.STRING),
            Field("api_key", V.STRING),
            Field("timestamp", V.TIMESTAMP)),
    description="api-key validity check"))

# mixer/template/authorization/template.proto:26-49 — Subject/Action
AUTHORIZATION = registry.register(TemplateInfo(
    name="authorization", variety=Variety.CHECK,
    fields=(Field("subject", submessage=(
                Field("user", V.STRING),
                Field("groups", V.STRING),
                Field("properties", expr_map=True))),
            Field("action", submessage=(
                Field("namespace", V.STRING),
                Field("service", V.STRING),
                Field("method", V.STRING),
                Field("path", V.STRING),
                Field("properties", expr_map=True)))),
    description="who(subject) may do what(action)"))

# mixer/template/logentry/template.proto — variables + severity + time
LOGENTRY = registry.register(TemplateInfo(
    name="logentry", variety=Variety.REPORT,
    fields=(Field("variables", expr_map=True),
            Field("timestamp", V.TIMESTAMP),
            Field("severity", V.STRING),
            Field("monitored_resource_type", V.STRING),
            Field("monitored_resource_dimensions", expr_map=True)),
    description="structured log record"))

# mixer/template/metric/template.proto — value + dimensions
METRIC = registry.register(TemplateInfo(
    name="metric", variety=Variety.REPORT,
    fields=(Field("value", V.UNSPECIFIED, required=True),
            Field("dimensions", expr_map=True),
            Field("monitored_resource_type", V.STRING),
            Field("monitored_resource_dimensions", expr_map=True)),
    description="one measurement with dimensions"))

# mixer/adapter/kubernetesenv/template/template.proto — the APA
# (ATTRIBUTE_GENERATOR) template: inputs identify workloads, the
# adapter's output attributes are merged into the request bag during
# Preprocess (dispatcher.go:285). Output mapping comes from the
# instance's attribute_bindings (runtime config), not the schema.
KUBERNETES = registry.register(TemplateInfo(
    name="kubernetes", variety=Variety.ATTRIBUTE_GENERATOR,
    fields=(Field("source_uid", V.STRING),
            Field("source_ip", V.IP_ADDRESS),
            Field("destination_uid", V.STRING),
            Field("destination_ip", V.IP_ADDRESS),
            Field("origin_uid", V.STRING),
            Field("origin_ip", V.IP_ADDRESS)),
    description="k8s pod metadata attribute generation"))

# mixer/adapter/servicecontrol/template/servicecontrolreport/
# template.proto:51-65 — the adapter-private report template
SERVICECONTROLREPORT = registry.register(TemplateInfo(
    name="servicecontrolreport", variety=Variety.REPORT,
    fields=(Field("api_version", V.STRING),
            Field("api_operation", V.STRING),
            Field("api_protocol", V.STRING),
            Field("api_service", V.STRING),
            Field("api_key", V.STRING),
            Field("request_time", V.TIMESTAMP),
            Field("request_method", V.STRING),
            Field("request_path", V.STRING),
            Field("request_bytes", V.INT64),
            Field("response_time", V.TIMESTAMP),
            Field("response_code", V.INT64),
            Field("response_bytes", V.INT64),
            Field("response_latency", V.DURATION)),
    description="Google Service Control API usage report"))

# mixer/template/tracespan/template.proto
TRACESPAN = registry.register(TemplateInfo(
    name="tracespan", variety=Variety.REPORT,
    fields=(Field("trace_id", V.STRING, required=True),
            Field("span_id", V.STRING),
            Field("parent_span_id", V.STRING),
            Field("span_name", V.STRING),
            Field("start_time", V.TIMESTAMP),
            Field("end_time", V.TIMESTAMP),
            Field("span_tags", expr_map=True)),
    description="distributed-trace span"))
