"""Mixer client — the mixc / Envoy-mixerclient role.

Encodes attribute dicts with global-dictionary compression, issues
Check/Report RPCs, and (like the C++ mixerclient) can CACHE Check
verdicts keyed by the response's ReferencedAttributes: a subsequent
request whose referenced attribute values are identical reuses the
cached verdict until its TTL/use-count budget is spent.
"""
from __future__ import annotations

import datetime
import threading
import time
from typing import Any, Mapping, Sequence

import grpc

from istio_tpu.api import mixer_pb2 as pb
from istio_tpu.api.wire import (bag_to_compressed,
                                decode_batch_check_response,
                                encode_batch_check_request, _lookup)
from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST


class MixerClient:
    def __init__(self, target: str, enable_check_cache: bool = True,
                 root_cert_pem: bytes | None = None,
                 key_pem: bytes | None = None,
                 cert_pem: bytes | None = None,
                 server_name: str | None = None):
        """`root_cert_pem` switches the channel to TLS (server verified
        against the mesh root); `key_pem`+`cert_pem` add the client's
        workload identity (mTLS). `server_name` overrides the TLS
        authority for serving certs issued to a DNS SAN rather than
        the dial address (the CA-service pattern)."""
        if root_cert_pem is not None:
            from istio_tpu.secure.mtls import client_channel_credentials
            creds = client_channel_credentials(root_cert_pem, key_pem,
                                               cert_pem)
            options = []
            if server_name:
                options.append(("grpc.ssl_target_name_override",
                                server_name))
            self._channel = grpc.secure_channel(target, creds,
                                                options=options)
        else:
            self._channel = grpc.insecure_channel(target)
        # the identity this client authenticates AS (first spiffe://
        # URI SAN of its own cert) folds into every cache signature:
        # a cached verdict was granted to a PRINCIPAL, so a rotation
        # that changes the principal must never reuse it
        self._identity: str | None = None
        if cert_pem is not None:
            from istio_tpu.secure.mtls import spiffe_identity_from_pem
            self._identity = spiffe_identity_from_pem(cert_pem)
        self._check = self._channel.unary_unary(
            "/istio.mixer.v1.Mixer/Check",
            request_serializer=pb.CheckRequest.SerializeToString,
            response_deserializer=pb.CheckResponse.FromString)
        self._report = self._channel.unary_unary(
            "/istio.mixer.v1.Mixer/Report",
            request_serializer=pb.ReportRequest.SerializeToString,
            response_deserializer=pb.ReportResponse.FromString)
        self._batch_check_rpc = self._channel.unary_unary(
            "/istio.mixer.v1.Mixer/BatchCheck",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        self._cache_enabled = enable_check_cache
        self._cache: dict[tuple, list] = {}
        self._lock = threading.Lock()
        self._dedup_counter = 0
        # check-cache accounting (the server-issued grant bench/test
        # surface): hits never crossed the wire; expirations count
        # entries evicted on TTL, exhaustions on spent use-count
        self.cache_stats = {"hits": 0, "misses": 0,
                            "expired": 0, "exhausted": 0}

    # -- caching (mixerclient check_cache semantics) --

    def set_identity(self, identity: str | None) -> None:
        """The workload's identity rotated to a different principal:
        fold the new one into future signatures and drop every cached
        verdict granted to the old one. (grpcio channel credentials
        are fixed at construction — a cert swap needs a fresh client;
        same-principal renewals keep the cache, that's the point of
        the signature fold being the IDENTITY, not the cert bytes.)"""
        with self._lock:
            if identity != self._identity:
                self._identity = identity
                self._cache.clear()

    def _signature(self, ref: "pb.ReferencedAttributes",
                   values: Mapping[str, Any]) -> tuple | None:
        """Cache signature of `values` under a response's referenced-
        attribute set; None when the conditions don't transfer (the
        mixerclient can't reuse the verdict). map_key=0 means "no key"
        — the server reserves local word 0 (wire.py). The client's own
        authenticated identity is the first signature element: verdicts
        are granted to a principal, so an identity rotation that
        changes the principal can never hit the old entries."""
        sig = [("__peer_identity__", None, self._identity)]
        words = list(ref.words)
        gc = len(GLOBAL_WORD_LIST)
        for m in ref.attribute_matches:
            name = _lookup(m.name, words, gc)
            container = values.get(name)
            if m.map_key != 0:
                key = _lookup(m.map_key, words, gc)
                present = isinstance(container, Mapping) \
                    and key in container
                value = container.get(key) if present else None
            else:
                key = None
                present = name in values
                value = container if present else None
            if m.condition == pb.ReferencedAttributes.ABSENCE:
                if present:
                    return None          # mismatch: entry unusable
                sig.append((name, key, None))
            elif m.condition == pb.ReferencedAttributes.EXACT:
                if not present:
                    return None
                sig.append((name, key, repr(value)))
        return tuple(sig)

    def check(self, values: Mapping[str, Any],
              quotas: Mapping[str, int] | None = None,
              dedup_id: str | None = None) -> "pb.CheckResponse":
        if self._cache_enabled and not quotas:
            now = time.monotonic()
            with self._lock:
                hit = None
                for ref, entry in list(self._cache.items()):
                    resp, expiry, uses = entry
                    if expiry <= now or uses <= 0:     # evict spent entries
                        del self._cache[ref]
                        self.cache_stats[
                            "expired" if expiry <= now
                            else "exhausted"] += 1
                        continue
                    if hit is None:
                        sig = self._signature(
                            resp.precondition.referenced_attributes, values)
                        if sig is not None and sig == ref:
                            entry[2] -= 1
                            hit = resp
                if hit is not None:
                    self.cache_stats["hits"] += 1
                    return hit
                self.cache_stats["misses"] += 1
        req = pb.CheckRequest()
        bag_to_compressed(values, msg=req.attributes)
        req.global_word_count = len(GLOBAL_WORD_LIST)
        if dedup_id is None:
            self._dedup_counter += 1
            dedup_id = f"py-mixc-{self._dedup_counter}"
        req.deduplication_id = dedup_id
        for name, amount in (quotas or {}).items():
            req.quotas[name].amount = amount
            req.quotas[name].best_effort = True
        resp = self._check(req)
        if self._cache_enabled and not quotas:
            sig = self._signature(resp.precondition.referenced_attributes,
                                  values)
            if sig is not None:
                ttl = resp.precondition.valid_duration.ToTimedelta() \
                    .total_seconds()
                with self._lock:
                    self._cache[sig] = [resp,
                                        time.monotonic() + ttl,
                                        resp.precondition.valid_use_count]
        return resp

    def batch_check(self, batch: Sequence[Mapping[str, Any]]
                    ) -> "list[pb.CheckResponse]":
        """Amortized Check for pre-batched traffic (the shim protocol,
        mixer.proto BatchCheck): one RPC for many independent bags. No
        quotas/dedup; the client cache is bypassed — the shim caches
        per-sidecar, not here."""
        blobs = []
        for values in batch:
            msg = pb.CompressedAttributes()
            bag_to_compressed(values, msg=msg)
            blobs.append(msg.SerializeToString())
        raw = self._batch_check_rpc(encode_batch_check_request(
            blobs, len(GLOBAL_WORD_LIST)))
        return [pb.CheckResponse.FromString(b)
                for b in decode_batch_check_response(raw)]

    def report(self, records: Sequence[Mapping[str, Any]]) -> None:
        """Delta-encodes consecutive records (report_batch behavior).
        The wire protocol accumulates deltas server-side and has no
        removal marker, so a record that DROPS a key flushes the
        current request and starts a fresh accumulation."""
        req = pb.ReportRequest()
        req.global_word_count = len(GLOBAL_WORD_LIST)
        prev: dict[str, Any] = {}
        for values in records:
            if prev and any(k not in values for k in prev):
                if len(req.attributes):
                    self._report(req)
                req = pb.ReportRequest()
                req.global_word_count = len(GLOBAL_WORD_LIST)
                prev = {}
            delta = {k: v for k, v in values.items()
                     if k not in prev or prev[k] != v}
            bag_to_compressed(delta, msg=req.attributes.add())
            prev = dict(values)
        if len(req.attributes):
            self._report(req)

    def close(self) -> None:
        self._channel.close()
