"""Native Mixer front-end — the C++ HTTP/2 wire + python engine pumps.

The data-plane component SURVEY §2.9 implication (a) owes: unary
istio.mixer.v1.Mixer/Check|Report terminated in C++
(native/httpd.cpp — connections, HTTP/2 framing, HPACK, gRPC framing,
envelope split, adaptive batch formation, response framing), with
python doing only per-BATCH engine work through the same fused path
the grpc front uses. Reference anchor: mixer/pkg/api/grpcServer.go:118
(Check), :262 (Report) — same request semantics (precondition check +
per-quota loop with dedup ids), different transport economics: the
python-grpc front pays ~0.4 ms of interpreter per RPC; this front pays
it once per batch.

Pump threads block in h2srv_take (ctypes releases the GIL, so the C++
wire keeps running), run the batch through
RuntimeServer.check_batch_preprocessed / report, resolve quotas via
the device pools, and hand serialized CheckResponse bytes back for
C++ to frame. Response serialization is memoized per verdict signature
(uniform traffic → a handful of distinct responses per snapshot).
"""
from __future__ import annotations

import ctypes
import logging
import struct
import threading
from typing import Any

from istio_tpu.adapters.sdk import QuotaArgs
from istio_tpu.api import mixer_pb2 as pb
from istio_tpu.api.grpc_server import MixerGrpcServer
from istio_tpu.api.wire import LazyWireBag
from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST
from istio_tpu.native.build import ensure_httpd_built
from istio_tpu.runtime import monitor
from istio_tpu.runtime.server import RuntimeServer

log = logging.getLogger("istio_tpu.api.native")

_TAKE_TIMEOUT_MS = 200
_COUNTER_NAMES = ("requests_decoded", "responses_sent",
                  "batches_formed", "batch_rows", "in_flight",
                  "conns_opened", "conns_closed", "protocol_errors",
                  "bytes_in", "bytes_out")


class _RowRequest:
    """The slice of RawCheckRequest the quota loop reads."""

    __slots__ = ("deduplication_id", "quotas")

    def __init__(self, dedup: str, quotas: dict):
        self.deduplication_id = dedup
        self.quotas = quotas


# must mirror Server::kLatBuckets in httpd.cpp: the wire latency
# histogram's log-bucket count (bucket i covers ≤ 1µs·2^(i/8))
_LAT_BUCKETS = 192


def start_echo_server(max_batch: int = 1024) -> tuple[int, Any]:
    """Wire-ceiling mode: the C++ server answers every Check with a
    fixed OK CheckResponse, no engine — (port, stop_fn). Single home
    of the h2srv C ABI for bench/scripts (with _load_lib below)."""
    lib = _load_lib()
    h = lib.h2srv_start(0, max_batch, 256, 2000, 1, 1, 0)
    if not h:
        raise RuntimeError("h2srv_start failed (echo)")
    return lib.h2srv_port(h), lambda: lib.h2srv_stop(h)


def _load_lib() -> ctypes.CDLL:
    lib = ctypes.CDLL(ensure_httpd_built())
    lib.h2srv_start.restype = ctypes.c_void_p
    lib.h2srv_start.argtypes = [ctypes.c_int32] * 3 + \
        [ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
         ctypes.c_int32]
    lib.h2srv_port.restype = ctypes.c_int32
    lib.h2srv_port.argtypes = [ctypes.c_void_p]
    lib.h2srv_latency.restype = None
    lib.h2srv_latency.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.POINTER(ctypes.c_int64)]
    lib.h2srv_take.restype = ctypes.c_int64
    lib.h2srv_take.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                               ctypes.c_char_p, ctypes.c_int64]
    lib.h2srv_complete.restype = None
    lib.h2srv_complete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int64]
    lib.h2srv_counters.restype = None
    lib.h2srv_counters.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_int64)]
    lib.h2srv_stop.restype = None
    lib.h2srv_stop.argtypes = [ctypes.c_void_p]
    lib.h2srv_quiesce.restype = None
    lib.h2srv_quiesce.argtypes = [ctypes.c_void_p]
    return lib


class NativeMixerServer(MixerGrpcServer):
    """C++-wire Mixer server over a RuntimeServer core.

    Inherits the response/quota assembly from MixerGrpcServer (the
    single home of PreconditionResult/quota-loop semantics); replaces
    the grpcio transport entirely.
    """

    def __init__(self, runtime: RuntimeServer, port: int = 0,
                 max_batch: int = 1024, min_fill: int = 256,
                 window_us: int = 2000, pumps: int = 2,
                 continuous: bool = False, tls=None,
                 mtls_mode: str = "strict"):
        # deliberately NOT calling super().__init__ — no grpc.server
        # `continuous`: the C++ take policy never holds for min_fill/
        # window — an idle pump launches the next device step the
        # moment anything is queued (in-flight depth bounded by
        # `pumps`); the latency lane vs the occupancy-fill default
        self.runtime = runtime
        # `tls` (secure.mtls.ServingCerts): start a TLS-terminating
        # lane (secure/tlslane.py) in front of the C++ pump —
        # `secure_port` is what clients dial; the plaintext `port`
        # stays loopback-reachable so the pump's wire accounting and
        # every parity gate see byte-for-byte the plaintext stream.
        # Strict mode requires + verifies the client cert at the lane
        # handshake (connection-level authn; per-request identity→bag
        # lives on the gRPC fronts — see the tlslane module docstring).
        self._tls_lane = None
        self._tls_mode = mtls_mode
        self._tls_certs = tls
        self._ref_cache: dict = {}
        self._ref_cache_lock = threading.Lock()
        self._resp_memo: dict = {}
        self._lib = _load_lib()
        self.continuous = bool(continuous)
        self._h = self._lib.h2srv_start(port, max_batch, min_fill,
                                        window_us, pumps, 0,
                                        1 if continuous else 0)
        if not self._h:
            raise RuntimeError("h2srv_start failed (port in use?)")
        self.port = self._lib.h2srv_port(self._h)
        self._stop_flag = threading.Event()
        self._final_counters: dict | None = None
        self._final_latency: dict | None = None
        # serializes h2srv_complete against stop(): deferred quota
        # completions fire from pool-worker threads and must never
        # race the server teardown into a freed handle
        self._comp_lock = threading.Lock()
        self._pumps = [
            threading.Thread(target=self._pump_loop, daemon=True,
                             name=f"mixer-native-pump-{i}")
            for i in range(pumps)]

    # -- lifecycle --

    def start(self) -> int:
        for t in self._pumps:
            t.start()
        if self._tls_certs is not None:
            from istio_tpu.secure.tlslane import TlsTerminatingLane
            self._tls_lane = TlsTerminatingLane(
                self._tls_certs, self.port, mode=self._tls_mode)
            self.secure_port = self._tls_lane.start()
            log.info("native mixer server on port %d (tls lane :%d)",
                     self.port, self.secure_port)
        else:
            log.info("native mixer server on port %d", self.port)
        return self.port

    def tls_lane_stats(self) -> dict:
        """Connection/handshake accounting of the TLS terminating
        lane ({} when serving plaintext or already stopped)."""
        lane = self._tls_lane
        if lane is None:
            return {}
        with lane._lock:
            return dict(lane.stats)

    def stop(self, grace: float = 1.0) -> None:
        """Ordered graceful stop (the native leg of the lifecycle
        plane): quiesce intake → drain in-flight rows → join pumps →
        tear down the wire. Every submitted row resolves — to its real
        verdict during the drain window, or to a typed UNAVAILABLE
        rejection past it — never a silent drop."""
        if self._h is None:
            return
        import time as _time

        # 0. the TLS lane stops accepting first (quiesce ordering: the
        #    outermost intake closes before the pump's)
        if self._tls_lane is not None:
            self._tls_lane.stop()
            self._tls_lane = None
        # 1. stop intake: new wire requests answer UNAVAILABLE in C++;
        #    already-queued rows dispatch to the pumps immediately
        #    (no min_fill hold during a drain)
        self._lib.h2srv_quiesce(self._h)
        # 2. drain: wait for queued + dispatched + deferred-quota rows
        #    to complete (in_flight counts enqueue → completion-write)
        deadline = _time.monotonic() + grace
        while _time.monotonic() < deadline:
            if self.counters().get("in_flight", 0) <= 0:
                break
            _time.sleep(0.01)
        # 3. pumps must be out of h2srv_take before the handle is torn
        #    down
        self._stop_flag.set()
        for t in self._pumps:
            t.join(timeout=grace + 30)
        self._final_counters = self.counters()
        self._final_latency = self.latency_raw()
        if any(t.is_alive() for t in self._pumps):
            # a pump is wedged mid-batch (device stall): freeing the
            # handle under it would turn a stall into a segfault —
            # leak the C++ server instead (it stays valid for the
            # straggler's h2srv_take/complete calls, and h2srv_stop's
            # own abi-call guard would leak it anyway)
            log.error("native server handle leaked: pump stuck "
                      "past %.0fs grace", grace + 30)
            return
        # 4. teardown: rows the drain deadline abandoned get typed
        #    rejections framed + flushed by the IO thread's shutdown
        #    drain; double-stop is a C++-side no-op
        with self._comp_lock:
            self._lib.h2srv_stop(self._h)
            self._h = None

    def counters(self) -> dict:
        with self._comp_lock:   # h2srv_complete's teardown guard too
            if self._h is None:   # post-stop: last snapshot, no NULL
                return dict(self._final_counters or {})
            c = (ctypes.c_int64 * 10)()
            hist = (ctypes.c_int64 * 16)()
            self._lib.h2srv_counters(self._h, c, hist)
        out = dict(zip(_COUNTER_NAMES, [int(v) for v in c]))
        out["batch_size_hist"] = {1 << b: int(hist[b])
                                  for b in range(16) if hist[b]}
        self._publish_counters(out)
        return out

    # -- wire latency (the measured wire-to-verdict plane) --

    def latency_raw(self) -> dict:
        """Cumulative wire-to-verdict histogram straight off the C++
        ABI: {"buckets": [n]*192, "min_ns", "max_ns"}. Bucket i counts
        requests whose frame-decode → response-frame-write latency was
        ≤ 1µs·2^(i/8). Use as the `since` baseline for per-window
        quantiles via latency_snapshot(since=...)."""
        with self._comp_lock:
            if self._h is None:
                return dict(self._final_latency or {
                    "buckets": [0] * _LAT_BUCKETS,
                    "min_ns": 0, "max_ns": 0})
            buckets = (ctypes.c_int64 * _LAT_BUCKETS)()
            mm = (ctypes.c_int64 * 2)()
            self._lib.h2srv_latency(self._h, buckets, mm)
        return {"buckets": [int(v) for v in buckets],
                "min_ns": int(mm[0]), "max_ns": int(mm[1])}

    @staticmethod
    def _quantiles(buckets: list, qs=(0.50, 0.95, 0.99)) -> dict:
        """Quantiles (ms) from the log-bucket counts, geometric-mean
        interpolated within the landing bucket (bucket ratio 2^(1/8)
        → ≤ ±4.5% quantile error by construction)."""
        total = sum(buckets)
        out = {"n": total}
        for q in qs:
            key = "p" + f"{q * 100:g}".replace(".", "")
            if not total:
                out[key] = 0.0
                continue
            target = q * total
            acc = 0
            idx = len(buckets) - 1
            for i, n in enumerate(buckets):
                acc += n
                if acc >= target:
                    idx = i
                    break
            # bucket i spans (2^((i-1)/8), 2^(i/8)] µs → report the
            # geometric midpoint, in ms
            hi = 2.0 ** (idx / 8.0)
            lo = hi / (2.0 ** 0.125) if idx else hi / 2.0
            out[key] = round((lo * hi) ** 0.5 / 1000.0, 4)
        return out

    def latency_snapshot(self, since: dict | None = None) -> dict:
        """Wire-to-verdict latency quantiles — cumulative, or the
        DELTA vs a latency_raw() baseline (per-bench-window reads).
        The measurement is taken entirely in C++ (frame decode →
        response frame write): it includes the take-queue wait, batch
        formation, the python pump, tensorize, device step and
        response build — everything a python-side timer misses."""
        raw = self.latency_raw()
        buckets = raw["buckets"]
        if since is not None:
            buckets = [a - b for a, b in
                       zip(buckets, since.get("buckets", []))]
            if len(buckets) != _LAT_BUCKETS:
                buckets = raw["buckets"]
        snap = self._quantiles(buckets)
        # min/max scoped to the SAME window as the quantiles: the
        # geometric bounds of the extreme non-empty delta buckets
        # (bucket-resolution, ±9%). The exact lifetime extremes ride
        # under explicit *_lifetime names — mixing scopes silently
        # made a warmup-era outlier look like a window straggler.
        nz = [i for i, v in enumerate(buckets) if v > 0]
        if nz:
            lo_hi = 2.0 ** (nz[0] / 8.0) / 1000.0
            snap["min_ms"] = round(
                (lo_hi / (2.0 ** 0.125) if nz[0] else lo_hi / 2.0),
                4)
            snap["max_ms"] = round(2.0 ** (nz[-1] / 8.0) / 1000.0, 4)
        else:
            snap["min_ms"] = snap["max_ms"] = 0.0
        snap["min_ms_lifetime"] = round(raw["min_ns"] / 1e6, 4)
        snap["max_ms_lifetime"] = round(raw["max_ns"] / 1e6, 4)
        snap["raw"] = raw      # pass-through: the next window's base
        self._publish_latency(snap)
        return snap

    _LAT_GAUGES: dict = {}

    def _publish_latency(self, snap: dict) -> None:
        """Mirror the wire quantiles into the shared registry
        (mixer_native_wire_p{50,95,99}_ms + count) so /metrics carries
        the measured wire-to-verdict numbers."""
        from istio_tpu.utils import metrics as hostmetrics

        with NativeMixerServer._NATIVE_GAUGES_LOCK:
            g = NativeMixerServer._LAT_GAUGES
            if not g:
                for k, name, desc in (
                        ("p50", "mixer_native_wire_p50_ms",
                         "wire-to-verdict p50 ms"),
                        ("p95", "mixer_native_wire_p95_ms",
                         "wire-to-verdict p95 ms"),
                        ("p99", "mixer_native_wire_p99_ms",
                         "wire-to-verdict p99 ms"),
                        ("n", "mixer_native_wire_latency_count",
                         "wire-to-verdict observations")):
                    g[k] = hostmetrics.default_registry.gauge(
                        name, f"native front {desc}")
        for k in ("p50", "p95", "p99", "n"):
            if k in snap:
                g[k].set(float(snap[k]))

    # gauges (not counters): the C++ side owns the monotonic totals,
    # we mirror absolute snapshots — lazily created so merely importing
    # this module never registers native metrics. The lock serializes
    # first-use registration: an introspect scrape thread and a bench
    # thread racing the init would double-register the families (a
    # malformed exposition forever) or KeyError on a half-built dict.
    _NATIVE_GAUGES: dict = {}
    _NATIVE_GAUGES_LOCK = threading.Lock()

    def _publish_counters(self, snap: dict) -> None:
        """Mirror the C++ wire counters into the shared homegrown
        registry so /metrics covers the native front end (previously
        these lived only in this ad-hoc dict — invisible to scrapes).
        Called on every counters() read; the introspect server reads
        counters() before each exposition."""
        from istio_tpu.utils import metrics as hostmetrics

        with NativeMixerServer._NATIVE_GAUGES_LOCK:
            gauges = NativeMixerServer._NATIVE_GAUGES
            if not gauges:
                for name in _COUNTER_NAMES:
                    gauges[name] = hostmetrics.default_registry.gauge(
                        f"mixer_native_{name}",
                        f"native front-end wire counter {name}")
                gauges["batch_size_hist"] = \
                    hostmetrics.default_registry.gauge(
                        "mixer_native_batch_rows_bucketed",
                        "native front-end batch counts by power-of-two "
                        "size bucket (label: bucket; per-bucket point "
                        "values, NOT a cumulative histogram ladder)")
        for name in _COUNTER_NAMES:
            gauges[name].set(float(snap.get(name, 0)))
        # label is `bucket`, not `le`: these are per-bucket point
        # counts — `le` is reserved for cumulative histogram series
        # and would silently break histogram_quantile()
        for bucket, n in snap.get("batch_size_hist", {}).items():
            gauges["batch_size_hist"].set(float(n), bucket=str(bucket))

    # -- pump --

    def _pump_loop(self) -> None:
        cap = 1 << 23          # per-thread: cap and buffer must agree
        buf = ctypes.create_string_buffer(cap)
        while not self._stop_flag.is_set():
            n = self._lib.h2srv_take(self._h, _TAKE_TIMEOUT_MS, buf,
                                     cap)
            if n == -1:
                return
            if n == 0:
                continue
            if n < 0:          # buffer too small: grow and retry
                cap = -int(n) * 2
                buf = ctypes.create_string_buffer(cap)
                continue
            try:
                self._run_batch(buf.raw[:n])
            except Exception:
                log.exception("native pump batch failed")

    @staticmethod
    def _parse_take(blob: bytes) -> list[tuple]:
        """→ [(tag, kind, payload, gwc, dedup, quotas{name: (amount,
        best_effort)}, traceparent)]."""
        items = []
        (_, n) = struct.unpack_from("<II", blob, 0)
        off = 8
        for _ in range(n):
            (tag,) = struct.unpack_from("<Q", blob, off)
            off += 8
            kind = blob[off]
            off += 1
            (plen,) = struct.unpack_from("<I", blob, off)
            off += 4
            payload = blob[off:off + plen]
            off += plen
            (gwc, dlen) = struct.unpack_from("<II", blob, off)
            off += 8
            dedup = blob[off:off + dlen].decode("utf-8", "replace")
            off += dlen
            (tplen,) = struct.unpack_from("<I", blob, off)
            off += 4
            traceparent = blob[off:off + tplen].decode(
                "utf-8", "replace")
            off += tplen
            (nq,) = struct.unpack_from("<H", blob, off)
            off += 2
            quotas = {}
            for _q in range(nq):
                (nlen,) = struct.unpack_from("<I", blob, off)
                off += 4
                qname = blob[off:off + nlen].decode("utf-8", "replace")
                off += nlen
                amount, be = struct.unpack_from("<qB", blob, off)
                off += 9
                quotas[qname] = (amount, bool(be))
            items.append((tag, kind, payload, gwc, dedup, quotas,
                          traceparent))
        return items

    def _run_batch(self, blob: bytes) -> None:
        items = self._parse_take(blob)
        completions: list[tuple[int, int, bytes]] = []
        deferred: set[int] = set()
        try:
            self._run_batch_inner(items, completions, deferred)
        except Exception:
            # belt: NO failure may abandon a row — an unanswered tag
            # hangs its client until deadline AND leaks the C++
            # in_flight count (one bad request must not poison its
            # batch-mates' connections)
            log.exception("native pump batch failed")
        done = {tag for tag, _, _ in completions} | deferred
        for item in items:
            if item[0] not in done:
                completions.append(
                    (item[0], 13, b"internal: batch processing failed"))
        self._send_completions(completions)

    def _run_batch_inner(self, items: list, completions: list,
                         deferred: set) -> None:
        from istio_tpu.utils import tracing

        checks = [it for it in items if it[1] == 0]
        reports = [it for it in items if it[1] == 1]

        if checks:
            # ROOT span at wire decode (API-layer root, same role as
            # the grpc fronts' rpc.check): downstream engine spans on
            # this pump thread parent under it via the thread-local
            # stack, so the batch's queue/tensorize/device time is
            # attributed to the RPC group that paid it. The batch
            # parents under the FIRST row's W3C traceparent (wire
            # header, decoded in C++) when one was sent — the same
            # oldest-request attribution rule the batcher uses.
            # first row whose header PARSES (a malformed header in an
            # earlier row must not suppress a valid one behind it)
            parent = next(
                (p for p in (tracing.parent_from_traceparent(it[6])
                             for it in checks if it[6])
                 if p is not None), None)
            span_ctx = tracing.get_tracer().span(
                "rpc.check", parent=parent, transport="native",
                batch=len(checks))
            with span_ctx as span:
                self._run_checks(checks, completions, deferred,
                                 span=span)

        if reports:
            # rpc.report root at the wire (same role as rpc.check
            # above): parents under the first report row's W3C
            # traceparent when one was sent
            parent = next(
                (p for p in (tracing.parent_from_traceparent(it[6])
                             for it in reports if it[6])
                 if p is not None), None)
            with tracing.get_tracer().span(
                    "rpc.report", parent=parent, transport="native",
                    rpcs=len(reports)) as span:
                self._run_reports(reports, completions, span=span)

    def _run_reports(self, reports: list, completions: list,
                     span: dict | None = None) -> None:
        """ACK-AFTER-ENQUEUE report serving (the ingestion plane's
        native leg): each RPC's records are decoded, admitted into the
        bounded cross-RPC record coalescer, and the RPC is answered
        the moment its records are ACCEPTED — the pump thread never
        waits out a device trip, so Report rows sharing a take batch
        with Check rows add only decode+enqueue time in front of them.

        Admission overflow answers a typed RESOURCE_EXHAUSTED (and a
        draining coalescer UNAVAILABLE) instead of buffering without
        bound behind an already-acked wire; admitted records are
        conservation-accounted by submit_report (every one ends
        exported or typed-rejected — never silently dropped)."""
        from istio_tpu.runtime.resilience import CheckRejected

        import time as _time

        n_records = 0
        first_bad = 0
        for tag, _, payload, _, _, _, _ in reports:
            monitor.REPORT_REQUESTS.inc()
            try:
                t0 = _time.perf_counter()
                req = pb.ReportRequest.FromString(payload)
                bags = self._decode_report(req)
                monitor.observe_report_stage(
                    "wire_decode", _time.perf_counter() - t0)
            except Exception as exc:
                completions.append(
                    (tag, 13, f"report decode failed: {exc}".encode()))
                first_bad = first_bad or 13
                continue
            n_records += len(bags)
            try:
                futs = self.runtime.submit_report(bags)
            except CheckRejected as exc:   # inline path's typed shed
                completions.append((tag, exc.grpc_code,
                                    str(exc).encode()))
                first_bad = first_bad or exc.grpc_code
                continue
            except Exception as exc:
                completions.append(
                    (tag, 13, f"report failed: {exc}".encode()))
                first_bad = first_bad or 13
                continue
            # ack-after-enqueue: only ALREADY-REJECTED futures (typed
            # admission sheds resolve synchronously inside submit)
            # turn the ack into an error — everything admitted will
            # export or typed-reject on its own, counted either way
            err = None
            for f in futs:
                if f.done():
                    try:
                        err = f.exception()
                    except BaseException as cancel:
                        # a cancelled admission future did NOT export
                        # its record (the ledger counted it rejected)
                        # — the ack must say so, never OK
                        err = cancel
                    if err is not None:
                        break
            if err is not None:
                code = getattr(err, "grpc_code", 13)
                completions.append((tag, code, str(err).encode()))
                first_bad = first_bad or code
            else:
                completions.append((tag, 0, b""))
                monitor.REPORT_RESPONSES.inc()
        if span is not None:
            span["tags"]["records"] = n_records
            span["tags"]["status"] = "ok" if first_bad == 0 \
                else str(first_bad)

    def _run_checks(self, checks: list, completions: list,
                    deferred: set, span: dict | None = None) -> None:
        monitor.CHECK_REQUESTS.inc(len(checks))
        # the C++ wire carries no per-RPC deadline — apply the
        # server-side default (--default-check-deadline-ms) from the
        # moment the pump took the batch: under saturation, chunks
        # this batch can't reach in time answer DEADLINE_EXCEEDED
        # pre-tensorize instead of queueing dead device work
        deadline = self._deadline_from(None)
        import time as _time

        from istio_tpu.runtime import forensics
        t_dec0 = _time.perf_counter()
        bags = []
        for _, _, payload, gwc, _, _, _ in checks:
            native = gwc in (0, len(GLOBAL_WORD_LIST))
            bags.append(self.runtime.preprocess(
                LazyWireBag(payload, gwc or None,
                            native_ok=native)))
        # flight-recorder pre-mark: the wire→bag decode wall joins the
        # next batch tape on this pump thread (httpd.cpp's t_decode_ns
        # covers the C++ side; this is the python envelope's share)
        forensics.RECORDER.note_wire_decode(
            _time.perf_counter() - t_dec0)
        # in-step quota (ServerArgs.quota_in_step): eligible
        # single-quota rows allocate IN the check trip — no
        # pool-flush trip serialized behind it, no defer
        # machinery. Ineligible rows (multi-quota, unknown name,
        # target-less snapshot) keep the classic defer path.
        target = self.runtime.instep_quota_target()
        qspecs = None
        if target is not None:
            _, by_name = target
            qspecs = []
            for _, _, _, _, dedup, quotas, _ in checks:
                spec = None
                if len(quotas) == 1:
                    (qname, (amount, be)), = quotas.items()
                    if qname in by_name:
                        spec = (qname, QuotaArgs(
                            quota_amount=amount, best_effort=be,
                            dedup_id=dedup + ":" + qname
                            if dedup else ""))
                qspecs.append(spec)
            if not any(qspecs):
                qspecs = None
        from istio_tpu.runtime.resilience import CheckRejected
        try:
            if qspecs is not None:
                results, inres = self._check_bags_quota_instep(
                    bags, qspecs, target, deadline=deadline)
            else:
                results = self._check_bags_chunked(bags,
                                                   deadline=deadline)
                inres = {}
        except CheckRejected as exc:
            # typed serving rejection (fail-closed UNAVAILABLE, shed):
            # answer every row with the honest status code instead of
            # letting the belt degrade it to a blanket INTERNAL
            msg = str(exc).encode()
            for tag, _, _, _, _, _, _ in checks:
                completions.append((tag, exc.grpc_code, msg))
            if span is not None:
                span["tags"]["status"] = str(exc.grpc_code)
            return
        finally:
            # a dispatch that ended in a typed rejection (or expired
            # every chunk) ran no batch_begin — drop the decode
            # pre-mark so a stale wall never inflates the NEXT
            # batch's wire_decode stage (no-op when a chunk consumed
            # it normally)
            forensics.RECORDER.clear_premarks()
        # `status` tag (batch-level: ok or the first non-OK code) so
        # /debug/traces can filter failing check spans on this front
        if span is not None:
            first_bad = next((r.status_code for r in results
                              if r.status_code), 0)
            span["tags"]["status"] = "ok" if first_bad == 0 \
                else str(first_bad)
        memo_hits = 0
        for row, (item, bag, result) in enumerate(
                zip(checks, bags, results)):
            tag, _, _, _, dedup, quotas, _ = item
            try:
                if row in inres:
                    # quota already allocated in the check trip;
                    # attach it only on success (a denied row's
                    # entry is grant-freely noise the gate never
                    # consumed for — the fronts omit quotas on
                    # denial, grpcServer.go:188)
                    qpair = []
                    if result.status_code == 0:
                        (qname, _), = quotas.items()
                        qpair = [(qname, inres[row])]
                    raw = self._check_response(
                        None, bag, result,
                        quotas=qpair).SerializeToString()
                    completions.append((tag, 0, raw))
                    continue
                if quotas and result.status_code == 0:
                    # quota rows complete via pool-future
                    # callbacks: a batch's non-quota rows must NOT
                    # wait out the quota flush window + device
                    # trip (that added ~2 serialized trips to
                    # EVERY row's latency)
                    req = _RowRequest(dedup, {
                        name: pb.CheckRequest.QuotaParams(
                            amount=amount, best_effort=be)
                        for name, (amount, be) in quotas.items()})
                    self._defer_quota_row(
                        tag, bag, result,
                        self._submit_quotas(req, bag, result))
                    deferred.add(tag)
                    continue
            except Exception as exc:   # row-isolated (quota path)
                monitor.DISPATCH_ERRORS.inc()
                completions.append(
                    (tag, 13, f"quota submit: {exc}".encode()))
                continue
            # memo ONLY bag-independent responses: presence must
            # COVER the referenced set (incomplete presence makes
            # _referenced_proto fall back to per-bag lookups —
            # grpc_server._referenced_proto applies the same gate)
            presence = result.referenced_presence
            if presence is not None and \
                    len(presence) == len(result.referenced):
                key = (result.status_code, result.status_message,
                       result.valid_duration_s,
                       result.valid_use_count, result.referenced,
                       frozenset(presence.items()))
                raw = self._resp_memo.get(key)
                if raw is None:
                    raw = self._check_response(
                        None, bag, result,
                        quotas=[]).SerializeToString()
                    if len(self._resp_memo) > 8192:
                        self._resp_memo.clear()
                    self._resp_memo[key] = raw
                else:
                    memo_hits += 1
            else:
                raw = self._check_response(
                    None, bag, result,
                    quotas=[]).SerializeToString()
            completions.append((tag, 0, raw))
        if memo_hits:   # memoized rows skip _check_response
            monitor.CHECK_RESPONSES.inc(memo_hits)

    def _send_completions(self, completions: list) -> None:
        if not completions:
            return
        out = [struct.pack("<I", len(completions))]
        for tag, status, raw in completions:
            out.append(struct.pack("<QiI", tag, status, len(raw)))
            out.append(raw)
        comp = b"".join(out)
        with self._comp_lock:
            if self._h is None:    # torn down under a deferred row
                return
            self._lib.h2srv_complete(self._h, comp, len(comp))

    def _defer_quota_row(self, tag: int, bag, result,
                         subs: list) -> None:
        """Complete one quota-carrying row when its pool futures
        resolve. All quotas were submitted already (they share a flush
        window); the LAST future to land builds + sends the response
        from the pool-worker thread — no pump thread blocks."""
        futures = [qr for _, qr in subs
                   if hasattr(qr, "add_done_callback")]
        remaining = [len(futures)]
        lock = threading.Lock()

        def finish() -> None:
            try:
                raw = self._check_response(
                    None, bag, result,
                    quotas=subs).SerializeToString()
                self._send_completions([(tag, 0, raw)])
            except Exception:
                log.exception("deferred quota completion failed")
                self._send_completions(
                    [(tag, 13, b"quota completion failed")])

        if not futures:
            finish()
            return

        def on_done(_value) -> None:
            with lock:
                remaining[0] -= 1
                if remaining[0]:
                    return
            finish()

        for fut in futures:
            fut.add_done_callback(on_done)
