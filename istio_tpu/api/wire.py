"""CompressedAttributes ↔ attribute-bag codec.

Reference: mixer/pkg/attribute wire model — mutableBag.ToProto
(mutableBag.go:230), ProtoBag lazy decode (protoBag.go:49,161), delta
update for Report (UpdateBagFromProto :311).

Index encoding (dictState.go / protoBag.go): an attribute name or
string value is a sint32 `index`. index < 0 → global dictionary entry
`-index - 1`; index >= 0 → per-message (or per-request default) word
list entry. The global dictionary is the 169-word list in
attribute/global_dict.py; both sides may agree on a shorter prefix via
`global_word_count` (grpcServer.go global dict plumbing).
"""
from __future__ import annotations

import datetime
from typing import Any, Mapping

from istio_tpu.api import mixer_pb2 as pb
from istio_tpu.attribute.global_dict import (GLOBAL_WORD_INDEX,
                                             GLOBAL_WORD_LIST)


class WireError(ValueError):
    pass


class _Words:
    """Per-message word-list builder (dictState.go)."""

    def __init__(self, global_count: int):
        self.global_count = global_count
        self.local: list[str] = []
        self.index: dict[str, int] = {}

    def ref(self, word: str) -> int:
        gi = GLOBAL_WORD_INDEX.get(word)
        if gi is not None and gi < self.global_count:
            return -gi - 1
        li = self.index.get(word)
        if li is None:
            li = len(self.local)
            self.local.append(word)
            self.index[word] = li
        return li


def _lookup(index: int, words: list[str], global_count: int) -> str:
    if index < 0:
        gi = -index - 1
        if gi >= global_count or gi >= len(GLOBAL_WORD_LIST):
            raise WireError(f"global word index {index} out of range")
        return GLOBAL_WORD_LIST[gi]
    if index >= len(words):
        raise WireError(f"message word index {index} out of range")
    return words[index]


def bag_to_compressed(values: Mapping[str, Any],
                      global_word_count: int | None = None,
                      msg: "pb.CompressedAttributes | None" = None
                      ) -> "pb.CompressedAttributes":
    """Encode name→value attributes (mutableBag.ToProto)."""
    gc = len(GLOBAL_WORD_LIST) if global_word_count is None \
        else global_word_count
    out = msg if msg is not None else pb.CompressedAttributes()
    words = _Words(gc)
    for name in sorted(values):
        v = values[name]
        k = words.ref(name)
        if isinstance(v, bool):
            out.bools[k] = v
        elif isinstance(v, int):
            out.int64s[k] = v
        elif isinstance(v, float):
            out.doubles[k] = v
        elif isinstance(v, str):
            out.strings[k] = words.ref(v)
        elif isinstance(v, bytes):
            out.bytes[k] = v
        elif isinstance(v, datetime.datetime):
            out.timestamps[k].FromDatetime(v)
        elif isinstance(v, datetime.timedelta):
            out.durations[k].FromTimedelta(v)
        elif isinstance(v, Mapping):
            sm = out.string_maps[k]
            for mk in sorted(v):
                sm.entries[words.ref(str(mk))] = words.ref(str(v[mk]))
        else:
            raise WireError(f"cannot encode {name}: {type(v)}")
    out.words.extend(words.local)
    return out


def compressed_to_dict(msg: "pb.CompressedAttributes",
                       global_word_count: int | None = None,
                       default_words: list[str] | None = None
                       ) -> dict[str, Any]:
    """Decode to a plain dict (ProtoBag semantics; default_words are
    the request-level word list Report uses when a record has none)."""
    out: dict[str, Any] = {}
    update_dict_from_proto(out, msg, global_word_count, default_words)
    return out


def update_dict_from_proto(target: dict[str, Any],
                           msg: "pb.CompressedAttributes",
                           global_word_count: int | None = None,
                           default_words: list[str] | None = None) -> None:
    """Delta-apply a record (UpdateBagFromProto mutableBag.go:311)."""
    gc = len(GLOBAL_WORD_LIST) if global_word_count in (None, 0) \
        else global_word_count
    words = list(msg.words) or list(default_words or [])

    def name(i: int) -> str:
        return _lookup(i, words, gc)

    for k, vi in msg.strings.items():
        target[name(k)] = name(vi)
    for k, v in msg.int64s.items():
        target[name(k)] = int(v)
    for k, v in msg.doubles.items():
        target[name(k)] = float(v)
    for k, v in msg.bools.items():
        target[name(k)] = bool(v)
    for k, ts in msg.timestamps.items():
        target[name(k)] = ts.ToDatetime(
            tzinfo=datetime.timezone.utc)
    for k, d in msg.durations.items():
        target[name(k)] = d.ToTimedelta()
    for k, v in msg.bytes.items():
        target[name(k)] = bytes(v)
    for k, sm in msg.string_maps.items():
        target[name(k)] = {name(ek): name(ev)
                           for ek, ev in sm.entries.items()}


def referenced_to_proto(referenced, bag) -> "pb.ReferencedAttributes":
    """Build ReferencedAttributes from the dispatcher's referenced set
    (names and (map, key) pairs): EXACT when the bag had the value,
    ABSENCE when it did not (protoBag.go trackReference conditions)."""
    out = pb.ReferencedAttributes()
    words = _Words(len(GLOBAL_WORD_LIST))
    words.ref("")   # reserve local index 0: proto3 default map_key=0
    #               # must unambiguously mean "no map key"
    for item in sorted(referenced, key=str):
        m = out.attribute_matches.add()
        if isinstance(item, tuple):
            attr, key = item
            m.name = words.ref(attr)
            m.map_key = words.ref(key)
            container, ok = bag.get(attr)
            present = ok and isinstance(container, Mapping) \
                and key in container
        else:
            m.name = words.ref(item)
            _, present = bag.get(item)
        m.condition = pb.ReferencedAttributes.EXACT if present \
            else pb.ReferencedAttributes.ABSENCE
    out.words.extend(words.local)
    return out
