"""CompressedAttributes ↔ attribute-bag codec.

Reference: mixer/pkg/attribute wire model — mutableBag.ToProto
(mutableBag.go:230), ProtoBag lazy decode (protoBag.go:49,161), delta
update for Report (UpdateBagFromProto :311).

Index encoding (dictState.go / protoBag.go): an attribute name or
string value is a sint32 `index`. index < 0 → global dictionary entry
`-index - 1`; index >= 0 → per-message (or per-request default) word
list entry. The global dictionary is the 169-word list in
attribute/global_dict.py; both sides may agree on a shorter prefix via
`global_word_count` (grpcServer.go global dict plumbing).
"""
from __future__ import annotations

import datetime
from typing import Any, Mapping

from istio_tpu.api import mixer_pb2 as pb
from istio_tpu.attribute.bag import Bag
from istio_tpu.attribute.global_dict import (GLOBAL_WORD_INDEX,
                                             GLOBAL_WORD_LIST)


class WireError(ValueError):
    pass


class _Words:
    """Per-message word-list builder (dictState.go)."""

    def __init__(self, global_count: int):
        self.global_count = global_count
        self.local: list[str] = []
        self.index: dict[str, int] = {}

    def ref(self, word: str) -> int:
        gi = GLOBAL_WORD_INDEX.get(word)
        if gi is not None and gi < self.global_count:
            return -gi - 1
        li = self.index.get(word)
        if li is None:
            li = len(self.local)
            self.local.append(word)
            self.index[word] = li
        return li


def _lookup(index: int, words: list[str], global_count: int) -> str:
    if index < 0:
        gi = -index - 1
        if gi >= global_count or gi >= len(GLOBAL_WORD_LIST):
            raise WireError(f"global word index {index} out of range")
        return GLOBAL_WORD_LIST[gi]
    if index >= len(words):
        raise WireError(f"message word index {index} out of range")
    return words[index]


def bag_to_compressed(values: Mapping[str, Any],
                      global_word_count: int | None = None,
                      msg: "pb.CompressedAttributes | None" = None
                      ) -> "pb.CompressedAttributes":
    """Encode name→value attributes (mutableBag.ToProto)."""
    gc = len(GLOBAL_WORD_LIST) if global_word_count is None \
        else global_word_count
    out = msg if msg is not None else pb.CompressedAttributes()
    words = _Words(gc)
    for name in sorted(values):
        v = values[name]
        k = words.ref(name)
        if isinstance(v, bool):
            out.bools[k] = v
        elif isinstance(v, int):
            out.int64s[k] = v
        elif isinstance(v, float):
            out.doubles[k] = v
        elif isinstance(v, str):
            out.strings[k] = words.ref(v)
        elif isinstance(v, bytes):
            out.bytes[k] = v
        elif isinstance(v, datetime.datetime):
            out.timestamps[k].FromDatetime(v)
        elif isinstance(v, datetime.timedelta):
            out.durations[k].FromTimedelta(v)
        elif isinstance(v, Mapping):
            sm = out.string_maps[k]
            for mk in sorted(v):
                sm.entries[words.ref(str(mk))] = words.ref(str(v[mk]))
        else:
            raise WireError(f"cannot encode {name}: {type(v)}")
    out.words.extend(words.local)
    return out


def compressed_to_dict(msg: "pb.CompressedAttributes",
                       global_word_count: int | None = None,
                       default_words: list[str] | None = None
                       ) -> dict[str, Any]:
    """Decode to a plain dict (ProtoBag semantics; default_words are
    the request-level word list Report uses when a record has none)."""
    out: dict[str, Any] = {}
    update_dict_from_proto(out, msg, global_word_count, default_words)
    return out


def update_dict_from_proto(target: dict[str, Any],
                           msg: "pb.CompressedAttributes",
                           global_word_count: int | None = None,
                           default_words: list[str] | None = None) -> None:
    """Delta-apply a record (UpdateBagFromProto mutableBag.go:311)."""
    gc = len(GLOBAL_WORD_LIST) if global_word_count in (None, 0) \
        else global_word_count
    words = list(msg.words) or list(default_words or [])

    def name(i: int) -> str:
        return _lookup(i, words, gc)

    for k, vi in msg.strings.items():
        target[name(k)] = name(vi)
    for k, v in msg.int64s.items():
        target[name(k)] = int(v)
    for k, v in msg.doubles.items():
        target[name(k)] = float(v)
    for k, v in msg.bools.items():
        target[name(k)] = bool(v)
    for k, ts in msg.timestamps.items():
        target[name(k)] = ts.ToDatetime(
            tzinfo=datetime.timezone.utc)
    for k, d in msg.durations.items():
        target[name(k)] = d.ToTimedelta()
    for k, v in msg.bytes.items():
        target[name(k)] = bytes(v)
    for k, sm in msg.string_maps.items():
        target[name(k)] = {name(ek): name(ev)
                           for ek, ev in sm.entries.items()}


def referenced_to_proto(referenced, bag,
                        presence: Mapping | None = None
                        ) -> "pb.ReferencedAttributes":
    """Build ReferencedAttributes from the dispatcher's referenced set
    (names and (map, key) pairs): EXACT when the bag had the value,
    ABSENCE when it did not (protoBag.go trackReference conditions).

    `presence` (item → bool) short-circuits the bag lookups — the fused
    serving path fills it from the device batch's presence planes so a
    wire-decoded request never needs a host-side dict decode."""
    out = pb.ReferencedAttributes()
    words = _Words(len(GLOBAL_WORD_LIST))
    words.ref("")   # reserve local index 0: proto3 default map_key=0
    #               # must unambiguously mean "no map key"
    for item in sorted(referenced, key=str):
        m = out.attribute_matches.add()
        known = presence.get(item) if presence is not None else None
        if isinstance(item, tuple):
            attr, key = item
            m.name = words.ref(attr)
            m.map_key = words.ref(key)
            if known is None:
                container, ok = bag.get(attr)
                known = ok and isinstance(container, Mapping) \
                    and key in container
        else:
            m.name = words.ref(item)
            if known is None:
                _, known = bag.get(item)
        m.condition = pb.ReferencedAttributes.EXACT if known \
            else pb.ReferencedAttributes.ABSENCE
    out.words.extend(words.local)
    return out


# ---------------------------------------------------------------------------
# Raw request splitting — the native-shim fast path
# ---------------------------------------------------------------------------

def _read_varint(data: bytes, off: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = data[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7
        if shift > 63:
            raise WireError("varint overflow")


def _write_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class RawBatchCheckRequest:
    """BatchCheckRequest split at the top level (mixer.proto
    BatchCheckRequest): each repeated `attributes` entry stays raw
    bytes for the native tensorizer. Unlike CheckRequest.attributes
    (a singular message field, where repeats MERGE by concatenation),
    entries here are independent bags — one per check."""

    __slots__ = ("attributes_raw", "global_word_count")

    def __init__(self, data: bytes):
        self.attributes_raw: list[bytes] = []
        self.global_word_count = 0
        off, n = 0, len(data)
        while off < n:
            tag, off = _read_varint(data, off)
            field, wt = tag >> 3, tag & 7
            if wt == 2:
                ln, off = _read_varint(data, off)
                if field == 1:
                    self.attributes_raw.append(data[off:off + ln])
                off += ln
            elif wt == 0:
                v, off = _read_varint(data, off)
                if field == 2:
                    self.global_word_count = v
            elif wt == 1:
                off += 8
            elif wt == 5:
                off += 4
            else:
                raise WireError(f"bad wire type {wt}")


def encode_batch_check_request(attribute_blobs: "list[bytes]",
                               global_word_count: int) -> bytes:
    """Serialize a BatchCheckRequest from pre-serialized
    CompressedAttributes blobs (client/shim side)."""
    parts = []
    for blob in attribute_blobs:
        parts.append(b"\x0a" + _write_varint(len(blob)) + blob)
    if global_word_count:
        parts.append(b"\x10" + _write_varint(global_word_count))
    return b"".join(parts)


def encode_batch_check_response(response_blobs: "list[bytes]") -> bytes:
    """Serialize a BatchCheckResponse from serialized CheckResponse
    blobs (server side)."""
    return b"".join(b"\x0a" + _write_varint(len(b_)) + b_
                    for b_ in response_blobs)


def decode_batch_check_response(data: bytes) -> "list[bytes]":
    """→ serialized CheckResponse blobs (client side)."""
    out, off, n = [], 0, len(data)
    while off < n:
        tag, off = _read_varint(data, off)
        field, wt = tag >> 3, tag & 7
        if wt != 2:
            raise WireError(f"bad wire type {wt} in BatchCheckResponse")
        ln, off = _read_varint(data, off)
        if field == 1:
            out.append(data[off:off + ln])
        off += ln
    return out


class RawCheckRequest:
    """A CheckRequest split at the top level WITHOUT full protobuf
    parsing: the `attributes` submessage stays raw bytes for the native
    tensorizer (istio_tpu/native); only quota params (rare) are parsed.
    Field numbers per istio.mixer.v1 (api/proto/mixer.proto:67-76)."""

    __slots__ = ("attributes_raw", "global_word_count",
                 "deduplication_id", "quotas")

    def __init__(self, data: bytes):
        self.attributes_raw = b""
        self.global_word_count = 0
        self.deduplication_id = ""
        self.quotas: dict[str, Any] = {}
        off, n = 0, len(data)
        while off < n:
            tag, off = _read_varint(data, off)
            field, wt = tag >> 3, tag & 7
            if wt == 2:      # length-delimited
                ln, off = _read_varint(data, off)
                payload = data[off:off + ln]
                off += ln
                if field == 1:
                    # protobuf merge semantics: repeated occurrences of
                    # a message field concatenate
                    self.attributes_raw += payload
                elif field == 3:
                    self.deduplication_id = payload.decode("utf-8")
                elif field == 4:
                    self._add_quota(payload)
            elif wt == 0:    # varint
                v, off = _read_varint(data, off)
                if field == 2:
                    self.global_word_count = v
            elif wt == 1:
                off += 8
            elif wt == 5:
                off += 4
            else:
                raise WireError(f"bad wire type {wt}")

    def _add_quota(self, entry: bytes) -> None:
        """One quotas map entry: key=1 string, value=2 QuotaParams."""
        off, name, params = 0, "", pb.CheckRequest.QuotaParams()
        while off < len(entry):
            tag, off = _read_varint(entry, off)
            field, wt = tag >> 3, tag & 7
            if wt == 0:
                v, off = _read_varint(entry, off)
                continue
            if wt == 1:
                off += 8
                continue
            if wt == 5:
                off += 4
                continue
            if wt != 2:
                raise WireError(f"bad wire type {wt}")
            ln, off = _read_varint(entry, off)
            payload = entry[off:off + ln]
            off += ln
            if field == 1:
                name = payload.decode("utf-8")
            elif field == 2:
                params = pb.CheckRequest.QuotaParams.FromString(payload)
        self.quotas[name] = params


class LazyWireBag(Bag):
    """Bag over raw CompressedAttributes bytes.

    The fused serving path tensorizes `wire` directly in C++ (zero
    host-side decode, the mixerclient contract per SURVEY §2.9(a));
    host consumers (APA preprocess, host-overlay adapters, quota
    instances, referenced-attribute fallbacks) trigger a one-time
    Python decode on first access — the ProtoBag lazy-decode role
    (protoBag.go:49,161)."""

    __slots__ = ("_wire", "_gwc", "_values", "native_ok")

    def __init__(self, wire: bytes, global_word_count: int | None = None,
                 native_ok: bool = True):
        self._wire = wire
        self._gwc = global_word_count
        self._values: dict[str, Any] | None = None
        # False → the C++ decoder can't interpret this encoding (e.g. a
        # shortened global dictionary prefix); the dispatcher must use
        # the python path, but the raw bytes stay intact for _decode
        self.native_ok = native_ok

    @property
    def wire(self) -> bytes | None:
        """Raw bytes for the native tensorizer; None when ineligible
        (the dispatcher then python-tensorizes the whole batch)."""
        return self._wire if self.native_ok else None

    def _decode(self) -> dict[str, Any]:
        if self._values is None:
            msg = pb.CompressedAttributes.FromString(self._wire)
            self._values = compressed_to_dict(msg, self._gwc)
        return self._values

    def get(self, name: str):
        values = self._decode()
        if name in values:
            return values[name], True
        return None, False

    def names(self):
        return list(self._decode())

    def with_attributes(self, extra: Mapping[str, Any]) -> "LazyWireBag":
        """Fresh bag = this bag's attributes + `extra`, RE-ENCODED to
        wire bytes (full global dictionary) so the returned bag stays
        native-tensorizable. This is how admission-time attributes —
        the verified peer identity (`source.user`, `connection.mtls`)
        — reach the device plane: a host-side overlay bag would force
        the whole batch off the C++ tensorizer. `extra` OVERRIDES any
        client-claimed value of the same name on purpose: an
        authenticated identity must beat a spoofed wire attribute."""
        values = dict(self._decode())
        values.update(extra)
        return LazyWireBag(
            bag_to_compressed(values).SerializeToString())
