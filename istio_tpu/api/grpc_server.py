"""Mixer gRPC server — istio.mixer.v1.Mixer over grpcio.

Reference: mixer/pkg/api/grpcServer.go. Check (:118): decode
CompressedAttributes → Preprocess → precondition check → per-quota
loop (:188-230); Report (:262): per-record delta decode → Preprocess →
report. Service wiring uses generic method handlers (no grpcio-tools
in this image); serialization is the generated mixer_pb2.

The precondition path rides the RuntimeServer's batcher, so concurrent
Check RPCs from many sidecar connections coalesce into device steps.
"""
from __future__ import annotations

import datetime
import logging
import threading
import time
from concurrent import futures
from typing import Any

import grpc

from istio_tpu.runtime import resilience
from istio_tpu.runtime.resilience import (CheckRejected,
                                          InvalidArgumentError,
                                          UnauthenticatedError)
from istio_tpu.secure.mtls import (MTLS_OFF, MTLS_STRICT, ServingCerts,
                                   peer_identity_from_auth_context,
                                   validate_mode)

from istio_tpu.adapters.sdk import QuotaArgs
from istio_tpu.api import mixer_pb2 as pb
from istio_tpu.api.wire import (LazyWireBag, RawBatchCheckRequest,
                                RawCheckRequest, WireError,
                                encode_batch_check_response,
                                referenced_to_proto, update_dict_from_proto)
from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST
from istio_tpu.runtime import monitor
from istio_tpu.runtime.server import RuntimeServer

log = logging.getLogger("istio_tpu.api")

_CLAMP_DURATION_S = 3600.0

# typed serving rejections (runtime/resilience.py) → wire status codes:
# overload and degradation must surface as DEADLINE_EXCEEDED /
# RESOURCE_EXHAUSTED / UNAVAILABLE, never a generic INTERNAL
_REJECT_CODES = {
    resilience.INVALID_ARGUMENT: grpc.StatusCode.INVALID_ARGUMENT,
    resilience.DEADLINE_EXCEEDED: grpc.StatusCode.DEADLINE_EXCEEDED,
    resilience.RESOURCE_EXHAUSTED: grpc.StatusCode.RESOURCE_EXHAUSTED,
    resilience.UNAVAILABLE: grpc.StatusCode.UNAVAILABLE,
    resilience.UNAUTHENTICATED: grpc.StatusCode.UNAUTHENTICATED,
}


def _reject_status(exc: CheckRejected) -> "grpc.StatusCode":
    return _REJECT_CODES.get(exc.grpc_code, grpc.StatusCode.UNKNOWN)


class MixerGrpcServer:
    """Serves Check/Report for a RuntimeServer core."""

    def __init__(self, runtime: RuntimeServer, address: str = "127.0.0.1:0",
                 max_workers: int = 16,
                 tls: ServingCerts | None = None,
                 mtls_mode: str = MTLS_OFF):
        self.runtime = runtime
        self._tls = tls
        self.mtls_mode = validate_mode(mtls_mode)
        if self.mtls_mode != MTLS_OFF and tls is None:
            raise ValueError(
                f"mtls={self.mtls_mode} needs serving certs (tls=)")
        # ReferencedAttributes protos memoized per (referenced,
        # presence) signature — the fused dispatcher shares those
        # objects across requests with identical device bitmaps, so
        # uniform traffic builds the proto once instead of per RPC
        self._ref_cache: dict = {}
        self._ref_cache_lock = threading.Lock()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers,
                                       thread_name_prefix="mixer-grpc"))
        handlers = {
            # Check splits the request at the top level instead of fully
            # parsing it: the attributes submessage stays raw bytes for
            # the C++ tensorizer (api/wire.py RawCheckRequest)
            "Check": grpc.unary_unary_rpc_method_handler(
                self._check,
                request_deserializer=RawCheckRequest,
                response_serializer=pb.CheckResponse.SerializeToString),
            "Report": grpc.unary_unary_rpc_method_handler(
                self._report,
                request_deserializer=pb.ReportRequest.FromString,
                response_serializer=pb.ReportResponse.SerializeToString),
            # shim protocol (mixer.proto BatchCheck): raw in, raw out —
            # per-item protos are built once and hand-framed
            "BatchCheck": grpc.unary_unary_rpc_method_handler(
                self._batch_check,
                request_deserializer=RawBatchCheckRequest,
                response_serializer=lambda b: b),
        }
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler("istio.mixer.v1.Mixer",
                                                 handlers),))
        if tls is not None:
            # strict: the handshake REQUIRES + verifies the client
            # cert (grpcio has no request-but-optional mode); _admit
            # then rejects verified-but-identity-less certs typed.
            # The credentials are rotation-aware (cert-config fetcher
            # rides ServingCerts.generation) — see secure/mtls.py.
            self.port = self._server.add_secure_port(
                address, tls.grpc_server_credentials(
                    require_client_auth=self.mtls_mode == MTLS_STRICT))
        else:
            self.port = self._server.add_insecure_port(address)

    # -- lifecycle --

    def start(self) -> int:
        self._server.start()
        log.info("mixer grpc server on port %d", self.port)
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace).wait()

    # -- admission (secure plane) --

    def _admit(self, context) -> str | None:
        """Peer-identity admission for every RPC on this front.

        Returns the verified SPIFFE identity (first spiffe:// URI SAN
        of the TLS-verified client cert) or None for an anonymous
        peer. In strict mode the handshake already required + verified
        a client cert; a peer whose VERIFIED cert carries no SPIFFE
        identity is refused here with a typed UNAUTHENTICATED
        (runtime/resilience.UnauthenticatedError) — an honest wire
        status the meshlint typed-rejection pass and the client's
        error handling both see, never a silent anonymous admit."""
        identity = None
        if self._tls is not None and context is not None:
            try:
                auth = context.auth_context()
            except Exception:
                auth = None
            identity = peer_identity_from_auth_context(auth)
        if identity is not None:
            monitor.IDENTITY_AUTHENTICATED.inc()
            return identity
        if self.mtls_mode == MTLS_STRICT:
            monitor.IDENTITY_UNAUTHENTICATED.inc()
            raise UnauthenticatedError(
                "mTLS strict: no verified client certificate identity")
        return None

    @staticmethod
    def _identity_attrs(identity: str | None) -> dict | None:
        """Admission attributes the verified identity contributes:
        `source.user` (the SPIFFE principal RBAC/authz predicates
        evaluate — on-device, via the re-encoded wire) and
        `connection.mtls`. None for anonymous peers (permissive/off):
        client-supplied attributes pass through untouched."""
        if identity is None:
            return None
        return {"source.user": identity, "connection.mtls": True}

    # -- RPCs --

    def _deadline_from(self, context) -> float | None:
        """Absolute perf_counter deadline for one Check: the client's
        RPC deadline when it sent one, else the server-side default
        (ServerArgs.default_check_deadline_ms; the native front's
        --default-check-deadline-ms knob), else None."""
        remaining = None
        if context is not None:
            try:
                remaining = context.time_remaining()
            except Exception:   # a front without deadline support
                remaining = None
        # grpcio reports a deadline-LESS client as a huge
        # time_remaining (years), not None — treating that as a real
        # deadline both defeats the server-side default below and
        # overflows bounded waits downstream (the executor fold's
        # Event.wait). Anything past a day is "no client deadline".
        if remaining is not None and remaining < 86_400.0:
            return time.perf_counter() + max(remaining, 0.0)
        d_ms = getattr(self.runtime.args, "default_check_deadline_ms",
                       0.0)
        if d_ms:
            return time.perf_counter() + d_ms / 1e3
        return None

    @staticmethod
    def _traceparent_from(context):
        """Incoming W3C traceparent (grpc metadata) → parent-span dict
        for the rpc.check root, so exemplar/server trace ids are
        join-able with the client's trace; None (self-generated ids,
        the previous behavior) when absent or malformed."""
        from istio_tpu.utils import tracing
        if context is None:
            return None
        try:
            md = context.invocation_metadata()
        except Exception:
            return None
        for item in md or ():
            key, value = item[0], item[1]
            if key == "traceparent":
                return tracing.parent_from_traceparent(value)
        return None

    @staticmethod
    def _tag_status(span, code) -> None:
        """`status` tag on a check span — "ok" or the google.rpc /
        grpc code — so /debug/traces?status=failed can filter."""
        if span is not None:
            span["tags"]["status"] = "ok" if code in (0, "0") \
                else str(code)

    def _check(self, request: RawCheckRequest,
               context) -> "pb.CheckResponse":
        # ROOT span at RPC decode (pkg/tracing's interceptor role):
        # the batcher's serve.batch span parents under it (submit
        # captures this thread's current span), so queue-wait is
        # attributed to a REQUEST, not anonymously to a batch. The
        # client's traceparent (when sent) becomes the root's parent.
        from istio_tpu.utils import tracing
        with tracing.get_tracer().span(
                "rpc.check",
                parent=self._traceparent_from(context)) as root:
            try:
                identity = self._admit(context)
                bag = self._check_bag(request, identity=identity)
                deadline = self._deadline_from(context)
                result = self.runtime.check_preprocessed(
                    bag, deadline=deadline)
                self._tag_status(root, result.status_code)
                return self._check_response(request, bag, result,
                                            deadline=deadline,
                                            identity=identity)
            except CheckRejected as exc:
                # abort() raises — the typed rejection becomes the
                # RPC's status instead of an INTERNAL stack trace
                self._tag_status(root, exc.grpc_code)
                context.abort(_reject_status(exc), str(exc))

    def _batch_check(self, request: RawBatchCheckRequest,
                     context) -> bytes:
        """One RPC, many independent Check bags (the data-plane shim's
        amortized front; mixer.proto BatchCheck). Per-item semantics =
        unary Check without quotas/dedup. The batch is padded to the
        server's prewarmed bucket shapes so arbitrary client batch
        sizes never re-trace."""
        try:
            return self._batch_check_body(
                request, self._deadline_from(context),
                parent=self._traceparent_from(context),
                identity=self._admit(context))
        except CheckRejected as exc:
            context.abort(_reject_status(exc), str(exc))

    def _batch_check_body(self, request: RawBatchCheckRequest,
                          deadline: float | None,
                          parent: dict | None = None,
                          identity: str | None = None) -> bytes:
        """Span + dispatch, shared by the sync front (which aborts
        inline) and the aio front (whose abort must be awaited on the
        loop, not called from the executor thread)."""
        from istio_tpu.utils import tracing
        with tracing.get_tracer().span(
                "rpc.batch_check", parent=parent,
                items=len(request.attributes_raw)) as span:
            try:
                return self._batch_check_traced(
                    request, deadline=deadline, span=span,
                    identity=identity)
            except CheckRejected as exc:
                # tag BEFORE the span closes: a rejected batch must
                # show in /debug/traces?status=failed (the unary
                # fronts tag in their own handlers)
                self._tag_status(span, exc.grpc_code)
                raise

    def _batch_check_traced(self, request: RawBatchCheckRequest,
                            deadline: float | None = None,
                            span: dict | None = None,
                            identity: str | None = None) -> bytes:
        gwc = request.global_word_count
        native = gwc in (0, len(GLOBAL_WORD_LIST))
        attrs = self._identity_attrs(identity)

        def _bag(raw):
            bag = LazyWireBag(raw, gwc or None, native_ok=native)
            if attrs is not None:
                # the connection's verified identity covers every item
                # in the batch (one peer, many bags)
                try:
                    bag = bag.with_attributes(attrs)
                except WireError as exc:
                    raise InvalidArgumentError(
                        f"malformed check attributes: {exc}") from exc
            return bag

        bags = [self.runtime.preprocess(_bag(raw))
                for raw in request.attributes_raw]
        if not bags:
            return b""
        monitor.CHECK_REQUESTS.inc(len(bags))
        results = self._check_bags_chunked(bags, deadline=deadline)
        first_bad = next((r.status_code for r in results
                          if r.status_code), 0)
        self._tag_status(span, first_bad)
        blobs = [
            self._check_response(None, bag, result, quotas=[],
                                 identity=identity).SerializeToString()
            for bag, result in zip(bags, results)]
        return encode_batch_check_response(blobs)

    @staticmethod
    def _expired_response():
        """CheckResponse for a request whose deadline expired before
        its chunk dispatched: the precondition status carries
        DEADLINE_EXCEEDED and zero TTLs (nothing was evaluated, so
        nothing may be cached)."""
        from istio_tpu.runtime.dispatcher import CheckResponse
        from istio_tpu.runtime.resilience import DEADLINE_EXCEEDED
        return CheckResponse(status_code=DEADLINE_EXCEEDED,
                             status_message="deadline expired before "
                                            "dispatch",
                             valid_duration_s=0.0, valid_use_count=0)

    def _check_bags_chunked(self, bags: list,
                            deadline: float | None = None) -> list:
        """Preprocessed bags → results, in largest-bucket CHUNKS padded
        to the prewarmed bucket shapes — an arbitrary over-bucket shape
        would force a fresh device compile per distinct size (client-
        controlled stalls). Single home of the rule: the BatchCheck
        front and the native front-end pump both ride it. `deadline`:
        chunks reached after it expire pre-tensorize — every remaining
        row answers DEADLINE_EXCEEDED instead of queueing device work
        the caller already abandoned."""
        from istio_tpu.runtime.batcher import pad_to_bucket

        buckets = self.runtime.batcher.buckets
        results: list = []
        for lo in range(0, len(bags), buckets[-1]):
            chunk = bags[lo:lo + buckets[-1]]
            if deadline is not None and \
                    time.perf_counter() >= deadline:
                monitor.CHECK_DEADLINE_EXPIRED.inc(len(chunk))
                results.extend(self._expired_response()
                               for _ in chunk)
                continue
            padded = pad_to_bucket(chunk, buckets)
            results.extend(
                self.runtime.check_batch_preprocessed(padded)[:len(chunk)])
        return results

    def _check_bags_quota_instep(self, bags: list, qspecs: list,
                                 target, deadline: float | None = None
                                 ) -> tuple[list, dict]:
        """_check_bags_chunked with each chunk's quota rows allocated
        IN its check trip (ServerArgs.quota_in_step; the pool-flush
        trip disappears — FusedPlan.packed_check_instep). qspecs[i] is
        (name, QuotaArgs) or None; `target` from
        RuntimeServer.instep_quota_target(). Returns (results,
        {global row → QuotaResult}); rows whose check was denied keep
        their entry but callers must NOT attach it (the device gate
        consumed nothing for them — grpcServer.go:188). `deadline`:
        chunks reached after it expire pre-tensorize like the
        non-quota chunked path — their quota rows allocate NOTHING
        (nothing was evaluated, nothing may be consumed)."""
        from istio_tpu.runtime.batcher import pad_to_bucket

        buckets = self.runtime.batcher.buckets
        results: list = []
        qres: dict[int, Any] = {}
        cap = buckets[-1]
        for lo in range(0, len(bags), cap):
            chunk = bags[lo:lo + cap]
            if deadline is not None and \
                    time.perf_counter() >= deadline:
                monitor.CHECK_DEADLINE_EXPIRED.inc(len(chunk))
                results.extend(self._expired_response()
                               for _ in chunk)
                continue
            padded = pad_to_bucket(chunk, buckets)
            qrows = [(i, qspecs[lo + i][0], qspecs[lo + i][1])
                     for i in range(len(chunk))
                     if qspecs[lo + i] is not None]
            resps, rq = self.runtime.check_batch_quota_instep(
                padded, qrows, target)
            results.extend(resps[:len(chunk)])
            for i, qr in rq.items():
                qres[lo + i] = qr
        return results, qres

    def _check_bag(self, request: RawCheckRequest,
                   identity: str | None = None):
        monitor.CHECK_REQUESTS.inc()
        gwc = request.global_word_count
        # a non-default dictionary prefix forces the python wire path —
        # the C++ decoder assumes the full global list
        bag = LazyWireBag(request.attributes_raw, gwc or None,
                          native_ok=gwc in (0, len(GLOBAL_WORD_LIST)))
        attrs = self._identity_attrs(identity)
        if attrs is not None:
            # fold the VERIFIED peer identity into the wire itself
            # (re-encode) so device tensorization — and therefore the
            # compiled RBAC predicates — see source.user exactly as
            # the SnapshotOracle does
            try:
                bag = bag.with_attributes(attrs)
            except WireError as exc:
                raise InvalidArgumentError(
                    f"malformed check attributes: {exc}") from exc
        # preprocess ONCE; precondition check and quota loop share the
        # bag (a no-op returning the wire bag when no APA is configured)
        return self.runtime.preprocess(bag)

    def _check_response(self, request: RawCheckRequest, bag,
                        result, quotas: list | None = None,
                        deadline: float | None = None,
                        identity: str | None = None
                        ) -> "pb.CheckResponse":
        resp = pb.CheckResponse()
        resp.precondition.status.code = result.status_code
        if result.status_message:
            resp.precondition.status.message = result.status_message
        ttl_s = min(result.valid_duration_s, _CLAMP_DURATION_S)
        uses = min(result.valid_use_count, 2**31 - 1)
        if identity is not None and self.runtime.grants is not None:
            # identity axis of the grant plane (runtime/grants.py):
            # a peer whose identity just rotated must not ride a
            # stale cached verdict — min() like every TTL source
            ittl, iuses = self.runtime.grants.identity_grant(identity)
            ttl_s = min(ttl_s, ittl)
            uses = min(uses, iuses)
        resp.precondition.valid_duration.FromTimedelta(
            datetime.timedelta(seconds=ttl_s))
        resp.precondition.valid_use_count = uses
        resp.precondition.referenced_attributes.CopyFrom(
            self._referenced_proto(result, bag))

        # quota loop (grpcServer.go:188-230): only on successful check.
        # Fused path: device quota pools + the check step's activity
        # bits (no re-resolve); pending futures are collected first so
        # multiple quotas in one request share a device batch.
        if result.status_code == 0:
            if quotas is None:
                quotas = self._submit_quotas(request, bag, result,
                                             deadline=deadline)
            for name, qr in quotas:
                if hasattr(qr, "result"):   # QuotaFuture (sync front)
                    qr = qr.result()
                out = resp.quotas[name]
                out.granted_amount = qr.granted_amount
                out.valid_duration.FromTimedelta(datetime.timedelta(
                    seconds=min(qr.valid_duration_s, _CLAMP_DURATION_S)))
        monitor.CHECK_RESPONSES.inc()
        return resp

    @staticmethod
    def _quota_args(request: RawCheckRequest, name: str,
                    params) -> QuotaArgs:
        return QuotaArgs(quota_amount=params.amount,
                         best_effort=params.best_effort,
                         dedup_id=request.deduplication_id + ":" + name
                         if request.deduplication_id else "")

    def _submit_quotas(self, request: RawCheckRequest, bag,
                       result, deadline: float | None = None) -> list:
        """→ [(name, QuotaResult | QuotaFuture)] — non-blocking on the
        fused path (pool futures); the dispatcher fallback (generic
        path / non-device quota handler) resolves inline, its host
        adapter call bounded by the RPC deadline (executor plane)."""
        pending = []
        for name, params in request.quotas.items():
            args = self._quota_args(request, name, params)
            qr = self.runtime.quota_fused(bag, name, args, result)
            if qr is None:   # generic path / non-device handler
                qr = self.runtime.quota(bag, name, args,
                                        preprocessed=True,
                                        deadline=deadline)
            pending.append((name, qr))
        return pending

    def _referenced_proto(self, result, bag) -> "pb.ReferencedAttributes":
        presence = result.referenced_presence
        if presence is None or len(presence) != len(result.referenced):
            # presence incomplete → the proto depends on this bag
            return referenced_to_proto(result.referenced, bag, presence)
        key = (result.referenced,
               frozenset(presence.items()) if presence else frozenset())
        with self._ref_cache_lock:
            cached = self._ref_cache.get(key)
        if cached is None:
            cached = referenced_to_proto(result.referenced, bag, presence)
            with self._ref_cache_lock:
                if len(self._ref_cache) > 4096:
                    self._ref_cache.clear()
                self._ref_cache[key] = cached
        return cached

    def _decode_report(self, request: "pb.ReportRequest") -> list:
        bags = []
        current: dict[str, Any] = {}
        default_words = list(request.default_words)
        for record in request.attributes:
            # delta decode (grpcServer.go:262-353)
            update_dict_from_proto(current, record,
                                   request.global_word_count or None,
                                   default_words)
            bags.append(bag_from_mapping(dict(current)))
        return bags

    def _report(self, request: "pb.ReportRequest",
                context) -> "pb.ReportResponse":
        # ROOT span at RPC decode (the report analog of rpc.check):
        # the coalescer's serve.batch span parents under it via the
        # thread-local stack; the client's W3C traceparent (metadata)
        # becomes the root's parent when sent
        from istio_tpu.utils import tracing
        monitor.REPORT_REQUESTS.inc()
        with tracing.get_tracer().span(
                "rpc.report", parent=self._traceparent_from(context),
                records=len(request.attributes)) as root:
            try:
                # strict mTLS covers the telemetry path too — an
                # anonymous peer must not inject report records
                self._admit(context)
            except CheckRejected as exc:
                self._tag_status(root, exc.grpc_code)
                context.abort(_reject_status(exc), str(exc))
            t0 = time.perf_counter()
            bags = self._decode_report(request)
            monitor.observe_report_stage("wire_decode",
                                         time.perf_counter() - t0)
            try:
                if bags:
                    self.runtime.report(bags)
            except CheckRejected as exc:
                # typed admission rejection (bounded report queue,
                # draining): the honest wire code, never INTERNAL
                self._tag_status(root, exc.grpc_code)
                context.abort(_reject_status(exc), str(exc))
            self._tag_status(root, 0)
        monitor.REPORT_RESPONSES.inc()
        return pb.ReportResponse()


class MixerAioGrpcServer(MixerGrpcServer):
    """Asyncio variant of the Mixer front-end.

    The sync server parks one thread-pool thread in `future.result()`
    for every in-flight Check — with the batcher's round-trip at
    ~100ms+ behind a remote device transport, throughput caps at
    workers / round-trip and the thread count itself melts the GIL.
    Here handlers `await` the batcher future on one event loop, so
    thousands of checks can be in flight from a single thread (the
    role grpcServer.go gets for free from goroutines)."""

    def __init__(self, runtime: RuntimeServer,
                 address: str = "127.0.0.1:0",
                 tls: ServingCerts | None = None,
                 mtls_mode: str = MTLS_OFF):
        # note: deliberately NOT calling super().__init__ — the sync
        # grpc.server and its thread pool are replaced by an aio
        # server owned by a loop thread
        self.runtime = runtime
        self._tls = tls
        self.mtls_mode = validate_mode(mtls_mode)
        if self.mtls_mode != MTLS_OFF and tls is None:
            raise ValueError(
                f"mtls={self.mtls_mode} needs serving certs (tls=)")
        self._ref_cache = {}
        self._ref_cache_lock = threading.Lock()
        self._address = address
        self._loop = None
        self._server = None
        self._ready = threading.Event()
        self._stopped = threading.Event()
        self.port = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mixer-aio-grpc")

    async def _abatch_check(self, request: RawBatchCheckRequest,
                            context) -> bytes:
        import asyncio
        deadline = self._deadline_from(context)
        try:
            identity = self._admit(context)
            # tensorize + device step block — off the loop
            return await asyncio.get_running_loop().run_in_executor(
                None, self._batch_check_body, request, deadline,
                self._traceparent_from(context), identity)
        except CheckRejected as exc:
            # aio abort is a coroutine and must run ON the loop — the
            # sync _batch_check's inline abort would no-op here
            await context.abort(_reject_status(exc), str(exc))

    async def _acheck(self, request: RawCheckRequest,
                      context) -> "pb.CheckResponse":
        import asyncio

        from istio_tpu.utils import tracing
        loop = asyncio.get_running_loop()
        # ROOT span at RPC decode, DETACHED (start_span): a `with`
        # span held across an await would leak onto interleaved tasks
        # via the thread-local stack. The batcher parents its batch
        # span under this dict (submit trace=).
        tr = tracing.get_tracer()
        root = tr.start_span("rpc.check",
                             parent=self._traceparent_from(context))
        try:
            return await self._acheck_traced(
                request, loop, root,
                deadline=self._deadline_from(context),
                identity=self._admit(context))
        except CheckRejected as exc:
            self._tag_status(root, exc.grpc_code)
            await context.abort(_reject_status(exc), str(exc))
        finally:
            tr.finish_span(root)

    async def _acheck_traced(self, request: RawCheckRequest, loop,
                             root,
                             deadline: float | None = None,
                             identity: str | None = None
                             ) -> "pb.CheckResponse":
        import asyncio
        d = self.runtime.controller.dispatcher
        if self.runtime.args.preprocess and d.has_apa:
            # preprocess runs an APA device round-trip — off the loop
            bag = await loop.run_in_executor(None, self._check_bag,
                                             request, identity)
        else:
            # identity preprocess: the executor hop would cost more
            # than the work
            bag = self._check_bag(request, identity)
        # shield: a client cancel must cancel THIS handler only, never
        # the shared batcher future (a cancelled batch-mate would
        # otherwise poison result distribution for the whole batch)
        result = await asyncio.shield(asyncio.wrap_future(
            self.runtime.submit_check_preprocessed(
                bag, trace=root, deadline=deadline)))
        self._tag_status(root, result.status_code)
        if request.quotas and result.status_code == 0:
            # fused-path quota futures bridge to the loop via
            # callbacks — an in-flight quota holds NO thread (an
            # executor thread per pending device batch serialized the
            # whole server behind ~5 threads × an RTT)
            # submit EVERY quota first so they share a device batch
            # window, then await — a per-quota await would serialize k
            # quotas into k windows
            pending = []
            for name, params in request.quotas.items():
                args = self._quota_args(request, name, params)
                qr = self.runtime.quota_fused(bag, name, args, result)
                if qr is None:
                    # dispatcher fallback re-resolves (device RTT) —
                    # off the loop; host adapter call bounded by the
                    # RPC deadline (executor plane)
                    qr = loop.run_in_executor(
                        None, self.runtime.quota, bag, name, args,
                        True, deadline)
                elif hasattr(qr, "add_done_callback"):
                    af = loop.create_future()

                    def _resolve(v, af=af):
                        # a client cancel mid-quota marks af done —
                        # setting a result then raises InvalidStateError
                        # inside a loop callback (observed r4)
                        if not af.done():
                            af.set_result(v)
                    qr.add_done_callback(
                        lambda v, _r=_resolve: loop.call_soon_threadsafe(
                            _r, v))
                    qr = af
                pending.append((name, qr))
            quotas = []
            for name, qr in pending:
                if asyncio.isfuture(qr):
                    qr = await qr
                quotas.append((name, qr))
            return self._check_response(request, bag, result,
                                        quotas=quotas,
                                        identity=identity)
        return self._check_response(request, bag, result,
                                    identity=identity)

    async def _areport(self, request: "pb.ReportRequest",
                       context) -> "pb.ReportResponse":
        import asyncio

        from istio_tpu.utils import tracing
        loop = asyncio.get_running_loop()
        monitor.REPORT_REQUESTS.inc()
        # rpc.report root: built inline (not via the thread-local
        # `with` — handler awaits hop threads); wire_decode is timed
        # in the executor wrapper so the stage covers the real work
        root = tracing.get_tracer().span(
            "rpc.report", parent=self._traceparent_from(context),
            records=len(request.attributes), transport="grpc-aio")

        def _decode():
            t0 = time.perf_counter()
            bags = self._decode_report(request)
            monitor.observe_report_stage("wire_decode",
                                         time.perf_counter() - t0)
            return bags

        with root as span:
            try:
                # strict mTLS covers the telemetry path too
                self._admit(context)
            except CheckRejected as exc:
                self._tag_status(span, exc.grpc_code)
                await context.abort(_reject_status(exc), str(exc))
            # decode + preprocess are synchronous host work — off the
            # loop; the WAIT for the coalesced batches holds no thread
            # (futures bridge back via wrap_future, like _acheck), so
            # in-flight Reports are bounded by the batcher, not a pool
            bags = await loop.run_in_executor(None, _decode)
            if bags:
                futs = await loop.run_in_executor(
                    None, self.runtime.submit_report, bags)
                if futs:
                    # shield: a client cancel must never poison shared
                    # batch-mates; gather-with-exceptions retrieves
                    # every future before the first error re-raises
                    results = await asyncio.shield(asyncio.gather(
                        *[asyncio.wrap_future(f) for f in futs],
                        return_exceptions=True))
                    first = next((r for r in results
                                  if isinstance(r, BaseException)),
                                 None)
                    if first is not None:
                        if isinstance(first, CheckRejected):
                            # typed shed (bounded report queue,
                            # draining) → honest wire status; aio
                            # abort is a coroutine and must run ON
                            # the loop
                            self._tag_status(span, first.grpc_code)
                            await context.abort(_reject_status(first),
                                                str(first))
                        # programming errors (non-CheckRejected) ride
                        # grpc's catch-all to UNKNOWN on purpose — a
                        # typed wrapper here would mislabel bugs as
                        # load sheds
                        raise first   # meshlint: raise-ok bug-surface
            self._tag_status(span, 0)
        monitor.REPORT_RESPONSES.inc()
        return pb.ReportResponse()

    def _run(self) -> None:
        import asyncio

        from grpc import aio

        async def serve():
            # dedicated executor for the blocking offloads. Check and
            # Report decode are short (their batch waits bridge back
            # via wrap_future, holding no thread), but _abatch_check
            # and the non-fused quota fallback still park a thread
            # across a full device trip — size for a burst of those
            # so unary decode never queues behind a device step
            # (asyncio's default is only ~cpu+4 on a small box)
            from concurrent.futures import ThreadPoolExecutor
            asyncio.get_running_loop().set_default_executor(
                ThreadPoolExecutor(max_workers=32,
                                   thread_name_prefix="mixer-aio-exec"))
            server = aio.server()
            handlers = {
                "Check": grpc.unary_unary_rpc_method_handler(
                    self._acheck,
                    request_deserializer=RawCheckRequest,
                    response_serializer=pb.CheckResponse.SerializeToString),
                "Report": grpc.unary_unary_rpc_method_handler(
                    self._areport,
                    request_deserializer=pb.ReportRequest.FromString,
                    response_serializer=pb.ReportResponse.SerializeToString),
                "BatchCheck": grpc.unary_unary_rpc_method_handler(
                    self._abatch_check,
                    request_deserializer=RawBatchCheckRequest,
                    response_serializer=lambda b: b),
            }
            server.add_generic_rpc_handlers((
                grpc.method_handlers_generic_handler(
                    "istio.mixer.v1.Mixer", handlers),))
            if self._tls is not None:
                # same posture as the sync front: strict requires the
                # client cert at handshake, _admit types the
                # identity-less-cert rejection
                self.port = server.add_secure_port(
                    self._address, self._tls.grpc_server_credentials(
                        require_client_auth=self.mtls_mode
                        == MTLS_STRICT))
            else:
                self.port = server.add_insecure_port(self._address)
            await server.start()
            self._server = server
            self._ready.set()
            await server.wait_for_termination()

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(serve())
        finally:
            self._loop.close()
            self._stopped.set()

    def start(self) -> int:
        self._thread.start()
        if not self._ready.wait(timeout=30):
            raise RuntimeError("aio grpc server failed to start")
        log.info("mixer aio grpc server on port %d", self.port)
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        import asyncio
        if self._loop is None or self._server is None:
            return
        asyncio.run_coroutine_threadsafe(
            self._server.stop(grace), self._loop)
        self._stopped.wait(timeout=grace + 10)
