"""Mixer gRPC server — istio.mixer.v1.Mixer over grpcio.

Reference: mixer/pkg/api/grpcServer.go. Check (:118): decode
CompressedAttributes → Preprocess → precondition check → per-quota
loop (:188-230); Report (:262): per-record delta decode → Preprocess →
report. Service wiring uses generic method handlers (no grpcio-tools
in this image); serialization is the generated mixer_pb2.

The precondition path rides the RuntimeServer's batcher, so concurrent
Check RPCs from many sidecar connections coalesce into device steps.
"""
from __future__ import annotations

import datetime
import logging
from concurrent import futures
from typing import Any

import grpc

from istio_tpu.adapters.sdk import QuotaArgs
from istio_tpu.api import mixer_pb2 as pb
from istio_tpu.api.wire import (LazyWireBag, RawCheckRequest,
                                referenced_to_proto, update_dict_from_proto)
from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST
from istio_tpu.runtime.server import RuntimeServer

log = logging.getLogger("istio_tpu.api")

_CLAMP_DURATION_S = 3600.0


class MixerGrpcServer:
    """Serves Check/Report for a RuntimeServer core."""

    def __init__(self, runtime: RuntimeServer, address: str = "127.0.0.1:0",
                 max_workers: int = 16):
        self.runtime = runtime
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers,
                                       thread_name_prefix="mixer-grpc"))
        handlers = {
            # Check splits the request at the top level instead of fully
            # parsing it: the attributes submessage stays raw bytes for
            # the C++ tensorizer (api/wire.py RawCheckRequest)
            "Check": grpc.unary_unary_rpc_method_handler(
                self._check,
                request_deserializer=RawCheckRequest,
                response_serializer=pb.CheckResponse.SerializeToString),
            "Report": grpc.unary_unary_rpc_method_handler(
                self._report,
                request_deserializer=pb.ReportRequest.FromString,
                response_serializer=pb.ReportResponse.SerializeToString),
        }
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler("istio.mixer.v1.Mixer",
                                                 handlers),))
        self.port = self._server.add_insecure_port(address)

    # -- lifecycle --

    def start(self) -> int:
        self._server.start()
        log.info("mixer grpc server on port %d", self.port)
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace).wait()

    # -- RPCs --

    def _check(self, request: RawCheckRequest,
               context) -> "pb.CheckResponse":
        gwc = request.global_word_count
        # a non-default dictionary prefix forces the python wire path —
        # the C++ decoder assumes the full global list
        bag = LazyWireBag(request.attributes_raw, gwc or None,
                          native_ok=gwc in (0, len(GLOBAL_WORD_LIST)))
        # preprocess ONCE; precondition check and quota loop share the
        # bag (a no-op returning the wire bag when no APA is configured)
        bag = self.runtime.preprocess(bag)

        resp = pb.CheckResponse()
        result = self.runtime.check_preprocessed(bag)
        resp.precondition.status.code = result.status_code
        if result.status_message:
            resp.precondition.status.message = result.status_message
        resp.precondition.valid_duration.FromTimedelta(
            datetime.timedelta(seconds=min(result.valid_duration_s,
                                           _CLAMP_DURATION_S)))
        resp.precondition.valid_use_count = min(result.valid_use_count,
                                                2**31 - 1)
        resp.precondition.referenced_attributes.CopyFrom(
            referenced_to_proto(result.referenced, bag,
                                result.referenced_presence))

        # quota loop (grpcServer.go:188-230): only on successful check
        if result.status_code == 0:
            for name, params in request.quotas.items():
                args = QuotaArgs(quota_amount=params.amount,
                                 best_effort=params.best_effort,
                                 dedup_id=request.deduplication_id +
                                 ":" + name if request.deduplication_id
                                 else "")
                qr = self.runtime.quota(bag, name, args,
                                        preprocessed=True)
                out = resp.quotas[name]
                out.granted_amount = qr.granted_amount
                out.valid_duration.FromTimedelta(datetime.timedelta(
                    seconds=min(qr.valid_duration_s, _CLAMP_DURATION_S)))
        return resp

    def _report(self, request: "pb.ReportRequest",
                context) -> "pb.ReportResponse":
        bags = []
        current: dict[str, Any] = {}
        default_words = list(request.default_words)
        for record in request.attributes:
            # delta decode (grpcServer.go:262-353)
            update_dict_from_proto(current, record,
                                   request.global_word_count or None,
                                   default_words)
            bags.append(bag_from_mapping(dict(current)))
        if bags:
            self.runtime.report(bags)
        return pb.ReportResponse()
