"""Adapter executor — host actions off the hot path, bulkheaded,
deadline-bounded.

The reference Mixer never runs adapter work inline: every aspect
dispatch goes through a bounded goroutine pool (mixer/pkg/pool,
SURVEY §2) precisely so one slow backend cannot stall the request
path. This module is that pool for the TPU build, shaped by the same
overload-control doctrine as runtime/resilience.py:

  * BULKHEADS — every handler (qualified name) owns its own bounded
    work queue and worker share. A wedged stackdriver exhausts its own
    lane's workers and queue; memquota's lane never notices. Overflow
    sheds typed (the ResourceExhaustedError family), never silently.
  * DEADLINE-BOUNDED FOLD — a host action inherits the request's
    remaining check deadline (threaded batcher → dispatcher →
    resolve()). An action still running at the deadline resolves to
    the configured --host-fail-policy verdict (open → OK with a
    1s/1-use TTL so the policy-bypass window closes with the outage;
    closed → UNAVAILABLE) and is counted `overrun` — the batch folds
    as soon as device results + resolved-or-defaulted host bits are
    in, never held by a wedged backend.
  * PER-HANDLER CIRCUIT BREAKERS — the PR 2 CircuitBreaker, one per
    lane, persisting across config swaps (handler identity outlives
    snapshots, like the sharded plane's per-bank breakers). An open
    breaker short-circuits straight to the fail policy; recovery rides
    the standard half-open probe.
  * MAINTENANCE LANE — periodic host work (list-provider refresh,
    list.go:115-247's TTL loop) runs on a dedicated lane driven by the
    executor's own scheduler, pinned off the timed request window.

Accounting is conservation-exact at resolve(): every submitted action
resolves with EXACTLY one outcome in {ok, error, shed, expired,
overrun, breaker_open} (monitor.host_action_counters asserts
submitted == resolved). A worker completing an action the fold already
abandoned records `late_ok`/`late_error` separately — late results
are accounting, never verdicts.

Plain exceptions keep safeDispatch semantics (dispatcher.go:399): one
jittered retry, then the action's own INTERNAL result — NOT the fail
policy — so executor-path verdicts stay oracle-identical to the
generic host path whenever no breaker is open and no deadline struck.
"""
from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import Any, Callable, Mapping

from istio_tpu.runtime.resilience import (CHAOS, ChaosHooks,
                                          CircuitBreaker)

log = logging.getLogger("istio_tpu.runtime.executor")

# the lane every periodic/maintenance job runs on (provider refresh);
# never used for request-path actions
MAINTENANCE_LANE = "__maintenance__"

OUTCOMES = ("ok", "error", "shed", "expired", "overrun",
            "breaker_open")


@dataclasses.dataclass
class ExecutorConfig:
    """Knobs for the AdapterExecutor (ServerArgs mirrors these; mixs
    exposes --host-fail-policy / --executor-workers)."""
    # worker threads per handler lane (the bulkhead's concurrency
    # share; blocking adapters overlap up to this many calls)
    workers: int = 2
    # pending host actions per lane; submits past it shed typed
    queue_cap: int = 256
    # what an unresolvable host action contributes to the response:
    # "open" → OK with a 1s/1-use TTL (Mixer-client fail-open — policy
    # must not take the mesh down), "closed" → UNAVAILABLE
    fail_policy: str = "closed"
    # extra per-action bound applied even when the request carries no
    # deadline (0 = bound by the request deadline only)
    action_timeout_s: float = 0.0
    # per-handler breaker: consecutive failed/overrun actions that
    # trip it, and how long it stays open before a half-open probe
    breaker_failures: int = 3
    breaker_reset_s: float = 5.0
    # retry a failed adapter call once (jittered) before counting it
    retry: bool = True
    retry_backoff_s: float = 0.002
    retry_jitter_s: float = 0.004
    # maintenance scheduler poll quantum
    maintenance_tick_s: float = 0.25


class HostAction:
    """One submitted host adapter call. The WORKER completes it (sets
    the adapter's real result); the FOLD claims it exactly once via
    AdapterExecutor.resolve() — whichever side is late, accounting
    stays single-home: claim carries the conservation outcome, a
    completion after claim only bumps the late_* counters."""

    __slots__ = ("handler", "fallback", "fn", "_done", "_lock",
                 "_result", "_worker_outcome", "_claimed", "_wall",
                 "immediate")

    def __init__(self, handler: str,
                 fallback: Callable[[str, str], Any],
                 immediate: str | None = None,
                 fn: Callable[[], Any] | None = None):
        self.handler = handler
        self.fallback = fallback
        self.fn = fn
        # set for actions rejected AT submit (breaker_open / shed /
        # expired): resolve() never waits on these
        self.immediate = immediate
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._result: Any = None
        self._worker_outcome: str | None = None
        self._claimed = False
        self._wall = 0.0

    def _complete(self, result: Any, outcome: str,
                  wall: float) -> bool:
        """Worker side. Returns True when the fold still owns the
        action (normal completion); False when it was already
        abandoned (the late_* accounting path)."""
        with self._lock:
            self._result = result
            self._worker_outcome = outcome
            self._wall = wall
            fresh = not self._claimed
        self._done.set()
        return fresh

    def _claim(self, timeout: float | None
               ) -> tuple[Any, str, float] | None:
        """Fold side: wait up to `timeout`, claim the result. None →
        the action is still running (abandoned; caller applies the
        fail policy and counts `overrun`)."""
        self._done.wait(timeout)
        with self._lock:
            self._claimed = True
            if self._worker_outcome is None:
                return None
            return self._result, self._worker_outcome, self._wall


class HandlerLane:
    """One handler's bulkhead: bounded queue + dedicated workers + its
    own circuit breaker. Lanes persist across config swaps (keyed by
    qualified handler name) so breaker state survives republishes the
    way the sharded plane's per-bank breakers do."""

    def __init__(self, name: str, config: ExecutorConfig,
                 chaos: ChaosHooks):
        self.name = name
        self.config = config
        self.chaos = chaos
        # publish=False: the device breaker gauge belongs to the
        # device path — per-handler state surfaces via snapshot()
        self.breaker = CircuitBreaker(config.breaker_failures,
                                      config.breaker_reset_s,
                                      publish=False,
                                      name=f"handler:{name}")
        self._queue: "queue.Queue[HostAction | None]" = \
            queue.Queue(maxsize=max(int(config.queue_cap), 1))
        self._lock = threading.Lock()
        self._in_flight: dict[int, float] = {}   # id(act) → start wall
        self._closed = False
        self.outcomes: dict[str, int] = {o: 0 for o in OUTCOMES}
        self.late = {"ok": 0, "error": 0}
        self.submitted = 0
        self._workers = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"adapter-{name}-{i}")
            for i in range(max(int(config.workers), 1))]
        for t in self._workers:
            t.start()

    # -- submit side (dispatcher worker threads) ----------------------

    def submit(self, fn: Callable[[], Any],
               fallback: Callable[[str, str], Any]) -> HostAction:
        """Queue one adapter call. Never blocks and never raises out:
        a full queue, a closed lane or an open breaker returns an
        action pre-resolved to its typed immediate outcome — the fold
        applies the fail policy and the counters say why."""
        with self._lock:
            self.submitted += 1
        if self._closed:
            return HostAction(self.name, fallback, immediate="shed")
        if not self.breaker.allow_device():
            return HostAction(self.name, fallback,
                              immediate="breaker_open")
        act = HostAction(self.name, fallback, fn=fn)
        try:
            self._queue.put_nowait(act)
        except queue.Full:
            # bulkhead overflow: typed shed, never an unbounded queue
            # behind a wedged backend. The breaker slot allow_device
            # may have granted (a half-open probe) must be returned —
            # the probe never ran.
            self.breaker.release_probe()
            return HostAction(self.name, fallback, immediate="shed")
        return act

    # -- worker side --------------------------------------------------

    def _worker(self) -> None:
        while True:
            act = self._queue.get()
            if act is None:
                return
            self._run_one(act, act.fn)

    def _run_one(self, act: HostAction,
                 fn: Callable[[], Any]) -> None:
        import random

        from istio_tpu.runtime import monitor

        t0 = time.perf_counter()
        with self._lock:
            self._in_flight[id(act)] = t0
        outcome = "ok"
        result: Any = None
        try:
            try:
                self.chaos.adapter_call(self.name)
                result = fn()
            except Exception as exc:
                if self.config.retry:
                    # one jittered retry absorbs transient backend
                    # faults without involving the breaker
                    time.sleep(self.config.retry_backoff_s +
                               random.random() *
                               self.config.retry_jitter_s)
                    monitor.note_host_action_retry(self.name)
                    try:
                        self.chaos.adapter_call(self.name)
                        result = fn()
                    except Exception as exc2:
                        outcome, result = "error", exc2
                    else:
                        self.breaker.record_success()
                else:
                    outcome, result = "error", exc
                if outcome == "error":
                    self.breaker.record_failure()
            else:
                self.breaker.record_success()
        finally:
            with self._lock:
                self._in_flight.pop(id(act), None)
        wall = time.perf_counter() - t0
        if not act._complete(result, outcome, wall):
            # the fold already gave up on this action (overrun): the
            # late completion is accounting only — its result must
            # never reach a response the policy already answered
            key = "ok" if outcome == "ok" else "error"
            with self._lock:
                self.late[key] += 1
            monitor.note_host_action_late(self.name, key)

    def note_outcome(self, outcome: str) -> None:
        with self._lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1

    # -- introspection / lifecycle ------------------------------------

    def stats(self) -> dict:
        with self._lock:
            in_flight = dict(self._in_flight)
            outcomes = dict(self.outcomes)
            late = dict(self.late)
            submitted = self.submitted
        now = time.perf_counter()
        oldest = max((now - t for t in in_flight.values()),
                     default=0.0)
        return {
            "queue_depth": self._queue.qsize(),
            "queue_cap": self._queue.maxsize,
            "workers": len(self._workers),
            "in_flight": len(in_flight),
            # a wedged lane shows up here: actions running far past
            # any sane adapter wall are the smoking gun
            "oldest_running_s": round(oldest, 3),
            "submitted": submitted,
            "outcomes": outcomes,
            "late": late,
            "breaker": self.breaker.snapshot(),
        }

    def close(self, grace_s: float = 1.0) -> None:
        """Stop the workers. A wedged worker gets its thread LEAKED
        (daemon), never joined forever — the h2srv doctrine: a stuck
        backend must not wedge shutdown."""
        self._closed = True
        for _ in self._workers:
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                break
        end = time.perf_counter() + grace_s
        for t in self._workers:
            t.join(timeout=max(end - time.perf_counter(), 0.0))


class AdapterExecutor:
    """The adapter-executor plane: per-handler bulkhead lanes, the
    deadline-bounded resolve/fold contract, and the maintenance
    scheduler. One instance per RuntimeServer, shared by every
    dispatcher generation (lanes and breakers outlive config swaps)."""

    def __init__(self, config: ExecutorConfig | None = None,
                 chaos: ChaosHooks | None = None):
        self.config = config or ExecutorConfig()
        if self.config.fail_policy not in ("open", "closed"):
            raise ValueError(
                f"host fail_policy must be 'open' or 'closed', got "
                f"{self.config.fail_policy!r}")
        self.chaos = chaos if chaos is not None else CHAOS
        self._lanes: dict[str, HandlerLane] = {}
        self._lanes_lock = threading.Lock()
        self._closed = False
        # maintenance registry: name → {fn, interval_s, next_due, ...}
        self._refresh: dict[str, dict] = {}
        # persistent refreshables (e.g. the workload-identity rotation
        # loop): unlike handler providers these are NOT rebuilt from
        # the published handler map, so a config republish must not
        # evict them — register_refreshables re-merges this dict
        self._persistent_refresh: dict[str, Any] = {}
        self._refresh_lock = threading.Lock()
        self._maint_stop = threading.Event()
        self._maint_thread: threading.Thread | None = None

    # -- lanes ---------------------------------------------------------

    def lane(self, handler: str) -> HandlerLane:
        ln = self._lanes.get(handler)
        if ln is None:
            with self._lanes_lock:
                ln = self._lanes.get(handler)
                if ln is None:
                    ln = HandlerLane(handler, self.config, self.chaos)
                    self._lanes[handler] = ln
        return ln

    def submit(self, handler: str, fn: Callable[[], Any],
               fallback: Callable[[str, str], Any]) -> HostAction:
        """Queue one host adapter call onto the handler's bulkhead.
        `fn` is the zero-arg adapter call (built by the dispatcher,
        instance already constructed); `fallback(policy, reason)`
        builds the fail-policy result for this action's variety
        (check_fallback / quota_fallback below). Returns immediately —
        the fold pairs every submit with exactly one resolve()."""
        from istio_tpu.runtime import monitor

        if self._closed:
            act = HostAction(handler, fallback, immediate="shed")
            # closed-executor submits still hit the ledger: lane() on
            # a closed executor would resurrect worker threads
            monitor.note_host_action_submitted(handler)
            return act
        monitor.note_host_action_submitted(handler)
        return self.lane(handler).submit(fn, fallback)

    def resolve(self, act: HostAction,
                deadline: float | None = None) -> Any:
        """Claim one action's result, bounded by the request's
        remaining deadline (absolute perf_counter instant) and the
        configured per-action timeout. THE single accounting home:
        every submitted action passes through here exactly once and
        lands on exactly one conservation outcome."""
        from istio_tpu.runtime import monitor

        lane = self._lanes.get(act.handler)
        policy = self.config.fail_policy
        if act.immediate is not None:
            outcome = act.immediate
            if lane is not None:
                lane.note_outcome(outcome)
            monitor.note_host_action(act.handler, outcome)
            return act.fallback(policy, outcome)
        timeout: float | None = None
        if deadline is not None:
            timeout = deadline - time.perf_counter()
            if timeout > 86_400.0:
                # a deadline days out IS no deadline (and absurd
                # values would overflow Event.wait's C time type)
                timeout = None
        if self.config.action_timeout_s > 0:
            timeout = self.config.action_timeout_s if timeout is None \
                else min(timeout, self.config.action_timeout_s)
        if timeout is not None and timeout <= 0:
            # the request's deadline is already gone: don't wait at
            # all — claim whatever finished, else expire the action
            timeout = 0.0
        t_wait0 = time.perf_counter()
        got = act._claim(timeout)
        # flight-recorder tape: the fold's claim wait, per handler
        # lane — the stage a wedged adapter's victims show up under
        # (runtime/forensics.py; no-op off-batch)
        from istio_tpu.runtime import forensics
        forensics.RECORDER.host_wait(act.handler,
                                     time.perf_counter() - t_wait0)
        if got is None:
            # still running at the bound: the batch folds with the
            # policy verdict; the worker's eventual completion counts
            # late_*. An overrun is a breaker failure — a wedged
            # backend whose calls never return must still trip open
            # (record_failure also returns any half-open probe slot).
            outcome = "overrun" if timeout is None or timeout > 0 \
                else "expired"
            if lane is not None:
                lane.breaker.record_failure()
                lane.note_outcome(outcome)
            monitor.note_host_action(act.handler, outcome)
            return act.fallback(policy, outcome)
        result, outcome, wall = got
        if lane is not None:
            lane.note_outcome(outcome)
        monitor.note_host_action(act.handler, outcome, wall)
        if outcome == "error":
            # safeDispatch parity (dispatcher.go:399): an adapter
            # exception degrades THIS action to INTERNAL — the same
            # result the inline path produces, so executor-path
            # verdicts stay oracle-identical
            return act.fallback("error", f"{type(result).__name__}: "
                                         f"{result}")
        return result

    def abandon(self, act: HostAction) -> None:
        """Account an action a fold is unwinding past (an exception
        between submit and claim) — unless the fold already claimed
        it. Zero wait, result discarded (the batch is failing with its
        own exception), and deliberately NO breaker involvement: the
        fold's failure is not the adapter's. Keeps the conservation
        ledger exact on error paths."""
        from istio_tpu.runtime import monitor

        with act._lock:
            if act._claimed:
                return
        if act.immediate is not None:
            outcome = act.immediate
        else:
            got = act._claim(0.0)
            outcome = got[1] if got is not None else "expired"
        lane = self._lanes.get(act.handler)
        if lane is not None:
            lane.note_outcome(outcome)
        monitor.note_host_action(act.handler, outcome)

    # -- maintenance lane ---------------------------------------------

    def register_refreshables(self,
                              handlers: Mapping[str, Any]) -> None:
        """(Re)build the maintenance registry from a published handler
        map: every handler carrying a callable `refresh` with a
        positive `refresh_interval_s` and a live provider gets a
        periodic slot on the maintenance lane. Called from the config
        publish hook — per-name stats survive republishes (handler
        identity may change; the NAME is the operator-facing key)."""
        fresh: dict[str, dict] = {}
        now = time.monotonic()
        with self._refresh_lock:
            for name, h in handlers.items():
                refresh = getattr(h, "refresh", None)
                interval = float(getattr(h, "refresh_interval_s", 0.0)
                                 or 0.0)
                if not callable(refresh) or interval <= 0:
                    continue
                if getattr(h, "_provider", None) is None:
                    continue   # nothing to re-pull
                prev = self._refresh.get(name)
                fresh[name] = self._refresh_entry(refresh, interval,
                                                  now, prev)
            # persistent refreshables (identity rotation) survive the
            # rebuild: carry their live entries across, due times and
            # stats intact
            for name, obj in self._persistent_refresh.items():
                prev = self._refresh.get(name)
                fresh[name] = prev if prev is not None else \
                    self._refresh_entry(
                        obj.refresh,
                        float(obj.refresh_interval_s), now, None)
            self._refresh = fresh
        if fresh and self._maint_thread is None and not self._closed:
            self._maint_thread = threading.Thread(
                target=self._maintenance_loop, daemon=True,
                name="adapter-maintenance")
            self._maint_thread.start()

    @staticmethod
    def _refresh_entry(fn, interval: float, now: float,
                       prev: "dict | None") -> dict:
        return {
            "fn": fn,
            "interval_s": interval,
            "next_due": now + interval,
            "total": prev["total"] if prev else 0,
            "failures": prev["failures"] if prev else 0,
            "last_success_wall":
                prev["last_success_wall"] if prev else None,
            "last_error": prev["last_error"] if prev else None,
            "in_flight": False,
        }

    def register_refreshable(self, name: str, obj: Any) -> None:
        """Register a PERSISTENT maintenance-lane refreshable — a
        `refresh()` + `refresh_interval_s` duck (the workload-identity
        rotation loop rides here). Unlike handler providers it is not
        evicted when a config republish rebuilds the registry."""
        refresh = getattr(obj, "refresh", None)
        interval = float(getattr(obj, "refresh_interval_s", 0.0) or 0.0)
        if not callable(refresh) or interval <= 0:
            raise ValueError(
                f"refreshable {name!r} needs a callable refresh and a "
                f"positive refresh_interval_s")
        with self._refresh_lock:
            self._persistent_refresh[name] = obj
            self._refresh[name] = self._refresh_entry(
                refresh, interval, time.monotonic(),
                self._refresh.get(name))
        if self._maint_thread is None and not self._closed:
            self._maint_thread = threading.Thread(
                target=self._maintenance_loop, daemon=True,
                name="adapter-maintenance")
            self._maint_thread.start()

    def _maintenance_loop(self) -> None:
        tick = max(self.config.maintenance_tick_s, 0.01)
        while not self._maint_stop.wait(tick):
            now = time.monotonic()
            due: list[tuple[str, dict]] = []
            with self._refresh_lock:
                for name, st in self._refresh.items():
                    if st["next_due"] <= now and not st["in_flight"]:
                        st["in_flight"] = True
                        due.append((name, st))
            for name, st in due:
                # the refresh runs ON the maintenance lane — a slow
                # provider occupies the maintenance worker, never a
                # request lane and never the scheduler thread
                act = self.lane(MAINTENANCE_LANE).submit(
                    lambda n=name, s=st: self._run_refresh(n, s),
                    lambda _p, _r: None)
                if act.immediate is not None:
                    # shed at the lane (full queue / closing): the
                    # refresh never ran — clear in_flight and retry
                    # next tick, or the provider would stall forever
                    with self._refresh_lock:
                        st["in_flight"] = False

    def _run_refresh(self, name: str, st: dict) -> None:
        from istio_tpu.runtime import monitor

        monitor.LIST_REFRESH_TOTAL.inc()
        err: str | None = None
        try:
            st["fn"]()
        except Exception as exc:
            err = f"{type(exc).__name__}: {exc}"
            monitor.LIST_REFRESH_FAILURES.inc()
            log.warning("provider refresh %s failed: %s", name, err)
        with self._refresh_lock:
            st["total"] += 1
            if err is None:
                st["last_success_wall"] = time.time()
                st["last_error"] = None
            else:
                st["failures"] += 1
                st["last_error"] = err
            st["next_due"] = time.monotonic() + st["interval_s"]
            st["in_flight"] = False
        from istio_tpu.runtime import forensics
        forensics.record_event("provider_refresh", coalesce_s=0.5,
                               provider=name, ok=err is None)

    def refresh_now(self, name: str) -> bool:
        """Synchronous one-shot refresh (tests, /debug triggers);
        False when the name is not registered."""
        with self._refresh_lock:
            st = self._refresh.get(name)
        if st is None:
            return False
        self._run_refresh(name, st)
        return True

    # -- introspection / lifecycle ------------------------------------

    def snapshot(self) -> dict:
        """/debug/executor payload: per-lane bulkhead + breaker state,
        the conservation counter families, policy knobs and the
        maintenance registry (incl. last-refresh-age per provider)."""
        from istio_tpu.runtime import monitor

        with self._lanes_lock:
            lanes = dict(self._lanes)
        now = time.time()
        with self._refresh_lock:
            maint = {}
            for name, st in self._refresh.items():
                last = st["last_success_wall"]
                maint[name] = {
                    "interval_s": st["interval_s"],
                    "refresh_total": st["total"],
                    "refresh_failures": st["failures"],
                    "last_refresh_age_s":
                        round(now - last, 3) if last else None,
                    "last_error": st["last_error"],
                }
        return {
            "policy": {
                "fail_policy": self.config.fail_policy,
                "workers_per_handler": self.config.workers,
                "queue_cap": self.config.queue_cap,
                "action_timeout_s": self.config.action_timeout_s,
                "breaker_failures": self.config.breaker_failures,
                "breaker_reset_s": self.config.breaker_reset_s,
                "retry": self.config.retry,
            },
            "lanes": {name: ln.stats()
                      for name, ln in sorted(lanes.items())},
            "counters": monitor.host_action_counters(),
            "maintenance": maint,
            "closed": self._closed,
        }

    def close(self, grace_s: float = 1.0) -> None:
        """Ordered teardown: scheduler first (no new maintenance
        work), then every lane. Idempotent; wedged workers are leaked
        as daemons, never waited on forever."""
        if self._closed:
            return
        self._closed = True
        self._maint_stop.set()
        t = self._maint_thread
        if t is not None and t.is_alive():
            t.join(timeout=grace_s)
        with self._lanes_lock:
            lanes = list(self._lanes.values())
        for ln in lanes:
            ln.close(grace_s)


# -- fail-policy result factories (the dispatcher's fallbacks) --------

def check_fallback(policy: str, reason: str):
    """CheckResult for an unresolvable CHECK action. `policy` is
    "open"/"closed" for deadline/bulkhead/breaker outcomes, or the
    literal "error" for adapter exceptions (safeDispatch INTERNAL —
    NOT a policy decision, so it is oracle-identical)."""
    from istio_tpu.adapters.sdk import CheckResult
    from istio_tpu.models.policy_engine import INTERNAL, OK
    from istio_tpu.runtime.resilience import UNAVAILABLE

    if policy == "error":
        # safeDispatch accounting parity: one dispatch error per
        # failed action, counted where the verdict is built
        from istio_tpu.runtime import monitor
        monitor.DISPATCH_ERRORS.inc()
        log.warning("adapter check failed: %s", reason)
        return CheckResult(status_code=INTERNAL,
                           status_message=f"adapter panic: {reason}")
    if policy == "open":
        # fail-open answers OK but with a 1s/1-use TTL: sidecars
        # re-check promptly instead of caching the blanket allow for a
        # normal success's 5s/10k uses (resilience.py's posture)
        return CheckResult(status_code=OK, valid_duration_s=1.0,
                           valid_use_count=1)
    return CheckResult(
        status_code=UNAVAILABLE,
        status_message=f"host adapter unavailable ({reason})")


def quota_fallback(policy: str, reason: str, amount: int):
    """QuotaResult for an unresolvable QUOTA action: fail-open grants
    the requested amount (quota outage must not starve the mesh),
    fail-closed grants nothing with UNAVAILABLE; adapter exceptions
    keep dispatcher.quota's INTERNAL shape."""
    from istio_tpu.adapters.sdk import QuotaResult
    from istio_tpu.models.policy_engine import INTERNAL
    from istio_tpu.runtime.resilience import UNAVAILABLE

    if policy == "error":
        from istio_tpu.runtime import monitor
        monitor.DISPATCH_ERRORS.inc()
        log.warning("adapter quota failed: %s", reason)
        return QuotaResult(granted_amount=0, status_code=INTERNAL,
                           status_message=reason)
    if policy == "open":
        return QuotaResult(granted_amount=amount, valid_duration_s=1.0)
    return QuotaResult(
        granted_amount=0, status_code=UNAVAILABLE,
        status_message=f"quota adapter unavailable ({reason})")
