"""Controller — store watch → snapshot rebuild → atomic publish.

Reference: mixer/pkg/runtime/controller.go — watchChanges (:192) with a
debounce, rebuild (attribute finder :273, handler table, rules :380),
publishSnapShot (:115) swapping the resolver atomically; the old
snapshot's orphaned handlers close after the swap (cleanupResolver
:543's drain role — Python's GIL + our immutable Dispatcher make the
swap itself safe; handler closing happens in HandlerTable.rebuild).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Mapping

from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.layout import InternTable
from istio_tpu.runtime import monitor
from istio_tpu.runtime.config import Snapshot, SnapshotBuilder
from istio_tpu.runtime.dispatcher import DEFAULT_IDENTITY_ATTR, Dispatcher
from istio_tpu.runtime.handler_table import HandlerTable
from istio_tpu.runtime.store import Event, Store

log = logging.getLogger("istio_tpu.runtime.controller")


def _serving_backoff() -> None:
    """Yield between prewarm shape compiles so the warm never starves
    serving: the jaxpr trace is pure python holding the GIL for
    seconds at a time on large rulesets, and on a loaded single core
    the stream threads would otherwise stall behind it. Always yields
    a scheduling quantum; backs off harder while the live p99 gauge is
    over the SLO target (the serving-latency backoff — prewarm is the
    lowest-priority work in the process by construction)."""
    import time

    time.sleep(0.005)
    try:
        monitor.refresh_latency_gauges()
        if monitor.CHECK_P99_MS.value() > monitor.CHECK_P99_TARGET_MS:
            time.sleep(0.1)
    except Exception:   # a gauge refresh must never break a rebuild
        pass


class Controller:
    def __init__(self, store: Store,
                 default_manifest: Mapping[str, ValueType] | None = None,
                 identity_attr: str = DEFAULT_IDENTITY_ATTR,
                 debounce_s: float = 0.05,
                 max_str_len: int | None = None,
                 on_publish: Callable[[Dispatcher], None] | None = None,
                 fused: bool = True,
                 prewarm_buckets: tuple[int, ...] = (),
                 mesh=None,
                 rule_telemetry: bool = True,
                 canary=None,
                 on_canary_reject: Callable[..., None] | None = None,
                 initial_prewarm: bool = True,
                 prewarm_hook: Callable[..., None] | None = None,
                 warm_parent_plans: bool = True,
                 executor=None,
                 grants=None,
                 overlap_h2d: bool = False):
        self.store = store
        # AdapterExecutor (runtime/executor.py): handed to every
        # published Dispatcher so host-overlay adapter work runs
        # bulkheaded + deadline-bounded; the executor outlives
        # snapshots (lane breakers persist across swaps)
        self.executor = executor
        # GrantPolicy (runtime/grants.py): handed to every published
        # Dispatcher so check responses carry volatility-derived
        # cache grants. When THIS controller's dispatcher is the
        # serving surface (warm_parent_plans True — monolithic mode),
        # revocation fires HERE, immediately before the atomic ref
        # swap: a request served by the new generation must never
        # carry a grant computed from the old generation's age. Under
        # sharding the serving swap is the router swap instead, and
        # RuntimeServer._rebuild_sharded revokes (delta-scoped) before
        # set_routers.
        self.grants = grants
        # overlapped h2d from the wire decoder's pinned staging
        # (Dispatcher._stage_h2d) — resolved by the owner per backend
        self.overlap_h2d = overlap_h2d
        self.identity_attr = identity_attr
        self.debounce_s = debounce_s
        self.on_publish = on_publish
        self.fused_enabled = fused
        self.rule_telemetry = rule_telemetry
        # config canary (istio_tpu/canary.ConfigCanary): shadow-replay
        # recorded live traffic through every rebuilt snapshot before
        # the atomic swap; in gate mode a divergent candidate VETOES
        # the publish (the old dispatcher keeps serving) and the typed
        # CanaryRejected surfaces via last_canary_rejection /
        # on_canary_reject / the introspect /debug/canary view
        self.canary = canary
        self.on_canary_reject = on_canary_reject
        self.last_canary_rejection = None
        self.mesh = mesh    # jax.sharding.Mesh for multi-chip serving
        self.prewarm_buckets = tuple(prewarm_buckets)
        # False skips the BACKGROUND first-build prewarm (callers that
        # warm explicitly, e.g. bench rigs — the duplicate compiles
        # contend for the core and a thread still compiling at process
        # exit aborts the interpreter); config-SWAP prewarms are
        # synchronous and unaffected
        self.initial_prewarm = initial_prewarm
        # False when a sharded serving plane owns the check path
        # (istio_tpu/sharding): the parent monolithic plan is then a
        # metadata/oracle surface only — warming its bucket × tier
        # device programs would compile XLA programs serving never
        # runs (at 100k+ rules, the compile the sharding plane exists
        # to avoid). The RuntimeServer warms the shard BANKS instead,
        # inside its own publish hook.
        self.warm_parent_plans = warm_parent_plans
        # called with the candidate plan next to plan.prewarm (config
        # SWAPS only, pre-swap, rebuild thread): the owner warms extra
        # per-plan programs (e.g. the in-step quota step) while the
        # old dispatcher keeps serving
        self.prewarm_hook = prewarm_hook
        self._prewarm_stop = False
        self._closing = False
        self._prewarm_thread: threading.Thread | None = None
        # post-swap background warm (the shapes live traffic was NOT
        # serving pre-swap): stoppable per swap — a superseding swap
        # or close() flips the event and the thread exits between
        # shapes; batches racing onto a not-yet-warm shape serve
        # through the host oracle (Dispatcher._check_fused bridge)
        self._swap_warm_thread: threading.Thread | None = None
        self._swap_warm_stop: threading.Event | None = None
        self._builder = SnapshotBuilder(default_manifest,
                                        InternTable(), max_str_len,
                                        lower_rbac=fused)
        self._handler_table = HandlerTable()
        # device-backed served quota (runtime/device_quota.py); pools
        # keep counters across snapshot swaps via signature reuse
        self._quota_table = None
        if fused:
            from istio_tpu.runtime.device_quota import DeviceQuotaTable
            self._quota_table = DeviceQuotaTable()
        self.device_quotas: dict = {}
        self._lock = threading.Lock()
        self._rebuild_serial = threading.Lock()   # one rebuild at a time
        self._timer: threading.Timer | None = None
        self._dispatcher: Dispatcher | None = None
        # wall seconds of the last COMPLETED publish, store read →
        # snapshot compile → swap → on_publish hooks (the sharded bank
        # rebuild included) — the republish-latency number the delta-
        # compilation bench and smoke read
        self.last_publish_wall_s = 0.0
        self.rebuild()                      # initial snapshot
        store.watch(self._on_events)

    @property
    def dispatcher(self) -> Dispatcher:
        d = self._dispatcher
        assert d is not None
        return d

    def _on_events(self, events: list[Event]) -> None:
        """Debounced rebuild trigger (controller.go watchChanges)."""
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(self.debounce_s, self.rebuild)
            self._timer.daemon = True
            self._timer.start()

    # grace period before closing handlers orphaned by a config swap —
    # lets requests in flight on the OLD dispatcher finish (the
    # reference refcounts the resolver, resolver.go:240-247; a timed
    # drain keeps the hot path free of per-request accounting)
    ORPHAN_DRAIN_S = 2.0

    def rebuild(self) -> Dispatcher:
        # a debounce Timer that fires into teardown must not start a
        # rebuild: compiling a candidate plan while the interpreter /
        # device stack is being torn down is the XLA abort the
        # shutdown-reap regression test guards against
        if self._closing:
            return self._dispatcher
        with self._rebuild_serial:
            if self._closing:
                return self._dispatcher
            return self._rebuild_locked()

    def _rebuild_locked(self) -> Dispatcher:
        t_pub0 = time.perf_counter()
        snapshot = self._builder.build(self.store)
        for err in snapshot.errors:
            log.warning("config: %s", err)
        plan = None
        swap_rest: list = []
        if self.fused_enabled:
            from istio_tpu.runtime.fused import build_fused_plan
            plan = build_fused_plan(snapshot, mesh=self.mesh,
                                    rule_telemetry=self.rule_telemetry)
            if plan is not None and self.prewarm_buckets \
                    and self.warm_parent_plans:
                if self._dispatcher is not None:
                    # shadow-compile BEFORE the swap (SURVEY hard-part
                    # #5: a config change must never surface trace
                    # time in-band) — but only the shapes live traffic
                    # is actually SERVING (the old plan's observed
                    # (bucket, byte-tier) set), so swap latency scales
                    # with the served working set, not the full
                    # bucket × tier product. The remainder compiles
                    # post-swap in a background thread (below); a
                    # batch racing onto a not-yet-warm shape serves
                    # through the host oracle instead of tracing
                    # in-band. Between shapes the warm YIELDS to
                    # serving (_serving_backoff) — on a loaded single
                    # core the pure-python jaxpr trace would otherwise
                    # starve the stream threads of the GIL.
                    old_plan = self._dispatcher.fused
                    pairs = plan.all_warm_shapes(self.prewarm_buckets)
                    first = plan.map_served_shapes(
                        self.prewarm_buckets,
                        old_plan.served_shapes()
                        if old_plan is not None else set())
                    swap_rest = [p for p in pairs
                                 if p not in set(first)]
                    plan.begin_warm()
                    plan.warm_shapes(
                        first,
                        should_stop=lambda: self._prewarm_stop,
                        backoff=_serving_backoff)
                    if self.prewarm_hook is not None:
                        # extra shapes the OWNER serves through this
                        # plan (RuntimeServer: the merged check+quota
                        # in-step program) — warmed here, BEFORE the
                        # swap, for the same reason; a post-publish
                        # warm would leave a window where the first
                        # quota batch traces in-band
                        try:
                            self.prewarm_hook(plan)
                        except Exception:
                            log.exception("prewarm hook failed")
                elif self.initial_prewarm:
                    # first build: serve immediately, warm in the
                    # background — blocking startup for minutes of
                    # per-bucket device compiles helps nobody, but
                    # without ANY warm the first requests serialize
                    # behind those same compiles. The thread polls the
                    # controller's stop flag between shapes so close()
                    # never leaves it compiling into teardown.
                    self._prewarm_thread = threading.Thread(
                        target=self._guarded_prewarm, args=(plan,),
                        daemon=True, name="prewarm-initial")
                    self._prewarm_thread.start()
        # config canary: replay recorded live traffic through the
        # candidate BEFORE any publish side effect (the handler table
        # and quota pools below mutate shared state toward the new
        # snapshot; a vetoed candidate must leave them untouched so
        # the old dispatcher keeps serving unchanged). The gate never
        # raises — internal canary failures fail open.
        if self.canary is not None and self._dispatcher is not None:
            rejection = self.canary.gate(self._dispatcher, snapshot,
                                         plan, self.prewarm_buckets)
            if rejection is not None:
                self.last_canary_rejection = rejection
                log.error("config publish VETOED (generation %d kept "
                          "serving): %s", self._dispatcher.snapshot
                          .revision, rejection)
                from istio_tpu.runtime import forensics
                forensics.record_event(
                    "canary_veto",
                    serving_generation=self._dispatcher.snapshot
                    .revision,
                    reason=str(rejection)[:200])
                if self.on_canary_reject is not None:
                    try:
                        self.on_canary_reject(rejection)
                    except Exception:
                        log.exception("on_canary_reject hook failed")
                return self._dispatcher
        handlers, orphans = self._handler_table.rebuild(snapshot)
        quota_orphans: list = []
        if self._quota_table is not None:
            self.device_quotas, quota_orphans = \
                self._quota_table.rebuild(snapshot)
        dispatcher = Dispatcher(snapshot, handlers, self.identity_attr,
                                fused=plan,
                                buckets=self.prewarm_buckets,
                                recorder=self.canary.recorder
                                if self.canary is not None else None,
                                executor=self.executor,
                                grants=self.grants,
                                overlap_h2d=self.overlap_h2d)
        if self.grants is not None and self.warm_parent_plans:
            # monolithic serving surface: revoke BEFORE the swap (a
            # global floor is always safe; the sharded plane refines
            # to the delta's namespaces before ITS router swap)
            self.grants.on_publish(None)
        self._dispatcher = dispatcher      # atomic publish (GIL ref swap)
        # a successful publish supersedes any earlier veto: introspect
        # must not report a stale rejection against the live config
        self.last_canary_rejection = None
        if plan is not None and plan._warm_pending:
            # the pre-swap phase warmed only the live-served shapes;
            # finish the rest in the background (oracle-bridged until
            # each shape lands), or end the warm outright when the
            # served set already covered everything
            if swap_rest:
                self._start_swap_warm(plan, swap_rest)
            else:
                plan.end_warm()
        if self.canary is not None:
            # post-swap hook: re-baselines the recorder when the
            # published candidate was divergent (gate.on_published)
            self.canary.on_published(dispatcher)
        if quota_orphans:
            # same delayed drain as handler orphans: in-flight quota
            # loops may still hold the old pool (alloc() on a closed
            # pool fails fast, but draining avoids spurious UNAVAILABLE)
            t = threading.Timer(
                self.ORPHAN_DRAIN_S,
                lambda: [p.close() for p in quota_orphans])
            t.daemon = True
            t.start()
        if orphans:
            t = threading.Timer(
                self.ORPHAN_DRAIN_S,
                self._handler_table.close_handlers, args=(orphans,))
            t.daemon = True
            t.start()
        monitor.CONFIG_GENERATION.set(snapshot.revision)
        # mesh event timeline: the publish IS the event a p99 spike at
        # swap time gets attributed to (runtime/forensics.py)
        from istio_tpu.runtime import forensics
        forensics.record_event("config_publish",
                               generation=snapshot.revision,
                               rules=len(snapshot.rules),
                               errors=len(snapshot.errors))
        log.info("published config generation %d (%d rules, %d handlers,"
                 " %d instances, %d errors)", snapshot.revision,
                 len(snapshot.rules), len(handlers),
                 len(snapshot.instances), len(snapshot.errors))
        if self.on_publish is not None:
            self.on_publish(dispatcher)
        self.last_publish_wall_s = time.perf_counter() - t_pub0
        return dispatcher

    def _guarded_prewarm(self, plan) -> None:
        try:
            plan.prewarm(self.prewarm_buckets,
                         should_stop=lambda: self._prewarm_stop)
        except Exception:
            log.exception("initial prewarm failed")

    def _start_swap_warm(self, plan, pairs: list) -> None:
        """Post-swap background warm of the (bucket, tier) shapes the
        pre-swap phase skipped. Serialized behind any previous swap's
        still-running warm (one compile stream — concurrent traces
        would contend for the core the serving threads need), stopped
        by a superseding swap or close(), and always end_warm()ed so
        the oracle bridge disengages."""
        prev_stop = self._swap_warm_stop
        if prev_stop is not None:
            prev_stop.set()   # superseded candidate: stop its warm
        prev_thread = self._swap_warm_thread
        stop = threading.Event()
        self._swap_warm_stop = stop

        def run() -> None:
            try:
                if prev_thread is not None and prev_thread.is_alive():
                    prev_thread.join()
                plan.warm_shapes(
                    pairs,
                    should_stop=lambda: (stop.is_set()
                                         or self._prewarm_stop),
                    backoff=_serving_backoff)
            except Exception:
                log.exception("post-swap background warm failed")
            finally:
                plan.end_warm()

        t = threading.Thread(target=run, daemon=True,
                             name="prewarm-swap")
        self._swap_warm_thread = t
        t.start()

    def begin_close(self) -> None:
        """Flag-only first phase of close(): stop admitting rebuilds,
        cancel the debounce timer and flip every warm thread's stop
        flag — NO joins. RuntimeServer.shutdown calls this FIRST so
        in-flight warms start winding down while the fronts drain,
        instead of discovering the stop flag only after the device
        stack is half torn down."""
        self._closing = True
        self._prewarm_stop = True
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
        ev = self._swap_warm_stop
        if ev is not None:
            ev.set()

    def close(self) -> None:
        self.begin_close()
        # reap any IN-FLIGHT rebuild (a debounce Timer that fired just
        # before begin_close may still be compiling a candidate on its
        # own thread): rebuild() holds _rebuild_serial for the whole
        # publish, so acquiring it here is the join. New rebuilds are
        # already refused by the _closing guard.
        with self._rebuild_serial:
            pass
        # stop + reap the initial prewarm: a daemon thread still inside
        # an XLA compile at interpreter exit aborts the process
        # ("terminate called without an active exception"). The join is
        # UNTIMED on purpose: the flag is polled between shapes, so the
        # thread exits after at most the in-flight compile — a timed
        # join that expires mid-compile re-opens the teardown abort.
        t = self._prewarm_thread
        if t is not None and t.is_alive():
            t.join()
        # same discipline for the post-swap background warm: flag is
        # polled between shapes, join is untimed (expiring mid-compile
        # re-opens the teardown abort)
        ev = self._swap_warm_stop
        if ev is not None:
            ev.set()
        t = self._swap_warm_thread
        if t is not None and t.is_alive():
            t.join()
        self._handler_table.close()
        if self._quota_table is not None:
            self._quota_table.close()
