"""Device lowering for REPORT instance construction.

The reference builds report instances through generated ProcessReport
bodies: per record, per field, one IL interpreter run
(mixer/template/template.gen.go ProcessReport dispatched from
mixer/pkg/runtime/dispatcher/dispatcher.go:194). Once rule resolve is
fused, that per-record, per-field host evaluation IS the report path's
serving cost. Here every lowerable field expression compiles into the
SAME batched masked tensor algebra as Check predicates
(compiler/tensor_expr.compile_field) and rides the report path's single
packed device trip (FusedPlan.packed_report): the device evaluates all
fields for all records at once, the host decodes intern ids back to
Python values with one unique-id pass per batch, and adapters receive
finished instances — only adapter I/O stays host-side.

Fallback contract: an instance with ANY unlowerable field keeps
InstanceBuilder.build (host oracle) — mixed configs serve with fused
and host instances side by side. A device-invalid field (the rows where
the oracle would raise EvalError) aborts that row's instance exactly
like the host error path (errorpath.go semantics in the dispatcher).
"""
from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.tensor_expr import HostFallback, compile_field
from istio_tpu.templates import Variety
from istio_tpu.utils.log import scope

log = scope("runtime.report_lower")


@dataclasses.dataclass(frozen=True)
class FieldSlot:
    """One compiled field expression: where its value/valid rows live
    in the stacked planes and how to decode the raw int32."""
    path: tuple           # ("value",) / ("dimensions", "k") / nested
    row: int              # row index in the [F, B] field planes
    is_bool: bool         # True → raw 0/1, not an intern id


@dataclasses.dataclass(frozen=True)
class InstanceSpec:
    """Recipe to materialize one instance from decoded field planes."""
    name: str
    fields: tuple[FieldSlot, ...]
    consts: tuple[tuple[tuple, Any], ...]     # (path, constant value)
    # (path,) of every map/submessage container, in creation order —
    # created empty first so zero-entry maps still appear ({} like the
    # host build) and nested const/field paths have a parent
    containers: tuple[tuple, ...]


@dataclasses.dataclass
class ReportLowering:
    """Per-snapshot compiled report-field programs + specs."""
    specs: dict[str, InstanceSpec]        # instance qname → recipe
    host_instances: frozenset             # qnames kept on the host build
    field_fns: list                       # NodeFn per plane row

    @property
    def n_fields(self) -> int:
        return len(self.field_fns)

    @property
    def n_valid_words(self) -> int:
        return (len(self.field_fns) + 31) // 32

    def field_planes(self, batch):
        """JAX: ([F, B] int32 values, [F, B] bool valid). Composed into
        FusedPlan's report packer — never pulled standalone on the
        serving path (each extra pull is a full RTT)."""
        import jax.numpy as jnp

        vals, valid = [], []
        for fn in self.field_fns:
            t = fn(batch)
            vals.append(t.val.astype(jnp.int32))
            valid.append(t.ok & ~t.err)
        return jnp.stack(vals), jnp.stack(valid)

    def decode_planes(self, raw: np.ndarray, valid: np.ndarray,
                      batch, interner) -> np.ndarray:
        """Pulled id planes → object array of Python values, via ONE
        unique-id decode per chunk (per-record dict lookups replace
        per-record expression evaluation). Invalid cells decode from a
        masked 0 id (never read — materialize() aborts first)."""
        if raw.size == 0:
            return np.empty(raw.shape, object)
        safe = np.where(valid, raw, 0)
        uniq, inv = np.unique(safe, return_inverse=True)
        table = np.empty(len(uniq), object)
        for j, u in enumerate(uniq):
            table[j] = batch.value_of(int(u), interner)
        return table[inv].reshape(raw.shape)

    def materialize(self, iname: str, b: int, decoded: np.ndarray,
                    raw: np.ndarray, valid: np.ndarray) -> dict | None:
        """Instance dict for record `b`, or None when any field row is
        device-invalid (the host path's EvalError abort)."""
        spec = self.specs[iname]
        out: dict[str, Any] = {"name": iname}
        for path in spec.containers:
            _set_path(out, path, {})
        for path, v in spec.consts:
            _set_path(out, path, v)
        for fs in spec.fields:
            if not valid[fs.row, b]:
                return None
            v = bool(raw[fs.row, b]) if fs.is_bool else decoded[fs.row, b]
            _set_path(out, fs.path, v)
        return out


class ReportFieldCtx:
    """Decoded field planes for ONE dispatcher.report() call.

    The report path chunks oversize batches through the prewarmed
    serving buckets (dispatcher._report_active_fused); each chunk adds
    its real-row slice here, and `seal()` concatenates along the record
    axis so `materialize(iname, b)` addresses records by their global
    position in the call's bag list."""

    def __init__(self, lowering: ReportLowering, interner) -> None:
        self.rl = lowering
        self.interner = interner
        self._raw: list[np.ndarray] = []
        self._valid: list[np.ndarray] = []
        self._dec: list[np.ndarray] = []
        self.raw = self.valid = self.dec = None

    def add_chunk(self, packed: np.ndarray, base: int, n_real: int,
                  batch, decode: bool = True) -> None:
        """Slice this chunk's field rows out of the packed pull
        (rows base..base+F are int32 values, then ceil(F/32) bitpacked
        valid words) and decode ids once. `decode=False` skips the
        unique-id decode for chunks the caller already knows carry no
        active report rule (their cells are never materialized)."""
        from istio_tpu.runtime.fused import unpack_word_rows

        f, fw = self.rl.n_fields, self.rl.n_valid_words
        raw = packed[base:base + f, :n_real]
        if fw:
            valid = unpack_word_rows(
                packed[base + f:base + f + fw, :n_real], f).T
        else:
            valid = np.zeros((0, n_real), bool)
        self._raw.append(raw)
        self._valid.append(valid)
        self._dec.append(
            self.rl.decode_planes(raw, valid, batch, self.interner)
            if decode else np.full(raw.shape, None, object))

    def seal(self) -> None:
        self.raw = np.concatenate(self._raw, axis=1) if self._raw \
            else np.zeros((self.rl.n_fields, 0), np.int32)
        self.valid = np.concatenate(self._valid, axis=1) if self._valid \
            else np.zeros((self.rl.n_fields, 0), bool)
        self.dec = np.concatenate(self._dec, axis=1) if self._dec \
            else np.empty((self.rl.n_fields, 0), object)

    def materialize(self, iname: str, b: int) -> dict | None:
        return self.rl.materialize(iname, b, self.dec, self.raw,
                                   self.valid)


def _set_path(d: dict, path: tuple, value: Any) -> None:
    for p in path[:-1]:
        d = d[p]
    d[path[-1]] = value


def _lower_instance(ib, finder, layout, interner, next_row: int
                    ) -> tuple[InstanceSpec, list]:
    """Compile every field of one instance (all-or-nothing: raises
    HostFallback if any field cannot lower)."""
    fields: list[FieldSlot] = []
    consts: list[tuple[tuple, Any]] = []
    containers: list[tuple] = []
    fns: list = []

    def walk(plan: list[tuple], prefix: tuple) -> None:
        for fname, kind, payload in plan:
            path = prefix + (fname,)
            if kind == "const":
                consts.append((path, payload))
            elif kind == "sub":
                containers.append(path)
                walk(payload, path)
            elif kind == "map":
                containers.append(path)
                for k in sorted(payload):
                    node, rtype = compile_field(payload[k].ast, finder,
                                                layout, interner)
                    fields.append(FieldSlot(
                        path=path + (k,), row=next_row + len(fns),
                        is_bool=rtype is ValueType.BOOL))
                    fns.append(node)
            else:
                node, rtype = compile_field(payload.ast, finder,
                                            layout, interner)
                fields.append(FieldSlot(
                    path=path, row=next_row + len(fns),
                    is_bool=rtype is ValueType.BOOL))
                fns.append(node)

    walk(ib.compiled_plan(), ())
    return InstanceSpec(name=ib.name, fields=tuple(fields),
                        consts=tuple(consts),
                        containers=tuple(containers)), fns


def build_report_lowering(snapshot) -> ReportLowering | None:
    """Compile every REPORT instance referenced by a rule action.

    Returns None when nothing lowered (the dispatcher keeps the pure
    host build). Per-instance failures (HostFallback, or a layout slot
    the requirements pre-pass could not provide) demote just that
    instance to `host_instances`."""
    rs = snapshot.ruleset
    layout, interner, finder = rs.layout, rs.interner, snapshot.finder
    specs: dict[str, InstanceSpec] = {}
    host: set[str] = set()
    field_fns: list = []
    for ridx in range(len(snapshot.rules)):
        for hc, template, inst_names in snapshot.actions_for(
                ridx, Variety.REPORT):
            for iname in inst_names:
                if iname in specs or iname in host:
                    continue
                ib = snapshot.instances[iname]
                try:
                    spec, fns = _lower_instance(
                        ib, finder, layout, interner, len(field_fns))
                except (HostFallback, KeyError) as exc:
                    host.add(iname)
                    log.info("report instance %s keeps the host build: "
                             "%s", iname, exc)
                    continue
                specs[iname] = spec
                field_fns.extend(fns)
    if not specs:
        return None
    log.info("report lowering: %d instances / %d field programs on "
             "device, %d instances host-built", len(specs),
             len(field_fns), len(host))
    return ReportLowering(specs=specs, host_instances=frozenset(host),
                          field_fns=field_fns)
