"""Generic config store: typed KV + watch.

Reference: mixer/pkg/config/store (store.go:115 Backend, :129 Store,
fsstore.go, queue.go). Keys are (kind, namespace, name); values are
plain dict specs. Backends: in-memory (test backbone + programmatic
config) and a filesystem backend reading k8s-style YAML documents
(kind/metadata/spec), reloadable like the reference's fsstore polling.
Watchers receive coalesced change events on a dedicated delivery thread
(queue.go's eventQueue role) — never on the mutator's thread.
"""
from __future__ import annotations

import dataclasses
import glob
import os
import queue
import threading
import weakref
from typing import Any, Callable, Iterable, Mapping

import yaml


class StoreError(ValueError):
    pass


Key = tuple[str, str, str]   # (kind, namespace, name)


@dataclasses.dataclass(frozen=True)
class Event:
    """Update or Delete (value None = delete)."""
    key: Key
    value: Mapping[str, Any] | None


Validator = Callable[[Key, Mapping[str, Any] | None], None]
Watcher = Callable[[list[Event]], None]


class Store:
    """Thread-safe KV with watch; backends load into it."""

    def __init__(self, validator: Validator | None = None):
        self._data: dict[Key, Mapping[str, Any]] = {}
        self._lock = threading.Lock()
        self._watchers: list[Watcher] = []
        self._validator = validator
        self._queue: "queue.Queue[list[Event] | None]" = queue.Queue()
        # The delivery thread must NOT hold a strong reference to the
        # store (a bound-method target would): a store dropped without
        # close() would then pin its thread — and itself — forever,
        # and a long process accumulates one parked thread per dead
        # store. The thread sees the store only through a weakref; the
        # finalizer wakes it with the same None sentinel close() uses,
        # so GC of an unclosed store reaps its thread.
        self._delivery = threading.Thread(
            target=_deliver_loop, args=(self._queue, weakref.ref(self)),
            daemon=True, name="store-delivery")
        self._delivery.start()
        self._finalizer = weakref.finalize(self, self._queue.put, None)

    # -- read --
    def get(self, key: Key) -> Mapping[str, Any] | None:
        with self._lock:
            return self._data.get(key)

    def list(self, kind: str | None = None) -> dict[Key, Mapping[str, Any]]:
        with self._lock:
            return {k: v for k, v in self._data.items()
                    if kind is None or k[0] == kind}

    # -- write --
    def set(self, key: Key, value: Mapping[str, Any]) -> None:
        self.apply_events([Event(key, dict(value))])

    def delete(self, key: Key) -> None:
        self.apply_events([Event(key, None)])

    def apply_events(self, events: list[Event],
                     notify: bool = True) -> None:
        """Apply mutations; `notify=False` skips watcher delivery —
        the deterministic-republish hook benches and smokes use to
        pair one store edit with ONE explicit controller.rebuild()
        instead of racing the debounce timer's background rebuild."""
        if self._validator is not None:
            for ev in events:
                self._validator(ev.key, ev.value)
        with self._lock:
            for ev in events:
                if ev.value is None:
                    self._data.pop(ev.key, None)
                else:
                    self._data[ev.key] = dict(ev.value)
        if notify:
            self._queue.put(list(events))

    # -- watch --
    def watch(self, watcher: Watcher) -> None:
        self._watchers.append(watcher)

    def close(self) -> None:
        # finalize() is idempotent: first call enqueues the None
        # sentinel and detaches the GC finalizer
        self._finalizer()
        self._delivery.join(timeout=5)


def _deliver_loop(q: "queue.Queue[list[Event] | None]",
                  store_ref: "weakref.ref[Store]") -> None:
    """Watcher delivery loop — module-level so the thread only holds
    the queue and a weakref (see Store.__init__)."""
    while True:
        events = q.get()
        if events is None:
            return
        store = store_ref()
        if store is None:
            return
        for w in list(store._watchers):
            try:
                w(events)
            except Exception:   # watcher isolation (queue.go behavior)
                import logging
                logging.getLogger("istio_tpu.store").exception(
                    "config watcher failed")
        del store   # no strong ref while parked on q.get()


class MemStore(Store):
    """Programmatic backend (reference config/store memstore test
    backend); also the target the fs backend loads into."""


class FsStore(Store):
    """Filesystem backend: a directory of YAML files, each holding one
    or more k8s-style documents:

        kind: rule
        metadata: {name: r1, namespace: default}
        spec: {match: ..., actions: [...]}

    `reload()` re-reads the tree and emits the diff as events
    (reference fsstore.go periodic-poll semantics; callers or the
    server's timer drive the cadence)."""

    def __init__(self, root: str, validator: Validator | None = None):
        super().__init__(validator)
        self.root = root
        self.reload()

    def _read_tree(self) -> dict[Key, Mapping[str, Any]]:
        out: dict[Key, Mapping[str, Any]] = {}
        for path in sorted(glob.glob(os.path.join(self.root, "**", "*.y*ml"),
                                     recursive=True)):
            with open(path, encoding="utf-8") as f:
                for doc in yaml.safe_load_all(f):
                    if not doc or not isinstance(doc, Mapping):
                        continue
                    kind = doc.get("kind")
                    meta = doc.get("metadata") or {}
                    name = meta.get("name")
                    if not kind or not name:
                        raise StoreError(
                            f"{path}: document needs kind + metadata.name")
                    ns = meta.get("namespace", "")
                    out[(str(kind), str(ns), str(name))] = \
                        dict(doc.get("spec") or {})
        return out

    def reload(self) -> int:
        """Diff disk vs memory; emit changes. Returns #events."""
        disk = self._read_tree()
        current = self.list()
        events = [Event(k, v) for k, v in disk.items()
                  if current.get(k) != v]
        events += [Event(k, None) for k in current if k not in disk]
        if events:
            self.apply_events(events)
        return len(events)
