"""Config kinds + validated Snapshot building.

Maps the reference's runtime2 config model (mixer/pkg/runtime2/config/
ephemeral.go → snapshot.go) with the same kinds the reference's store
carries: `attributemanifest`, `handler`, `instance`, `rule`, plus the
rbac adapter's `servicerole`/`servicerolebinding` (mixer/adapter/rbac
watches those kinds itself in the reference; here the snapshot feeds
them to the handler).

A Snapshot is immutable: attribute finder, handler configs (built
handlers live in the controller's HandlerTable so they survive snapshot
swaps when unchanged — handlerTable.go diffing), instance builders, and
rules with their ACTION wiring. The rule match predicates are compiled
to the device RuleSetProgram here; action wiring that matches the fused
fast path (denier/list/quota over id-exact entries) is extracted for
PolicyEngine construction by the controller.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping, Sequence

from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.ruleset import Rule as RulePred
from istio_tpu.compiler.ruleset import RuleSetProgram, compile_ruleset
from istio_tpu.compiler.layout import InternTable, Tensorizer
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.templates import (InstanceBuilder, TemplateError, Variety,
                                 registry as template_registry)
from istio_tpu.runtime.store import Key, Store, StoreError

KIND_MANIFEST = "attributemanifest"
KIND_HANDLER = "handler"
KIND_INSTANCE = "instance"
KIND_RULE = "rule"
KIND_SERVICE_ROLE = "servicerole"
KIND_SERVICE_ROLE_BINDING = "servicerolebinding"


@dataclasses.dataclass(frozen=True)
class HandlerConfig:
    name: str
    namespace: str
    adapter: str
    params: Mapping[str, Any]

    @property
    def signature(self) -> str:
        """Identity for handler reuse across snapshots
        (handlerTable.go signature diffing)."""
        return json.dumps([self.adapter, self.params], sort_keys=True,
                          default=str)


@dataclasses.dataclass(frozen=True)
class Action:
    """One rule action: a handler plus instances (config.proto Action)."""
    handler: str                 # fully-qualified handler name
    instances: tuple[str, ...]   # instance names


@dataclasses.dataclass(frozen=True)
class RuleConfig:
    name: str
    namespace: str
    match: str
    actions: tuple[Action, ...]


@dataclasses.dataclass
class RbacGroup:
    """Device-lowered rbac policy for one (handler, authorization
    instance) pair: pseudo-rule rows appended to the ruleset
    (compiler/rbac_lower.py). `lowered` is False when any row fell back
    to the host oracle — the action then stays on the host adapter."""
    handler: str
    instance: str
    allow_rows: tuple[int, ...]        # OR of these rows = allowed
    guard_row: int = -1                # -1: instance can never error
    n_triples: int = 0
    lowered: bool = True
    reason: str = ""


@dataclasses.dataclass
class Snapshot:
    """Validated, compiled config generation (runtime2 Snapshot)."""
    revision: int
    finder: AttributeDescriptorFinder
    handlers: dict[str, HandlerConfig]
    instances: dict[str, InstanceBuilder]
    instance_templates: dict[str, str]
    rules: list[RuleConfig]
    ruleset: RuleSetProgram            # one predicate row per rule,
    #                                    then rbac pseudo-rule rows
    tensorizer: Tensorizer
    roles: list[Mapping[str, Any]]
    bindings: list[Mapping[str, Any]]
    errors: list[str]                  # per-resource soft errors
    # ruleset rows [n_config_rules:] are synthesized pseudo-rules (no
    # config rule / actions behind them — only the fused engine and the
    # RbacGroups below may reference them)
    n_config_rules: int = 0
    rbac_groups: dict[tuple[str, str], RbacGroup] = \
        dataclasses.field(default_factory=dict)
    # the exact compile_ruleset kwargs this snapshot's ruleset was
    # built with (extra derived/byte/extern sources, max_str_len,
    # rule_pad) — the sharding plane recompiles rule SUBSETS
    # (istio_tpu/sharding/banks.py) and must reproduce the layout
    # inputs, or a bank would miss a column its instances read
    compile_kwargs: dict = dataclasses.field(default_factory=dict)
    # per-instance content digest (template + raw store params,
    # compiler/cache.stable_digest): bank_content_key folds in the
    # digests of every instance a bank's rules reference, so an
    # instance edit invalidates exactly the banks that serve it
    instance_digests: dict = dataclasses.field(default_factory=dict)
    # the builder's cross-build DecompCache (compiler/cache.py) rides
    # along so shard sub-compiles hit the decomposition memo the
    # parent build just filled; NEVER part of the snapshot's content
    # identity (excluded from every digest)
    decomp_cache: Any = dataclasses.field(default=None, repr=False)

    def rule_index(self, name: str, namespace: str) -> int:
        for i, r in enumerate(self.rules):
            if r.name == name and r.namespace == namespace:
                return i
        raise KeyError((namespace, name))

    def qualified_rule_names(self) -> list[str]:
        """Positional rule index → "ns/name" (bare name for the
        default namespace) — THE rule naming convention every
        index-keyed surface renders through (rulestats aggregation,
        canary diff attribution, waiver matching). Memoized: the
        snapshot is immutable."""
        names = getattr(self, "_qnames", None)
        if names is None:
            names = [f"{r.namespace}/{r.name}" if r.namespace
                     else r.name for r in self.rules]
            self._qnames = names
        return names

    def actions_for(self, rule_idx: int,
                    variety: Variety) -> list[tuple[HandlerConfig, str, list[str]]]:
        """[(handler cfg, template, instance names)] of one variety —
        one tuple PER TEMPLATE so a mixed action (e.g. stdio handling
        both logentry and metric instances) dispatches each instance
        under its own template."""
        out = []
        for action in self.rules[rule_idx].actions:
            h = self.handlers.get(action.handler)
            if h is None:
                continue
            by_template: dict[str, list[str]] = {}
            for n in action.instances:
                if n not in self.instances:
                    continue
                tmpl = self.instance_templates[n]
                if template_registry.get(tmpl).variety == variety:
                    by_template.setdefault(tmpl, []).append(n)
            for tmpl, insts in by_template.items():
                out.append((h, tmpl, insts))
        return out


def _qualify(name: str, ns: str) -> str:
    """namespace-qualified resource name (reference uses
    name.kind.namespace; kind is implicit in our typed dicts)."""
    return f"{name}.{ns}" if ns else name


class SnapshotBuilder:
    """Ephemeral → Snapshot validation (runtime2/config/ephemeral.go):
    reads the whole store, type-checks everything, collects soft errors
    per resource (a bad rule/instance is dropped, not fatal — matching
    the reference controller's tolerance), and compiles the ruleset."""

    # the reference's configDefaultNamespace: rules here apply mesh-wide
    DEFAULT_CONFIG_NAMESPACE = "istio-system"

    def __init__(self, default_manifest: Mapping[str, ValueType]
                 | None = None,
                 interner: InternTable | None = None,
                 max_str_len: int | None = None,
                 config_namespace: str = DEFAULT_CONFIG_NAMESPACE,
                 lower_rbac: bool = True):
        self.default_manifest = dict(default_manifest or {})
        self.interner = interner or InternTable()
        self.max_str_len = max_str_len
        self.config_namespace = config_namespace
        # per-rule parse/DNF memo shared across every build() AND the
        # shard-bank sub-compiles (via Snapshot.decomp_cache): config
        # deltas re-present almost every predicate unchanged, so only
        # genuinely new match strings pay parse + decomposition
        from istio_tpu.compiler.cache import DecompCache
        self.decomp_cache = DecompCache()
        # False for non-fused servers: only the fused engine reads the
        # synthesized pseudo-rule rows — compiling them into a snapshot
        # the generic dispatcher serves would be pure compile/step waste
        self.lower_rbac = lower_rbac
        self._revision = 0

    def build(self, store: Store) -> Snapshot:
        self._revision += 1
        errors: list[str] = []

        # 1. attribute vocabulary (processAttributeManifests
        #    controller.go:273)
        manifest: dict[str, ValueType] = dict(self.default_manifest)
        for key, spec in store.list(KIND_MANIFEST).items():
            for attr, desc in (spec.get("attributes") or {}).items():
                vt_name = str((desc or {}).get("value_type",
                                               "STRING")).upper()
                try:
                    manifest[attr] = ValueType[vt_name]
                except KeyError:
                    errors.append(f"{key}: bad value_type {vt_name}"
                                  f" for {attr}")
        finder = AttributeDescriptorFinder(manifest)

        # 2. handlers
        handlers: dict[str, HandlerConfig] = {}
        for (kind, ns, name), spec in store.list(KIND_HANDLER).items():
            adapter = spec.get("adapter") or spec.get("compiledAdapter")
            if not adapter:
                errors.append(f"handler {name}.{ns}: missing adapter")
                continue
            hc = HandlerConfig(name=name, namespace=ns,
                               adapter=str(adapter),
                               params=dict(spec.get("params") or {}))
            handlers[_qualify(name, ns)] = hc

        # 3. instances
        from istio_tpu.compiler.cache import stable_digest
        instances: dict[str, InstanceBuilder] = {}
        instance_templates: dict[str, str] = {}
        instance_digests: dict[str, str] = {}
        for (kind, ns, name), spec in store.list(KIND_INSTANCE).items():
            tmpl_name = spec.get("template") or spec.get("compiledTemplate")
            if not tmpl_name:
                errors.append(f"instance {name}.{ns}: missing template")
                continue
            qname = _qualify(name, ns)
            # content identity BEFORE any param mutation below — the
            # bank cache keys on what the store said, not on builder
            # internals
            instance_digests[qname] = stable_digest(
                [str(tmpl_name), dict(spec.get("params") or {})])
            try:
                info = template_registry.get(str(tmpl_name))
                params = dict(spec.get("params") or {})
                bindings = params.pop("attribute_bindings", None)
                ib = InstanceBuilder(info, qname, params, finder)
                if bindings:
                    ib.attribute_bindings = dict(bindings)
                instances[qname] = ib
                instance_templates[qname] = info.name
            except TemplateError as exc:
                errors.append(f"instance {qname}: {exc}")

        # 4. rules (+ predicate compilation)
        rules: list[RuleConfig] = []
        preds: list[RulePred] = []
        for (kind, ns, name), spec in store.list(KIND_RULE).items():
            actions = []
            for a in (spec.get("actions") or ()):
                handler = str(a.get("handler", ""))
                if "." not in handler:
                    handler = _qualify(handler, ns)
                inst_names = []
                for inst in (a.get("instances") or ()):
                    inst = str(inst)
                    if "." not in inst:
                        inst = _qualify(inst, ns)
                    inst_names.append(inst)
                missing = [h for h in [handler] if h not in handlers]
                missing += [i for i in inst_names if i not in instances]
                if missing:
                    errors.append(f"rule {name}.{ns}: unknown refs "
                                  f"{missing}")
                    continue
                actions.append(Action(handler=handler,
                                      instances=tuple(inst_names)))
            rc = RuleConfig(name=name, namespace=ns,
                            match=str(spec.get("match", "") or ""),
                            actions=tuple(actions))
            rules.append(rc)
            # rules in the config (default) namespace are global: the
            # ruleset's "" namespace applies to every request
            pred_ns = "" if ns in ("", self.config_namespace) else ns
            preds.append(RulePred(name=_qualify(name, ns), match=rc.match,
                                  namespace=pred_ns))

        kwargs = {} if self.max_str_len is None \
            else {"max_str_len": self.max_str_len}
        # listentry instances whose value is a bare (map, key) read get
        # a derived layout column so the fused engine can absorb them
        # (runtime/fused.py id-membership scan)
        derived = set()
        for qname, ib in instances.items():
            if instance_templates[qname] != "listentry":
                continue
            ref = ib.value_attr_ref()
            if isinstance(ref, tuple):
                derived.add(ref)
        kwargs["extra_derived_keys"] = sorted(derived)
        # listentry instances feeding REGEX/IP_ADDRESSES list handlers
        # additionally get a BYTE slot: their device lowering matches
        # value bytes (DFA scan / CIDR prefix compare, runtime/fused.py)
        # rather than interned ids
        byte_srcs = set()
        for rc in rules:
            for a in rc.actions:
                hc = handlers.get(a.handler)
                if hc is None or hc.adapter != "list":
                    continue
                if hc.params.get("entry_type", "STRINGS") not in \
                        ("REGEX", "IP_ADDRESSES"):
                    continue
                for iname in a.instances:
                    if instance_templates.get(iname) != "listentry":
                        continue
                    ref = instances[iname].value_attr_ref()
                    if ref is not None:
                        byte_srcs.add(ref)
        kwargs["extra_byte_sources"] = sorted(byte_srcs, key=str)
        # REPORT instance field expressions lower onto the device
        # (runtime/report_lower.py — the reference runs them through
        # the same IL loop as predicates, template.gen.go
        # ProcessReport): collect their layout needs (derived map-key
        # columns, byte slots for match()/startsWith subjects, extern
        # ingest columns) in the same pre-pass the predicates use. An
        # instance whose requirements cannot collect keeps the host
        # build — never a config error.
        from istio_tpu.compiler.tensor_expr import (HostFallback,
                                                    Requirements,
                                                    collect_requirements)

        def _field_asts(tree):
            for v in tree.values():
                if isinstance(v, dict):
                    yield from _field_asts(v)
                else:
                    yield v

        rep_reqs = Requirements()
        seen_report: set[str] = set()
        for rc in rules:
            for a in rc.actions:
                for iname in a.instances:
                    ib = instances.get(iname)
                    tmpl = instance_templates.get(iname)
                    if ib is None or tmpl is None or iname in seen_report:
                        continue
                    if template_registry.get(tmpl).variety is not \
                            Variety.REPORT:
                        continue
                    seen_report.add(iname)
                    try:
                        r = Requirements()
                        for ast in _field_asts(ib.expr_tree()):
                            collect_requirements(ast, finder, r)
                        rep_reqs.merge(r)
                    except HostFallback:
                        pass    # instance keeps InstanceBuilder.build
        if rep_reqs.derived_keys:
            kwargs["extra_derived_keys"] = sorted(
                set(kwargs["extra_derived_keys"]) | rep_reqs.derived_keys)
        if rep_reqs.byte_sources:
            kwargs["extra_byte_sources"] = sorted(
                set(kwargs["extra_byte_sources"])
                | rep_reqs.byte_sources, key=str)
        if rep_reqs.extern_sources:
            kwargs["extra_extern_sources"] = [
                (n, k, east)
                for (n, k), east in sorted(rep_reqs.extern_sources.items(),
                                           key=lambda kv: kv[0])]
        # rule-axis padded to 8 so the matched/err planes shard evenly
        # over any mp ∈ {1,2,4,8} serving mesh (parallel/mesh.py)
        kwargs["rule_pad"] = 8

        roles = [dict(spec, name=k[2], namespace=k[1])
                 for k, spec in store.list(KIND_SERVICE_ROLE).items()]
        bindings = [dict(spec, name=k[2], namespace=k[1])
                    for k, spec in store.list(
                        KIND_SERVICE_ROLE_BINDING).items()]

        # rbac device lowering: synthesize pseudo-rule rows per
        # (handler, authorization instance) pair so the fused engine
        # can compute allow/deny on device (compiler/rbac_lower.py;
        # reference host loop: mixer/adapter/rbac/rbac.go:181)
        n_config_rules = len(preds)
        rbac_groups = self._lower_rbac_groups(
            rules, handlers, instances, instance_templates,
            roles, bindings, finder, preds) if self.lower_rbac else {}

        try:
            ruleset = compile_ruleset(preds, finder,
                                      interner=self.interner,
                                      decomp_cache=self.decomp_cache,
                                      **kwargs)
        except Exception as exc:
            # a predicate that doesn't type-check is a config error for
            # that rule; retry with offenders replaced by 'false'
            safe_preds, bad = [], []
            for p in preds:
                try:
                    compile_ruleset([p], finder, interner=self.interner,
                                    decomp_cache=self.decomp_cache,
                                    **kwargs)
                    safe_preds.append(p)
                except Exception as e2:
                    errors.append(f"rule {p.name}: {e2}")
                    safe_preds.append(RulePred(name=p.name, match="false",
                                               namespace=p.namespace))
            ruleset = compile_ruleset(safe_preds, finder,
                                      interner=self.interner,
                                      decomp_cache=self.decomp_cache,
                                      **kwargs)

        # pseudo-rules are implementation detail, not policy: their
        # predicate attrs must not leak into ReferencedAttributes (the
        # host path only evaluates rbac instance exprs when the parent
        # rule matched — instance_attrs cover that, runtime/fused.py)
        if len(preds) > n_config_rules:
            ruleset.attr_mask[n_config_rules:, :] = False
            for i in range(n_config_rules, len(preds)):
                ruleset.attr_names[i] = set()
            for g in rbac_groups.values():
                if not g.lowered:
                    continue
                rows = set(g.allow_rows)
                if g.guard_row >= 0:
                    rows.add(g.guard_row)
                bad_rows = rows & set(ruleset.host_fallback)
                if bad_rows:
                    g.lowered = False
                    g.reason = "; ".join(sorted(
                        ruleset.fallback_reason.get(r, "host fallback")
                        for r in bad_rows))

        return Snapshot(revision=self._revision, finder=finder,
                        handlers=handlers, instances=instances,
                        instance_templates=instance_templates,
                        rules=rules, ruleset=ruleset,
                        # no hash_slots: the serving engine runs
                        # quotas=() (host adapters own quota state), so
                        # nothing reads the hash plane. A quota-bearing
                        # PolicyEngine must tensorize via its own
                        # .tensorizer, which hashes its key slots.
                        tensorizer=Tensorizer(ruleset.layout,
                                              self.interner),
                        roles=roles, bindings=bindings, errors=errors,
                        n_config_rules=n_config_rules,
                        rbac_groups=rbac_groups,
                        compile_kwargs=dict(kwargs),
                        instance_digests=instance_digests,
                        decomp_cache=self.decomp_cache)

    @staticmethod
    def _lower_rbac_groups(rules, handlers, instances,
                           instance_templates, roles, bindings, finder,
                           preds):
        """Synthesize rbac pseudo-rule predicates, appending to `preds`.

        Every synthesized AST is pre-validated (eval_type == BOOL) so
        the whole-ruleset compile can never fail because of a pseudo
        rule — an unfusable policy shape keeps its action on the host
        adapter (group.lowered=False, logged), never changes
        semantics."""
        import logging

        from istio_tpu.compiler.rbac_lower import (RbacLowerError,
                                                   lower_rbac)
        from istio_tpu.expr.checker import DEFAULT_FUNCS, eval_type
        from istio_tpu.attribute.types import ValueType

        log = logging.getLogger("istio_tpu.runtime.config")

        groups: dict[tuple[str, str], RbacGroup] = {}
        for rc in rules:
            for action in rc.actions:
                hc = handlers.get(action.handler)
                if hc is None or hc.adapter != "rbac":
                    continue
                for inst in action.instances:
                    if instance_templates.get(inst) != "authorization" \
                            or (action.handler, inst) in groups:
                        continue
                    key = (action.handler, inst)
                    # handler params override store kinds, matching the
                    # host build (runtime/handler_table.py setdefault —
                    # an explicit empty list in params stays empty)
                    eff_roles = hc.params["roles"] \
                        if "roles" in hc.params else roles
                    eff_bindings = hc.params["bindings"] \
                        if "bindings" in hc.params else bindings
                    try:
                        low = lower_rbac(eff_roles, eff_bindings,
                                         instances[inst].expr_tree(),
                                         finder)
                        for ast in low.allow_asts + (
                                [low.guard_ast] if low.guard_ast
                                is not None else []):
                            t = eval_type(ast, finder, DEFAULT_FUNCS)
                            if t != ValueType.BOOL:
                                raise RbacLowerError(
                                    f"pseudo-rule type {t.name}")
                    except Exception as exc:
                        reason = f"{type(exc).__name__}: {exc}"
                        log.info("rbac policy for %s not device-"
                                 "lowerable, serving via host adapter:"
                                 " %s", inst, reason)
                        groups[key] = RbacGroup(
                            handler=action.handler, instance=inst,
                            allow_rows=(), lowered=False, reason=reason)
                        continue
                    base = len(preds)
                    for i, ast in enumerate(low.allow_asts):
                        preds.append(RulePred(
                            name=f"~rbac/{inst}/{i}", ast=ast))
                    guard_row = -1
                    if low.guard_ast is not None:
                        guard_row = len(preds)
                        preds.append(RulePred(
                            name=f"~rbac/{inst}/guard",
                            ast=low.guard_ast))
                    groups[key] = RbacGroup(
                        handler=action.handler, instance=inst,
                        allow_rows=tuple(
                            range(base, base + len(low.allow_asts))),
                        guard_row=guard_row, n_triples=low.n_triples)
        return groups
