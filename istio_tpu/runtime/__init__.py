"""Policy runtime: config store → controller → resolver/dispatcher.

Maps the reference's mixer/pkg/runtime (+ the runtime2 config model it
was migrating to, SURVEY.md §2.3): a generic KV+watch config store
feeds a controller that rebuilds an immutable Snapshot on change —
attribute vocabulary, handler table (diffed by signature), instance
builders, and the COMPILED rule tensors — and publishes it atomically.
The dispatcher resolves requests against the snapshot's device ruleset
program and fans instances out to adapter handlers; the batcher
coalesces concurrent Check() calls into single device steps.
"""
from istio_tpu.runtime.store import (Event, FsStore, Key, MemStore, Store,
                                     StoreError)
from istio_tpu.runtime.config import Snapshot, SnapshotBuilder
from istio_tpu.runtime.dispatcher import CheckResponse, Dispatcher
from istio_tpu.runtime.controller import Controller
from istio_tpu.runtime.server import RuntimeServer, ServerArgs

__all__ = ["Event", "FsStore", "Key", "MemStore", "Store", "StoreError",
           "Snapshot", "SnapshotBuilder", "CheckResponse", "Dispatcher",
           "Controller", "RuntimeServer", "ServerArgs"]
