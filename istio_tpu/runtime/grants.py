"""Server-issued check-cache grants — TTL/use-count from config
volatility.

The mixerclient-side check cache (api/client.MixerClient, modeled on
the reference's mixerclient check_cache) reuses a verdict until the
response's `valid_duration` / `valid_use_count` budget is spent — so
the SERVER decides how much repeat traffic never crosses the wire.
The protocol fields have been wired and client-tested since PR 5;
until now the serving path emitted the static CheckResult defaults
(5 s / 10 000 uses) for every response regardless of how volatile the
config actually is.

This module derives the grant from **delta-compile generation age**:
a namespace whose rules just changed gets the TTL floor (outstanding
client caches go stale within one generation — the revocation leg),
and the grant ramps back toward the cap as the namespace proves
stable. Deny rules' own configured TTLs still apply (the dispatcher
folds with min()), so a grant can only ever SHORTEN a verdict's
cache budget, never extend it.

Applied at the dispatcher's respond stage for every response (allow
AND deny — a config delta that flips a cached DENY must revoke it
too). Opt-in via ServerArgs.check_grants: the emitted TTL becomes a
function of wall time since publish, which exact-parity surfaces
(sharded-vs-monolithic, mesh-vs-single, canary TTL diffs) must not
see unless they opt in on both sides.
"""
from __future__ import annotations

import threading
import time

__all__ = ["GrantPolicy"]


class GrantPolicy:
    """(ttl_s, use_count) per namespace from generation age.

    ttl(ns)  = min(cap,  floor  + age_s * ttl_ramp_per_s)
    uses(ns) = min(ucap,  ufloor + age_s * use_ramp_per_s)

    where age_s is the wall seconds since the last publish that
    changed `ns` (or any publish, when the changed set is unknown —
    the conservative monolithic default). Defaults keep a long-stable
    config at exactly the pre-grant values (5 s / 10 000), so turning
    grants on changes nothing for stable configs except the
    revocation window after a delta.
    """

    def __init__(self, ttl_floor_s: float = 1.0,
                 ttl_cap_s: float = 5.0,
                 ttl_ramp_per_s: float = 0.5,
                 use_floor: int = 64,
                 use_cap: int = 10_000,
                 use_ramp_per_s: float = 1024.0,
                 quantum_s: float = 0.5):
        if ttl_floor_s <= 0 or ttl_cap_s < ttl_floor_s:
            raise ValueError(
                f"need 0 < ttl_floor_s <= ttl_cap_s, got "
                f"{ttl_floor_s}/{ttl_cap_s}")
        self.ttl_floor_s = float(ttl_floor_s)
        self.ttl_cap_s = float(ttl_cap_s)
        self.ttl_ramp_per_s = float(ttl_ramp_per_s)
        self.use_floor = int(use_floor)
        self.use_cap = int(use_cap)
        self.use_ramp_per_s = float(use_ramp_per_s)
        # age quantization: a continuously-varying TTL would defeat
        # every response memo keyed on it (the native front's
        # serialization memo) and make byte-exact parity surfaces
        # time-flaky — grants step at most once per quantum instead
        self.quantum_s = max(float(quantum_s), 0.0)
        self._lock = threading.Lock()
        now = time.monotonic()
        # last change instant: per-namespace when a delta publish
        # names its changed set, plus the global instant every
        # namespace inherits when a publish can't attribute changes
        self._global_change = now
        self._ns_change: dict[str, float] = {}
        # identity axis (secure plane): last rotation/revocation
        # instant per SPIFFE principal. Folded by the mTLS fronts —
        # min() over the namespace grant, so a grant issued to a peer
        # whose identity just rotated drops to the TTL floor and the
        # old principal's cached verdicts die within one floor window
        # instead of riding out the full namespace grant. Bounded: a
        # mesh has few distinct principals per rotation window.
        self._identity_change: dict[str, float] = {}
        self._identity_revocations = 0
        self.generation = 0
        self._grants_issued = 0
        self._revocations = 0
        # audit watermark: the policy generation current when the most
        # recent grant was issued. Revoke-before-swap (PR 12) means a
        # post-publish grant must always carry the post-publish
        # generation — the audit plane checks issue watermark vs the
        # publish count it observed (runtime/audit.py grant_coherence).
        self._issued_at_generation = 0

    # -- publish side --------------------------------------------------

    def on_publish(self, changed_namespaces=None) -> None:
        """A config generation published. `changed_namespaces`: the
        delta-compile changed set (iterable of ns names) — only those
        namespaces drop to the TTL floor; None = attribution unknown
        (monolithic rebuild), every namespace revokes."""
        now = time.monotonic()
        with self._lock:
            self.generation += 1
            self._revocations += 1
            if changed_namespaces is None:
                scope = "all"
                self._global_change = now
                self._ns_change.clear()
            else:
                changed = list(changed_namespaces)
                scope = f"{len(changed)} namespaces"
                for ns in changed:
                    self._ns_change[ns] = now
        # mesh event timeline: a revocation storm (every client cache
        # dropping to the TTL floor at once) is exactly the event a
        # post-publish p99 spike needs next to it
        from istio_tpu.runtime import forensics
        forensics.record_event("grant_revoke", scope=scope,
                               generation=self.generation)

    def on_identity_rotate(self, identity: str) -> None:
        """A workload identity rotated (or was revoked+reissued): the
        principal's outstanding client-cache grants must not outlive
        the floor window. Called from the WorkloadIdentity on_rotate
        chain AFTER the serving certs swapped (rotation ordering:
        sign → swap certs → revoke identity grants)."""
        now = time.monotonic()
        with self._lock:
            if len(self._identity_change) >= 4096:
                self._identity_change.clear()
            self._identity_change[identity] = now
            self._identity_revocations += 1
            generation = self.generation
        from istio_tpu.runtime import forensics
        forensics.record_event("grant_revoke", scope="identity",
                               identity=identity,
                               generation=generation)

    # -- serve side ----------------------------------------------------

    def _quantize(self, age: float) -> float:
        if self.quantum_s <= 0:
            return age
        return (age // self.quantum_s) * self.quantum_s

    def _pair(self, age: float) -> tuple[float, int]:
        age = self._quantize(age)
        return (min(self.ttl_cap_s,
                    self.ttl_floor_s + age * self.ttl_ramp_per_s),
                min(self.use_cap,
                    self.use_floor + int(age * self.use_ramp_per_s)))

    def grant(self, ns: str) -> tuple[float, int]:
        """(ttl_s, use_count) for one namespace, now."""
        now = time.monotonic()
        with self._lock:
            changed = self._ns_change.get(ns, self._global_change)
            age = max(now - max(changed, self._global_change), 0.0)
            self._grants_issued += 1
            self._issued_at_generation = self.generation
        return self._pair(age)

    def grants_for(self, ns_names) -> list[tuple[float, int]]:
        """Vector form for the respond loop — one clock read, one
        lock round for the whole batch's distinct namespaces."""
        now = time.monotonic()
        out = []
        with self._lock:
            for ns in ns_names:
                changed = self._ns_change.get(ns, self._global_change)
                age = max(now - max(changed, self._global_change), 0.0)
                out.append(self._pair(age))
            self._grants_issued += len(out)
            if out:
                self._issued_at_generation = self.generation
        return out

    def identity_grant(self, identity: str) -> tuple[float, int]:
        """(ttl_s, use_count) for one authenticated principal, now.
        A principal that never rotated gets the cap pair — min()
        against the namespace grant makes that fold a no-op, so the
        identity axis costs nothing until a rotation actually
        happens."""
        now = time.monotonic()
        with self._lock:
            changed = self._identity_change.get(identity)
        if changed is None:
            return (self.ttl_cap_s, self.use_cap)
        return self._pair(max(now - changed, 0.0))

    def watermark(self) -> dict:
        """Grant/generation coherence reading for the audit plane —
        one lock round, no TTL math."""
        with self._lock:
            return {
                "generation": self.generation,
                "revocations": self._revocations,
                "grants_issued": self._grants_issued,
                "issued_at_generation": self._issued_at_generation,
            }

    def stats(self) -> dict:
        """Introspect/bench view: params + live per-ns ages."""
        now = time.monotonic()
        with self._lock:
            ages = {ns: round(now - t, 3)
                    for ns, t in sorted(self._ns_change.items())[:32]}
            return {
                "generation": self.generation,
                "ttl_floor_s": self.ttl_floor_s,
                "ttl_cap_s": self.ttl_cap_s,
                "ttl_ramp_per_s": self.ttl_ramp_per_s,
                "use_floor": self.use_floor,
                "use_cap": self.use_cap,
                "global_age_s": round(now - self._global_change, 3),
                "ns_ages_s": ages,
                "grants_issued": self._grants_issued,
                "revocations": self._revocations,
                "identity_revocations": self._identity_revocations,
                "identities_tracked": len(self._identity_change),
            }
