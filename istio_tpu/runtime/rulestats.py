"""Rule-level check telemetry: on-device accumulators, drain, export.

The decision-level observability plane (reference: Mixer's Report path
feeding telemetry adapters — prometheus/statsd/stdio — via
mixer/pkg/api/grpcServer.go:262; here the signal is harvested where it
already lives). PR 1 gave batch-level stage histograms; this module
answers *which rule* fired, denied or errored, per namespace, without
giving back the hot path: the verdict/match tensors are already on
device after every fused check step, so per-rule attribution is one
extra fold into int32 accumulator tensors that LIVE ON DEVICE across
steps (`RuleTelemetry`). A generation-tagged drain pulls deltas on a
snapshot interval — never in the batch critical path (the one
device→host sync sits behind the `# hotpath: sync-ok` pragma in
`drain`, and `scripts/hotpath_lint.py` covers this file's hot
functions) — and hands them to `RuleStatsAggregator`, which maps the
compiler's rule indices back to rule names via the snapshot, feeds the
`utils/metrics` counter families on /metrics, forwards Report-style
metric instances to registered adapter handlers, and serves the
introspect `/debug/rulestats` view (top-K hot rules, never-hit rules,
per-namespace deny rates, decision exemplars linked to RingReporter
traces).

Correctness bar: telemetry is a measurement, not an estimate — drained
counters equal an oracle recount exactly on seeded workloads
(scripts/rulestats_smoke.py, tests/test_rulestats.py). Host-fallback
rules (invisible to the device step) are counted host-side at the
overlay patch point in `Dispatcher._overlay_active`, so the totals
cover every config rule.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Iterable, Mapping

import numpy as np

from istio_tpu.utils import metrics as hostmetrics
from istio_tpu.utils.log import scope

log = scope("runtime.rulestats")

OK = 0

# names of the adapter-facing Report-style instances a drain emits
INSTANCE_HITS = "rulestats.hits"
INSTANCE_DENIES = "rulestats.denies"
INSTANCE_ERRORS = "rulestats.errors"


def register_families(reg: hostmetrics.Registry) -> dict:
    """Create the rule-telemetry counter families on `reg` and
    pre-touch each with a zero so the exposition carries a zero series
    BEFORE the first drain (a dashboard must distinguish "no rule ever
    fired" from "telemetry missing"). Split out for tests that want a
    private registry."""
    fams = {
        "hits": reg.counter(
            "mixer_rule_check_hits_total",
            "check requests a rule matched (ns-visible), by rule — "
            "drained from the on-device per-rule accumulators"),
        "denies": reg.counter(
            "mixer_rule_check_denies_total",
            "check requests a rule was the winning (lowest-index) "
            "non-OK source for, by rule"),
        "errors": reg.counter(
            "mixer_rule_check_errors_total",
            "check requests whose predicate errored for a rule "
            "(ns-visible), by rule"),
        "drains": reg.counter(
            "mixer_rulestats_drains_total",
            "accumulator drains (device→host delta pulls)"),
        "drain_seconds": reg.histogram(
            "mixer_rulestats_drain_seconds",
            "drain wall time: accumulator swap + async device pull"),
    }
    for key in ("hits", "denies", "errors", "drains"):
        fams[key].inc(0.0)
    return fams


FAMILIES = register_families(hostmetrics.default_registry)


def preview_attributes(bag, limit: int = 16,
                       value_len: int = 128) -> dict:
    """Bounded attribute preview of one sampled request — THE exemplar
    rendering contract shared by /debug/rulestats and /debug/canary
    (istio_tpu/canary/differ.py): first `limit` attributes, reprs
    truncated to `value_len`, decode failures sentineled."""
    attrs: dict = {}
    try:
        for name in list(bag.names())[:limit]:
            v, ok = bag.get(name)
            if ok:
                attrs[str(name)] = repr(v)[:value_len]
    except Exception:
        attrs = {"<decode-failed>": "1"}
    return attrs


class RuleTelemetry:
    """Per-snapshot on-device rule accumulators.

    State (int32, resident on device across steps):
      hit  [S, n_rows] — requests the rule matched, per namespace slot
      deny [S, n_rows] — requests the rule won the deny for, per slot
      err  [n_rows]    — ns-visible predicate errors
    where S = len(ns_ids) + 1; the extra slot collects requests whose
    namespace is unknown to the snapshot (namespace_id() == -1).

    `observe()` runs on the batch hot path: one jitted delta program
    over the verdict (pure, dispatched async) plus one jitted fold
    chained onto the accumulators under a lock — dispatch only, no
    host↔device sync. Padding rows are masked out by the caller's
    `real_mask` so bucket padding never pollutes the counts.
    Host-fallback rules read matched=False on device; their hits and
    errors arrive through `add_host()` at the dispatcher's overlay
    patch point, into host-side numpy planes merged at drain.

    `drain()` swaps fresh zero accumulators in under the lock (cheap
    device allocs, no sync) and pulls the OLD buffers outside it — the
    only device→host copy, generation-tagged, never on the batch
    critical path."""

    def __init__(self, ruleset, n_cfg: int, exemplars_per_rule: int = 4,
                 seed: int = 0):
        import jax
        import jax.numpy as jnp

        self.n_rows = int(ruleset.rule_ns.shape[0])
        self.n_cfg = int(n_cfg)
        self.n_slots = len(ruleset.ns_ids) + 1
        self._default_ns = ruleset.ns_ids[""]
        rule_ns = np.asarray(ruleset.rule_ns, np.int32)
        # host-fallback rows read err=True on device by construction
        # (RuleSetProgram contract) — mask them out of the device err
        # fold; their real errors arrive via add_host()
        err_rows = np.ones(self.n_rows, bool)
        for ridx in ruleset.host_fallback:
            if ridx < self.n_rows:
                err_rows[ridx] = False
        self._lock = threading.Lock()
        self.generation = 0
        zeros2 = jnp.zeros((self.n_slots, self.n_rows), jnp.int32)
        self._acc_hit = zeros2
        self._acc_deny = zeros2
        self._acc_err = jnp.zeros(self.n_rows, jnp.int32)
        # host-side planes for host-fallback rules (overlay patch)
        self._host_hit = np.zeros((self.n_slots, self.n_rows), np.int64)
        self._host_err = np.zeros(self.n_rows, np.int64)
        # decision exemplars: per-rule reservoirs of denied/errored
        # requests (bag ref + trace/span ids), sampled host-side
        self._ex_cap = exemplars_per_rule
        self._ex: dict[int, list] = {}
        self._ex_seen: dict[int, int] = {}
        self._rng = random.Random(seed)
        self._delta_fn = jax.jit(self._make_delta(
            rule_ns, self._default_ns, self.n_slots, err_rows))
        self._fold_fn = jax.jit(
            lambda h, d, e, dh, dd, de: (h + dh, d + dd, e + de))

    @staticmethod
    def _make_delta(rule_ns: np.ndarray, default_ns: int, n_slots: int,
                    err_rows: np.ndarray):
        import jax.numpy as jnp
        from jax import lax

        rns = jnp.asarray(rule_ns)
        err_rows_j = jnp.asarray(err_rows)
        n_rows = rule_ns.shape[0]
        dims = (((0,), (0,)), ((), ()))

        def delta(matched, err, status, deny_rule, req_ns, real):
            ns_ok = (rns[None, :] == default_ns) | \
                    (rns[None, :] == req_ns[:, None])
            active = matched & ns_ok & real[:, None]
            slot = jnp.where(req_ns < 0, n_slots - 1,
                             jnp.clip(req_ns, 0, n_slots - 1))
            onehot = (slot[:, None] ==
                      jnp.arange(n_slots)[None, :]).astype(jnp.int8)
            hit = lax.dot_general(onehot, active.astype(jnp.int8),
                                  dims,
                                  preferred_element_type=jnp.int32)
            deny_mask = (deny_rule[:, None] ==
                         jnp.arange(n_rows)[None, :]) & \
                        (status != OK)[:, None] & real[:, None]
            deny = lax.dot_general(onehot,
                                   deny_mask.astype(jnp.int8), dims,
                                   preferred_element_type=jnp.int32)
            err_d = jnp.sum((err & ns_ok & real[:, None] &
                             err_rows_j[None, :]).astype(jnp.int32),
                            axis=0)
            return hit, deny, err_d

        return delta

    # ------------------------------------------------------------------
    # hot path (scripts/hotpath_lint.py HOT_SECTIONS cover these)
    # ------------------------------------------------------------------

    def observe(self, verdict, req_ns, real_mask) -> None:
        """Fold one check batch's per-rule counts into the device
        accumulators. `req_ns`/`real_mask` are host numpy ([B] int32 /
        bool); everything else stays on device — dispatch only, the
        fold chains onto the accumulator buffers and the drain thread
        pays the sync later."""
        deltas = self._delta_fn(verdict.matched, verdict.err,
                                verdict.status, verdict.deny_rule,
                                req_ns, real_mask)
        # the lock serializes the read-modify-write of the accumulator
        # HANDLES only (async dispatch, never a sync): concurrent
        # pipeline workers must chain their folds, not race them
        with self._lock:
            self._acc_hit, self._acc_deny, self._acc_err = \
                self._fold_fn(self._acc_hit, self._acc_deny,
                              self._acc_err, *deltas)

    def add_host(self, cols, active_cols: np.ndarray,
                 err_counts: Mapping[int, int],
                 ns_slots: np.ndarray) -> None:
        """Host-side counts for host-fallback rules, from the overlay
        patch point (Dispatcher._overlay_active): `cols` rule indices,
        `active_cols` bool [B, len(cols)] (already ns-masked, padding
        already trimmed), `ns_slots` int [B] namespace slots,
        `err_counts` rule idx → predicate errors this batch. Pure
        numpy on host arrays — no device work."""
        with self._lock:
            for j, ridx in enumerate(cols):
                col = active_cols[:, j]
                if col.any():
                    np.add.at(self._host_hit[:, ridx], ns_slots[col], 1)
            for ridx, n in err_counts.items():
                self._host_err[ridx] += n

    def sample(self, ridx: int, status: int, bag, span) -> None:
        """Reservoir-sample one denied/errored request for rule
        `ridx`: keep the bag (compressed attribute bag — decoded at
        drain, never here) and the active trace span ids so the
        exemplar links straight to a RingReporter trace."""
        entry = {
            "status": status,
            "bag": bag,
            "trace_id": span.get("traceId") if span else None,
            "span_id": span.get("id") if span else None,
            "t": time.time(),
        }
        with self._lock:
            seen = self._ex_seen.get(ridx, 0) + 1
            self._ex_seen[ridx] = seen
            bucket = self._ex.setdefault(ridx, [])
            if len(bucket) < self._ex_cap:
                bucket.append(entry)
            else:
                j = self._rng.randrange(seen)
                if j < self._ex_cap:
                    bucket[j] = entry

    def ns_slots(self, ns_ids: np.ndarray) -> np.ndarray:
        """Request ns ids → accumulator slots (unknown/-1 → last)."""
        return np.where(ns_ids < 0, self.n_slots - 1, ns_ids)

    # ------------------------------------------------------------------
    # drain boundary (the ONE deliberate device→host sync)
    # ------------------------------------------------------------------

    def drain(self) -> dict:
        """Swap fresh zero accumulators in (no sync) and pull the old
        buffers — generation-tagged deltas since the previous drain.
        Exemplars are a sample, not a counter: returned as the current
        reservoirs (bags still encoded), not reset."""
        import jax.numpy as jnp

        t0 = time.perf_counter()
        with self._lock:
            hit, deny, err = self._acc_hit, self._acc_deny, self._acc_err
            zeros2 = jnp.zeros((self.n_slots, self.n_rows), jnp.int32)
            self._acc_hit = zeros2
            self._acc_deny = zeros2
            self._acc_err = jnp.zeros(self.n_rows, jnp.int32)
            host_hit, self._host_hit = self._host_hit, np.zeros(
                (self.n_slots, self.n_rows), np.int64)
            host_err, self._host_err = self._host_err, np.zeros(
                self.n_rows, np.int64)
            self.generation += 1
            gen = self.generation
            exemplars = {r: list(v) for r, v in self._ex.items()}
            ex_seen = dict(self._ex_seen)
        # the drain pull: blocks THIS thread until every fold chained
        # before the swap has landed — the batch critical path already
        # moved on to the fresh buffers
        hit_np = np.asarray(hit).astype(np.int64)    # hotpath: sync-ok (drain boundary)
        deny_np = np.asarray(deny).astype(np.int64)  # hotpath: sync-ok (drain boundary)
        err_np = np.asarray(err).astype(np.int64)    # hotpath: sync-ok (drain boundary)
        hit_np += host_hit
        err_np += host_err
        wall = time.perf_counter() - t0
        return {"generation": gen, "hit": hit_np, "deny": deny_np,
                "err": err_np, "exemplars": exemplars,
                "exemplars_seen": ex_seen, "wall_s": wall}

    def wait(self) -> None:
        """Block until every dispatched fold has executed (bench
        timing helper — NOT for the serving path)."""
        import jax
        with self._lock:
            handles = (self._acc_hit, self._acc_deny, self._acc_err)
        jax.block_until_ready(handles)


class RuleStatsAggregator:
    """Name-keyed aggregation over drained deltas + export fan-out.

    One aggregator per RuntimeServer. `attach(dispatcher)` follows
    config swaps: the outgoing plan is drained first (no counts lost),
    then rule-index→name mapping rebinds to the new snapshot.
    Cumulative counts are keyed by qualified rule name so they survive
    revisions; `never_hit` is judged against the CURRENT snapshot's
    rules."""

    def __init__(self, top_k: int = 10, metrics: dict | None = None):
        self._lock = threading.Lock()
        self.top_k = top_k
        self._metrics = metrics if metrics is not None else FAMILIES
        self._plan = None
        self._names: list[str] = []
        self._slot_names: list[str] = []
        self.revision: int | None = None
        self.last_generation = 0
        self.drains = 0
        self.last_drain_wall_s = 0.0
        # rule name → {"hits", "denies", "errors", "ns": {ns: {...}}}
        self._cum: dict[str, dict] = {}
        self._exemplars: dict[str, list] = {}
        self._exporters: list[tuple[Any, str]] = []
        # swapped-out plans still being swept: (plan, their names,
        # drop-after timestamp) — see attach()
        self._retired: list[tuple] = []
        # sharded serving lanes (istio_tpu/sharding): bank dispatchers'
        # plans swept on every drain alongside the main plan — their
        # per-rule counts merge into the same name-keyed cumulative
        # stats (bank rule names ARE the global qualified names). See
        # attach_lanes(). Entries: (plan, names, slot_names).
        self._lanes: list[tuple] = []

    # -- wiring --

    # how long a swapped-out plan's telemetry keeps being swept by
    # subsequent drains: batches in flight on the OLD dispatcher may
    # still fold into it after the rebind (mirrors the controller's
    # orphan-handler drain grace)
    RETIRE_SWEEP_S = 3.0

    def attach(self, dispatcher) -> None:
        """Bind to a freshly published dispatcher. The OLD plan is
        drained immediately AND retired for continued sweeping: a
        batch already in flight on the old dispatcher can fold into
        the old accumulators after this rebind, so drain() keeps
        pulling retired telemetries for RETIRE_SWEEP_S before letting
        them go — a config swap never drops counts."""
        self.drain()
        snap = dispatcher.snapshot
        rs = snap.ruleset
        plan = dispatcher.fused
        with self._lock:
            old = self._plan
            if old is not None and old is not plan:
                self._retired.append(
                    (old, self._names, self._slot_names,
                     time.time() + self.RETIRE_SWEEP_S))
            has_tele = plan is not None and \
                getattr(plan, "telemetry", None) is not None
            self._plan = plan if has_tele else None
            # index→name mapping shared with the canary differ
            # (runtime/config.Snapshot.qualified_rule_names); test
            # doubles may hand bare rule lists without the method
            qn = getattr(snap, "qualified_rule_names", None)
            self._names = list(qn()) if qn is not None else [
                f"{r.namespace}/{r.name}"
                if getattr(r, "namespace", "") else r.name
                for r in snap.rules]
            by_id = {v: k for k, v in rs.ns_ids.items()}
            n_slots = len(rs.ns_ids) + 1
            self._slot_names = [
                by_id.get(i, f"ns#{i}") or "(default)"
                for i in range(n_slots - 1)] + ["(unknown)"]
            self.revision = snap.revision
            for name in self._names:
                self._cum.setdefault(
                    name, {"hits": 0, "denies": 0, "errors": 0,
                           "ns": {}})

    def attach_lanes(self, dispatchers) -> None:
        """Bind the sharded plane's bank dispatchers as additional
        drain sources (config swaps call this right after the lane
        publish). The PREVIOUS lane set is retired for continued
        sweeping exactly like attach()'s old plan — a batch in flight
        on an old bank can fold after the rebind, and a swap must
        never drop counts. The main attached plan is skipped if it
        also appears as a lane (replica-only mode's lane 0 rides the
        published dispatcher)."""
        lanes: list[tuple] = []
        seen: set[int] = set()
        with self._lock:
            main = self._plan
        for d in dispatchers:
            plan = getattr(d, "fused", None)
            if plan is None or plan is main or id(plan) in seen:
                continue
            if getattr(plan, "telemetry", None) is None:
                continue
            seen.add(id(plan))
            snap = d.snapshot
            qn = getattr(snap, "qualified_rule_names", None)
            names = list(qn()) if qn is not None else []
            rs = snap.ruleset
            by_id = {v: k for k, v in rs.ns_ids.items()}
            n_slots = len(rs.ns_ids) + 1
            slot_names = [by_id.get(i, f"ns#{i}") or "(default)"
                          for i in range(n_slots - 1)] + ["(unknown)"]
            lanes.append((plan, names, slot_names))
        with self._lock:
            for _plan, names, _slots in lanes:
                for name in names:
                    self._cum.setdefault(
                        name, {"hits": 0, "denies": 0, "errors": 0,
                               "ns": {}})
            old = self._lanes
            self._lanes = lanes
            deadline = time.time() + self.RETIRE_SWEEP_S
            live = {id(p) for p, _, _ in lanes}
            for plan, names, slots in old:
                if id(plan) not in live:
                    self._retired.append((plan, names, slots,
                                          deadline))

    def add_exporter(self, handler, template: str = "metric") -> None:
        """Register an adapter handler (prometheus/statsd/stdio/...)
        to receive Report-style metric instances on every drain."""
        with self._lock:
            self._exporters.append((handler, template))

    def reset(self) -> None:
        with self._lock:
            self._cum.clear()
            self._exemplars.clear()
            self.drains = 0
            self.last_generation = 0

    # -- drain + fold --

    def drain(self) -> dict | None:
        """Pull deltas from the attached plan's device accumulators,
        fold into the name-keyed cumulative stats, bump the /metrics
        counter families, and fan instances out to exporters. Retired
        plans (config swaps) are swept first — batches that were in
        flight across the swap fold late into the OLD accumulators.
        Returns the live plan's raw drain dict (None when no telemetry
        is attached). Called by the RuntimeServer's drain thread on
        its snapshot interval and on demand by /debug/rulestats —
        never by the serving path."""
        with self._lock:
            plan = self._plan
            names = self._names
            slot_names = self._slot_names
            now = time.time()
            retired = list(self._retired)
            self._retired = [r for r in self._retired if r[3] > now]
            lanes = list(self._lanes)
        instances: list[dict] = []
        for rplan, rnames, rslots, _deadline in retired:
            rtele = getattr(rplan, "telemetry", None)
            if rtele is None:
                continue
            try:
                instances += self._fold(rtele.drain(), rnames, rslots)
            except Exception:
                log.exception("retired-plan drain failed")
        tele = getattr(plan, "telemetry", None) if plan is not None \
            else None
        d = None
        if tele is not None:
            d = tele.drain()
            self._metrics["drains"].inc()
            self._metrics["drain_seconds"].observe(d["wall_s"])
            instances += self._fold(d, names, slot_names)
            with self._lock:
                self.last_generation = d["generation"]
                self.drains += 1
                self.last_drain_wall_s = d["wall_s"]
        # sharded serving lanes: every bank's accumulators drain into
        # the same name-keyed stats (bank names are global qualified
        # names, so counts from different banks never collide — each
        # rule lives in exactly one bank per generation, global rules
        # in every bank but each request served by exactly one)
        for lplan, lnames, lslots in lanes:
            ltele = getattr(lplan, "telemetry", None)
            if ltele is None:
                continue
            try:
                instances += self._fold(ltele.drain(), lnames, lslots)
            except Exception:
                log.exception("lane-plan drain failed")
        if d is None and not retired and not lanes:
            return None
        with self._lock:
            exporters = list(self._exporters)
        if instances:
            for handler, template in exporters:
                try:
                    handler.handle_report(template, instances)
                except Exception:
                    log.exception("rulestats exporter failed")
        return d

    def _fold(self, d: dict, names: list[str],
              slot_names: list[str]) -> list[dict]:
        """Fold one drain's deltas into the cumulative stats + counter
        families; returns the Report-style instances for exporters."""
        hit, deny, err = d["hit"], d["deny"], d["err"]
        n_cfg = min(len(names), hit.shape[1])
        hit_r = hit[:, :n_cfg].sum(axis=0)
        deny_r = deny[:, :n_cfg].sum(axis=0)
        instances: list[dict] = []
        with self._lock:
            for r in range(n_cfg):
                h, dn, e = int(hit_r[r]), int(deny_r[r]), int(err[r])
                if not (h or dn or e):
                    continue
                name = names[r]
                cum = self._cum.setdefault(
                    name, {"hits": 0, "denies": 0, "errors": 0,
                           "ns": {}})
                cum["hits"] += h
                cum["denies"] += dn
                cum["errors"] += e
                if h:
                    self._metrics["hits"].inc(h, rule=name)
                if dn:
                    self._metrics["denies"].inc(dn, rule=name)
                if e:
                    self._metrics["errors"].inc(e, rule=name)
                    instances.append({
                        "name": INSTANCE_ERRORS, "value": e,
                        "dimensions": {"rule": name}})
                for s in np.nonzero(hit[:, r] | deny[:, r])[0]:
                    ns = slot_names[s] if s < len(slot_names) \
                        else f"slot#{s}"
                    per = cum["ns"].setdefault(
                        ns, {"hits": 0, "denies": 0})
                    hs, ds = int(hit[s, r]), int(deny[s, r])
                    per["hits"] += hs
                    per["denies"] += ds
                    if hs:
                        instances.append({
                            "name": INSTANCE_HITS, "value": hs,
                            "dimensions": {"rule": name,
                                           "namespace": ns}})
                    if ds:
                        instances.append({
                            "name": INSTANCE_DENIES, "value": ds,
                            "dimensions": {"rule": name,
                                           "namespace": ns}})
            for ridx, entries in d["exemplars"].items():
                if ridx >= n_cfg:
                    continue
                self._exemplars[names[ridx]] = [
                    self._render_exemplar(e) for e in entries]
        return instances

    @staticmethod
    def _render_exemplar(e: dict) -> dict:
        """Decode a sampled request off the hot path: the compressed
        attribute bag renders to a bounded attribute preview, the
        trace/span ids pass through for /debug/traces joins."""
        return {"status": e["status"],
                "attributes": preview_attributes(e.get("bag")),
                "trace_id": e.get("trace_id"),
                "span_id": e.get("span_id"), "t": e.get("t")}

    # -- views --

    def snapshot(self, top_k: int | None = None,
                 shadowed: Iterable[str] = ()) -> dict:
        """JSON-able /debug/rulestats payload. `shadowed`: BARE rule
        names the static analyzer flagged shadowed (PR 3 findings
        carry unqualified names) — cross-checked against the never-hit
        list so a dead rule shows whether it is provably dead
        (analyzer agrees) or merely unexercised. A never-hit rule is
        flagged only when its bare name is BOTH in the set and unique
        among the current snapshot's rules: an ambiguous bare name
        (same rule name in two namespaces) must never mark a live rule
        provably dead."""
        k = top_k or self.top_k
        shadowed = set(shadowed)
        with self._lock:
            current = list(self._names)
            cum = {n: dict(v, ns={ns: dict(p)
                                  for ns, p in v["ns"].items()})
                   for n, v in self._cum.items()}
            exemplars = {n: list(v) for n, v in self._exemplars.items()}
            payload = {
                "revision": self.revision,
                "generation": self.last_generation,
                "drains": self.drains,
                "last_drain_wall_ms": round(
                    self.last_drain_wall_s * 1e3, 3),
                "rules_tracked": len(current),
            }
        ranked = sorted(
            (n for n in cum if cum[n]["hits"] or cum[n]["denies"]
             or cum[n]["errors"]),
            key=lambda n: (-cum[n]["hits"], -cum[n]["denies"], n))
        top = []
        for n in ranked[:k]:
            c = cum[n]
            deny_rate_by_ns = {
                ns: round(p["denies"] / p["hits"], 4)
                for ns, p in c["ns"].items() if p["hits"]}
            top.append({
                "rule": n, "hits": c["hits"], "denies": c["denies"],
                "errors": c["errors"],
                "deny_rate": round(c["denies"] / c["hits"], 4)
                if c["hits"] else 0.0,
                "deny_rate_by_namespace": deny_rate_by_ns,
                "by_namespace": c["ns"],
                "exemplars": exemplars.get(n, []),
            })
        never = [n for n in current
                 if not cum.get(n, {}).get("hits")]
        bare_counts: dict[str, int] = {}
        for n in current:
            bare = n.rsplit("/", 1)[-1]
            bare_counts[bare] = bare_counts.get(bare, 0) + 1
        payload["top"] = top
        never_hit = []
        for n in never:
            bare = n.rsplit("/", 1)[-1]
            never_hit.append({
                "rule": n,
                "analyzer_shadowed": bare in shadowed
                and bare_counts.get(bare) == 1})
        payload["never_hit"] = never_hit
        payload["never_hit_count"] = len(never)
        payload["exemplar_rules"] = sorted(exemplars)
        return payload

    def counts(self) -> dict:
        """{rule name: {hits, denies, errors, ns}} copy (tests, smoke
        recount comparisons)."""
        with self._lock:
            return {n: dict(v, ns={ns: dict(p)
                                   for ns, p in v["ns"].items()})
                    for n, v in self._cum.items()}


class RuleStatsDrainer:
    """Background snapshot-interval drain loop (the adapter-driven
    drain cadence). Owned by RuntimeServer; close() stops it."""

    def __init__(self, aggregator: RuleStatsAggregator,
                 interval_s: float = 0.5):
        self.aggregator = aggregator
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rulestats-drain")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.aggregator.drain()
            except Exception:
                log.exception("rulestats drain failed")

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
