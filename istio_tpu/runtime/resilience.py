"""Overload resilience for the Check() serving path.

The BASELINE tail SLO (<1ms p99 at 10k rules) only means something if
it survives the bad day: an unbounded batcher queue turns overload
into unbounded queue_wait, a request with no deadline is work the
caller stopped wanting long ago, and a single device-step exception
used to fail every batch-mate with a raw INTERNAL. The pieces here are
the standard overload-control toolkit ("The Tail at Scale", CACM 2013;
DAGOR, SOSP'18; Istio's Mixer client fail-open semantics):

  * typed rejections (CheckRejected) that the API fronts map onto real
    gRPC status codes — DEADLINE_EXCEEDED / RESOURCE_EXHAUSTED /
    UNAVAILABLE instead of INTERNAL for every failure shape;
  * a device CIRCUIT BREAKER (closed → open → half-open) in front of
    the fused device step: transient failures retry once with jittered
    backoff, consecutive failures trip the breaker and whole batches
    route to the CPU SnapshotOracle path (compiler/ruleset.py) — the
    same per-rule oracles the compiler tests conformance against, so
    degraded answers are CORRECT answers, just slower;
  * a fail policy for when even the oracle path is down: fail-open
    answers OK (Mixer client `policyCheckFailOpen`), fail-closed
    answers UNAVAILABLE;
  * ChaosHooks — the fault-injection seam the chaos suite and
    scripts/chaos_smoke.py drive (injected device-step exceptions,
    added device latency, oracle failures). The hooks sit at the real
    device boundary (FusedPlan.packed_check / Dispatcher._resolve), so
    an injected failure exercises exactly the production unwind path.

Admission control (queue cap, brownout, deadline expiry) lives in
runtime/batcher.py; this module owns what happens once a batch reaches
the device. Counters for every shed/expired/fallback decision are in
runtime/monitor.py and exported through /metrics and the introspect
server's /debug/resilience.
"""
from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Any, Callable, Sequence

log = logging.getLogger("istio_tpu.runtime.resilience")

# gRPC status codes the serving path rejects with (google.rpc.Code)
INVALID_ARGUMENT = 3
DEADLINE_EXCEEDED = 4
RESOURCE_EXHAUSTED = 8
UNAVAILABLE = 14
UNAUTHENTICATED = 16


class CheckRejected(RuntimeError):
    """A request the serving path refused to answer — carries the gRPC
    status code the API fronts must surface (INTERNAL is reserved for
    genuine bugs; overload and degradation get honest codes)."""
    grpc_code = 2   # UNKNOWN; subclasses override


class InvalidArgumentError(CheckRejected):
    """The request's wire attributes could not be decoded/re-encoded
    (malformed bag at the identity-injection boundary): the caller
    sent garbage, not the server — typed so the wire says so."""
    grpc_code = INVALID_ARGUMENT


class DeadlineExceededError(CheckRejected):
    grpc_code = DEADLINE_EXCEEDED


class ResourceExhaustedError(CheckRejected):
    grpc_code = RESOURCE_EXHAUSTED


class UnavailableError(CheckRejected):
    grpc_code = UNAVAILABLE


class UnauthenticatedError(CheckRejected):
    """Strict-mTLS admission refused a request that presented no
    verified peer identity (secure/mtls.py). Typed so the wire shows
    UNAUTHENTICATED — never an opaque TLS alert or INTERNAL — and the
    meshlint typed-rejection pass can audit the boundary."""
    grpc_code = UNAUTHENTICATED


@dataclasses.dataclass
class ResilienceConfig:
    """Knobs for the ResilientChecker (ServerArgs mirrors these; the
    mixs CLI exposes them as --check-fail-policy / --breaker-*)."""
    # "open": when device AND oracle paths are down, answer OK (the
    # Mixer client's fail-open posture — policy must not take the mesh
    # down with it). "closed": answer UNAVAILABLE.
    fail_policy: str = "closed"
    # consecutive failed batches (after the in-batch retry) that trip
    # the breaker
    breaker_failures: int = 3
    # how long the breaker stays open before a half-open probe
    breaker_reset_s: float = 5.0
    # retry a failed device step once, with jittered backoff, before
    # counting it as a breaker failure
    retry: bool = True
    retry_backoff_s: float = 0.005
    retry_jitter_s: float = 0.010


class CircuitBreaker:
    """closed → open (N consecutive failures) → half-open (one probe
    after reset_s) → closed on probe success / open on probe failure.

    Thread-safe: batches run concurrently on the batcher's worker pool,
    and state transitions must be decided under one lock (two probes in
    flight would double-count a flapping device)."""

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"

    def __init__(self, failures: int = 3, reset_s: float = 5.0,
                 publish: bool = True, name: str = "device"):
        self.failure_threshold = max(int(failures), 1)
        self.reset_s = reset_s
        # forensics identity: which breaker transitioned ("device",
        # "handler:<qualified name>", "bank:<shard>") — the mesh
        # event timeline records transitions by this name
        self.name = name
        # False for NON-device breakers (the adapter executor's
        # per-handler lanes): they must not clobber the device
        # breaker's mixer_check_breaker_state gauge — their state
        # surfaces via their owner's snapshot (/debug/executor)
        self._publish_gauge = publish
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._publish()

    def _publish(self) -> None:
        if not self._publish_gauge:
            return
        from istio_tpu.runtime import monitor
        monitor.BREAKER_STATE.set(
            {self.CLOSED: 0, self.HALF_OPEN: 1, self.OPEN: 2}[self._state])

    def _transition(self, to: str) -> None:
        if to == self._state:
            return
        log.warning("circuit breaker %s: %s -> %s", self.name,
                    self._state, to)
        # mesh event timeline (runtime/forensics.py): a breaker flip
        # is exactly the control-plane event a slow-request exemplar
        # needs next to it. record_event never raises and the ring
        # lock is a leaf, so holding self._lock here is safe.
        from istio_tpu.runtime import forensics
        forensics.record_event("breaker", name=self.name,
                               frm=self._state, to=to)
        self._state = to
        if self._publish_gauge:
            from istio_tpu.runtime import monitor
            monitor.BREAKER_TRANSITIONS.labels(to=to).inc()
        self._publish()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow_device(self) -> bool:
        """May this batch try the device? OPEN past the reset window
        admits exactly ONE half-open probe; everyone else falls back
        until the probe verdict lands."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN and \
                    time.monotonic() - self._opened_at >= self.reset_s:
                self._transition(self.HALF_OPEN)
            if self._state == self.HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probe_inflight = False
            if self._state != self.CLOSED:
                self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == self.HALF_OPEN:
                # the probe failed: back to open, restart the window
                self._probe_inflight = False
                self._opened_at = time.monotonic()
                self._transition(self.OPEN)
            elif self._state == self.CLOSED and \
                    self._consecutive >= self.failure_threshold:
                self._opened_at = time.monotonic()
                self._transition(self.OPEN)

    def release_probe(self) -> None:
        """A batch that got a device slot ended with NO verdict (a
        typed rejection or a non-Exception unwind rode out of the
        device call). The probe slot must be returned or a half-open
        breaker wedges with probe_inflight forever and never tries the
        device again."""
        with self._lock:
            self._probe_inflight = False

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "failure_threshold": self.failure_threshold,
                "reset_s": self.reset_s,
                "probe_inflight": self._probe_inflight,
            }
            if self._state == self.OPEN:
                out["open_for_s"] = round(
                    time.monotonic() - self._opened_at, 3)
            return out


class ChaosHooks:
    """Fault-injection seams for the chaos suite. All fields default to
    inert; production code pays one attribute read per batch. The
    device seam fires at the REAL device boundary (packed_check /
    the generic resolve step) so injected failures exercise the same
    unwind the hardware would."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()
        # injection observer (the audit plane's explainability scorer
        # registers expected-signature records here). Assigned AFTER
        # reset() and never touched by it: the scorer's registration
        # must survive the chaos suite's per-scenario resets. Called
        # OUTSIDE self._lock at each injection-commit point; must
        # never raise. Zero cost while chaos is unarmed.
        self.on_inject: Callable[..., None] | None = None

    def _notify(self, kind: str, **detail) -> None:
        cb = self.on_inject
        if cb is None:
            return
        try:
            cb(kind, **detail)
        except Exception:
            pass

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            # fail the next N device steps (a huge N = hard outage)
            self.device_failures = 0
            # exception factory for injected device failures
            self.device_exception: Callable[[], BaseException] | None = None
            # sleep added to every device step (queue-saturation lever:
            # a slow device backs the batcher queue up to its cap)
            self.device_latency_s = 0.0
            # fail the next N oracle-fallback batches (drives the
            # fail-open/fail-closed policy paths)
            self.oracle_failures = 0
            self.injected_device = 0
            self.injected_oracle = 0
            # -- adapter-boundary seams (the executor plane's chaos
            #    levers, keyed by qualified handler name) -------------
            # sleep added to every call on this handler's lane
            self.adapter_latency_s: dict[str, float] = {}
            # fail the next N calls on this handler's lane
            self.adapter_failures: dict[str, int] = {}
            # wedge: calls on this handler BLOCK until the event sets
            # (unwedge_adapter / reset releases them) — the bulkhead
            # and overrun paths' primary lever
            wedged = getattr(self, "_adapter_wedged", None)
            if wedged:
                for ev in wedged.values():
                    ev.set()   # release stuck workers before dropping
            self._adapter_wedged: dict[str, threading.Event] = {}
            self.injected_adapter = 0
            # -- quota-backend seams (memquota host lane, keyed by
            #    instance name) — the soak's "quota-backend stall" -----
            # sleep added to every handle_quota on this instance
            self.quota_latency_s: dict[str, float] = {}
            # fail the next N handle_quota calls on this instance
            self.quota_failures: dict[str, int] = {}
            self.injected_quota = 0
            # -- discovery-plane seam: sleep inserted at the top of
            #    DiscoveryService.publish (inside the publish lock, so
            #    the delay is a REAL push-pipeline stall) --------------
            self.discovery_push_delay_s = 0.0
            self.injected_discovery = 0
            # replay provenance: the seeded smokes stamp their --seed
            # here after reset() so /debug/resilience names the seed
            # any injected-fault run is replayable from
            self.seed: int | None = None

    def wedge_adapter(self, handler: str) -> None:
        """Every subsequent call on `handler`'s lane blocks until
        unwedge_adapter(handler) or reset()."""
        with self._lock:
            self._adapter_wedged.setdefault(handler, threading.Event())
        # chaos arms are control-plane events too: the forensics
        # smoke attributes a slow exemplar to the wedge that caused it
        from istio_tpu.runtime import forensics
        forensics.record_event("chaos_wedge", handler=handler)
        self._notify("wedge", handler=handler)

    def unwedge_adapter(self, handler: str) -> None:
        with self._lock:
            ev = self._adapter_wedged.pop(handler, None)
        if ev is not None:
            ev.set()
            from istio_tpu.runtime import forensics
            forensics.record_event("chaos_unwedge", handler=handler)

    def adapter_call(self, handler: str) -> None:
        """Called by the executor's lane worker immediately before a
        real adapter call — the adapter-boundary seam (latency, wedge,
        injected errors per handler). Inert fields cost two dict
        lookups per call."""
        ev = self._adapter_wedged.get(handler)
        if ev is not None:
            ev.wait()
        lat = self.adapter_latency_s.get(handler, 0.0)
        if lat:
            time.sleep(lat)
        if self.adapter_failures.get(handler, 0) <= 0:
            return
        with self._lock:
            n = self.adapter_failures.get(handler, 0)
            if n <= 0:
                return
            self.adapter_failures[handler] = n - 1
            self.injected_adapter += 1
        self._notify("adapter", handler=handler)
        raise RuntimeError(
            f"chaos: injected adapter failure ({handler})")

    def quota_call(self, name: str) -> None:
        """Called by MemQuotaHandler.handle_quota immediately before
        the real cell allocation — the quota-backend seam (stall
        latency + injected backend failures per instance name). Inert
        fields cost two dict lookups per quota. Latency-only arms do
        not notify the ledger (the device_latency_s precedent): a
        stall is not a fault, just tail pressure."""
        lat = self.quota_latency_s.get(name, 0.0)
        if lat:
            time.sleep(lat)
        if self.quota_failures.get(name, 0) <= 0:
            return
        with self._lock:
            n = self.quota_failures.get(name, 0)
            if n <= 0:
                return
            self.quota_failures[name] = n - 1
            self.injected_quota += 1
        self._notify("quota", handler=name)
        raise RuntimeError(
            f"chaos: injected quota-backend failure ({name})")

    def discovery_publish(self) -> None:
        """Called at the top of DiscoveryService.publish, inside the
        publish lock — an armed delay stalls the whole push pipeline
        (watchers stay parked on the old generation). Each delayed
        publish registers with the ledger; the expected evidence is
        the generation still advancing (the delayed push completed)."""
        lat = self.discovery_push_delay_s
        if not lat:
            return
        time.sleep(lat)
        with self._lock:
            self.injected_discovery += 1
        self._notify("discovery")

    def device_step(self) -> None:
        """Called immediately before a real check device step."""
        lat = self.device_latency_s
        if lat:
            time.sleep(lat)
        if self.device_failures <= 0:
            return
        with self._lock:
            if self.device_failures <= 0:
                return
            self.device_failures -= 1
            self.injected_device += 1
        self._notify("device")
        exc = self.device_exception
        raise exc() if exc is not None else \
            RuntimeError("chaos: injected device-step failure")

    def oracle_step(self) -> None:
        """Called before an oracle-fallback batch executes."""
        if self.oracle_failures <= 0:
            return
        with self._lock:
            if self.oracle_failures <= 0:
                return
            self.oracle_failures -= 1
            self.injected_oracle += 1
        self._notify("oracle")
        raise RuntimeError("chaos: injected oracle failure")

    def snapshot(self) -> dict:
        return {
            "device_failures_pending": self.device_failures,
            "oracle_failures_pending": self.oracle_failures,
            "device_latency_s": self.device_latency_s,
            "injected_device": self.injected_device,
            "injected_oracle": self.injected_oracle,
            "adapter_wedged": sorted(self._adapter_wedged),
            "adapter_latency_s": dict(self.adapter_latency_s),
            "adapter_failures_pending": dict(self.adapter_failures),
            "injected_adapter": self.injected_adapter,
            "quota_latency_s": dict(self.quota_latency_s),
            "quota_failures_pending": dict(self.quota_failures),
            "injected_quota": self.injected_quota,
            "discovery_push_delay_s": self.discovery_push_delay_s,
            "injected_discovery": self.injected_discovery,
            "seed": self.seed,
        }


# process-wide chaos seam: tests/scripts arm it, serving code probes it
CHAOS = ChaosHooks()


def _takes_deadline(fn: Callable) -> bool:
    """Does `fn` accept a `deadline` keyword? Decided once at wiring
    time (never per batch); unintrospectable callables answer False
    and are called (bags)-shaped."""
    import inspect

    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False
    if "deadline" in params:
        return True
    return any(p.kind is inspect.Parameter.VAR_KEYWORD
               for p in params.values())


class ResilientChecker:
    """Wraps the dispatcher's device check with retry, the circuit
    breaker, the CPU oracle fallback and the fail policy. This is
    RuntimeServer._run_check_batch's implementation — every serving
    entry (batcher, BatchCheck chunks, the native pump, check_many)
    rides it."""

    def __init__(self, device: Callable[[Sequence[Any]], Sequence[Any]],
                 oracle: Callable[[Sequence[Any]], Sequence[Any]],
                 config: ResilienceConfig | None = None,
                 chaos: ChaosHooks | None = None,
                 name: str = "device"):
        self.device = device
        self.oracle = oracle
        # deadline propagation (the adapter-executor plane): callables
        # that accept it get the batch's min remaining deadline so
        # host actions inherit the request budget; plain (bags)-shaped
        # callables (tests, legacy hooks) keep working
        self._device_takes_deadline = _takes_deadline(device)
        self._oracle_takes_deadline = _takes_deadline(oracle)
        self.config = config or ResilienceConfig()
        self.chaos = chaos if chaos is not None else CHAOS
        self.breaker = CircuitBreaker(self.config.breaker_failures,
                                      self.config.breaker_reset_s,
                                      name=name)

    def _n_real(self, bags: Sequence[Any]) -> int:
        from istio_tpu.runtime.batcher import trim_pads
        return len(trim_pads(list(bags)))

    def _device_call(self, bags: Sequence[Any],
                     deadline: float | None) -> Sequence[Any]:
        if self._device_takes_deadline:
            return self.device(bags, deadline=deadline)
        return self.device(bags)

    def run_batch(self, bags: Sequence[Any],
                  deadline: float | None = None) -> Sequence[Any]:
        from istio_tpu.runtime import monitor

        if not self.breaker.allow_device():
            return self._fallback(bags, "breaker_open",
                                  deadline=deadline)
        # every exit below must leave the breaker with a verdict
        # (success/failure) — or release the probe slot: an unwound
        # half-open probe with no verdict would wedge the breaker in
        # half_open and never try the device again
        recorded = False
        try:
            try:
                out = self._device_call(bags, deadline)
            except CheckRejected:
                raise           # typed rejections are answers, not faults
            except Exception as exc:
                first = exc
                if self.config.retry:
                    # one jittered retry absorbs transient device
                    # faults (a dropped tunnel frame, a preempted
                    # step) without involving the breaker
                    time.sleep(self.config.retry_backoff_s +  # hotpath: sync-ok failure-path backoff only
                               random.random() *
                               self.config.retry_jitter_s)
                    monitor.CHECK_DEVICE_RETRIES.inc()
                    try:
                        out = self._device_call(bags, deadline)
                    except CheckRejected:
                        raise
                    except Exception as exc2:
                        first = exc2
                    else:
                        self.breaker.record_success()
                        recorded = True
                        return out
                self.breaker.record_failure()
                recorded = True
                log.warning("device check batch failed (%s: %s); "
                            "serving via the CPU oracle path",
                            type(first).__name__, first)
                return self._fallback(bags, "device_error",
                                      deadline=deadline)
            self.breaker.record_success()
            recorded = True
            return out
        finally:
            if not recorded:
                self.breaker.release_probe()

    def _fallback(self, bags: Sequence[Any], reason: str,
                  deadline: float | None = None) -> Sequence[Any]:
        from istio_tpu.runtime import monitor

        n = self._n_real(bags)
        try:
            self.chaos.oracle_step()
            # the degraded path keeps the request's deadline when the
            # oracle callable takes one (check_host_oracle does) — a
            # wedged adapter must stay bounded even while the device
            # breaker routes batches host-side
            out = self.oracle(bags, deadline=deadline) \
                if self._oracle_takes_deadline else self.oracle(bags)
        except Exception as exc:
            if self.config.fail_policy == "open":
                # Mixer-client fail-open: policy outage must not take
                # the data plane down — answer OK, but with a 1s/1-use
                # TTL so sidecars re-check promptly instead of caching
                # the blanket allow for a normal success's 5s/10k uses
                # (the policy-bypass window must close with the outage)
                from istio_tpu.runtime.dispatcher import CheckResponse
                monitor.CHECK_FALLBACK.labels(reason="fail_open").inc(n)
                log.error("oracle fallback failed (%s: %s); policy is "
                          "fail-open, answering OK",
                          type(exc).__name__, exc)
                return [CheckResponse(valid_duration_s=1.0,
                                      valid_use_count=1)
                        for _ in range(n)]
            raise UnavailableError(
                f"device and oracle check paths both failed "
                f"({type(exc).__name__}: {exc})") from exc
        monitor.CHECK_FALLBACK.labels(reason=reason).inc(n)
        return out

    def snapshot(self) -> dict:
        """/debug/resilience payload fragment."""
        return {
            "breaker": self.breaker.snapshot(),
            "fail_policy": self.config.fail_policy,
            "retry": self.config.retry,
            "chaos": self.chaos.snapshot(),
        }
