"""Dispatcher — batched resolve + template/adapter fan-out.

Reference: mixer/pkg/runtime/dispatcher.go + resolver.go. Differences
by design (SURVEY.md §7 layer 4):

  * Resolution is BATCHED: one device ruleset evaluation matches a
    whole batch of requests against every rule (resolver.go's
    per-request per-rule IL loop collapses into the RuleSetProgram);
    host-fallback rules are overlaid per request.
  * Namespace targeting follows resolver.go:180 destAndNamespace — the
    identity attribute `destination.service` (svc.ns.suffix…) selects
    the rule namespace; default-namespace rules always apply.
  * The SERVING path is the fused device engine
    (models/policy_engine wired via runtime/fused): check verdicts,
    list/deny/rbac statuses, referenced bitmaps and report/quota
    activity bits come off one packed device step; only host-overlay
    actions (unfusable adapters) and host-fallback predicates run
    python per request. The generic path below (fused=None) keeps
    instance construction + adapter calls fully host-side and is the
    behavioral oracle. combineResults semantics preserved on both:
    lowest-rule-index non-OK status wins, TTLs take the min
    (dispatcher.go:322).
  * Adapter calls are panic-isolated (safeDispatch dispatcher.go:399):
    an adapter exception degrades that action to INTERNAL, never kills
    the request.
"""
from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from typing import Any, Mapping, Sequence

import numpy as np

from istio_tpu.adapters.sdk import (CheckResult, Handler, QuotaArgs,
                                    QuotaResult)
from istio_tpu.attribute.bag import Bag, MutableBag
from istio_tpu.expr.oracle import EvalError
from istio_tpu.models.policy_engine import INTERNAL, OK
from istio_tpu.runtime.config import Snapshot
from istio_tpu.runtime import monitor
from istio_tpu.templates import Variety

log = logging.getLogger("istio_tpu.runtime.dispatcher")

DEFAULT_IDENTITY_ATTR = "destination.service"


@dataclasses.dataclass
class CheckResponse:
    """Precondition result (CheckResponse.PreconditionResult)."""
    status_code: int = OK
    status_message: str = ""
    valid_duration_s: float = 5.0
    valid_use_count: int = 10_000
    referenced: tuple = ()
    # item → was-present, filled by the fused path from device planes so
    # ReferencedAttributes needs no host-side bag decode (None → the
    # gRPC layer falls back to bag lookups)
    referenced_presence: dict | None = None
    # QUOTA-variety rules active for this request (fused path only) —
    # lets the served quota loop skip the per-quota re-resolve
    # (runtime/device_quota.py). None → caller must resolve.
    active_quota_rules: tuple | None = None
    # the dispatcher that produced those indices: rule indices are
    # positional within ONE snapshot, so the quota loop must read the
    # same plan even if a config swap republished mid-request
    quota_context: Any = None
    # DEVICE deny attribution: the lowest-index rule whose fused check
    # action produced the non-OK device status (-1 when the device
    # answered OK — host-overlay adapters may still set a non-OK
    # status, which stays unattributed here). The canary recorder and
    # shadow replay (istio_tpu/canary) key their per-rule diff on it.
    deny_rule: int = -1


def _namespace_of(bag: Bag, identity_attr: str) -> str:
    """destAndNamespace (resolver.go:180): svc.ns.svc.cluster.local →
    'ns'; bare or absent destination → default namespace ''."""
    v, ok = bag.get(identity_attr)
    if not ok or not isinstance(v, str):
        return ""
    parts = v.split(".")
    return parts[1] if len(parts) >= 2 and parts[1] else ""


class Dispatcher:
    """Stateless over an immutable snapshot + built handler map; the
    controller swaps (snapshot, handlers) pairs atomically."""

    def __init__(self, snapshot: Snapshot, handlers: Mapping[str, Handler],
                 identity_attr: str = DEFAULT_IDENTITY_ATTR,
                 fused=None,
                 buckets: tuple[int, ...] = (),
                 recorder=None,
                 observe: bool = True,
                 executor=None,
                 grants=None,
                 overlap_h2d: bool = False):
        self.snapshot = snapshot
        self.handlers = dict(handlers)
        self.identity_attr = identity_attr
        # GrantPolicy (runtime/grants.py): when present, every check
        # response's valid_duration/valid_use_count is min-folded with
        # the namespace's volatility-derived grant at the respond
        # stage — the server-issued check-cache grant leg
        self.grants = grants
        self._ns_name_of: dict | None = None   # lazy rs.ns_ids inverse
        # begin the str_bytes h2d right after the C++ wire decode
        # (async device_put of the tier-narrowed plane from the pinned
        # staging buffers) so the dominant transfer overlaps the
        # host-side namespace extraction instead of serializing inside
        # the jitted call
        self.overlap_h2d = bool(overlap_h2d)
        # FusedPlan (runtime/fused.py) — when present, check() runs the
        # fused device engine and overlays only host-only actions
        self.fused = fused
        # AdapterExecutor (runtime/executor.py) — when present, the
        # fused path's host-overlay CHECK actions and quota() adapter
        # calls run on per-handler bulkhead lanes, deadline-bounded,
        # instead of inline on this thread. None (the generic path,
        # shadow replay, direct test construction) keeps the inline
        # safeDispatch loop — the behavioral oracle.
        self.executor = executor
        # canary TrafficRecorder (istio_tpu/canary/recorder.py): when
        # present, check batches tap their served decisions into the
        # sampling ring at this boundary — the same verdicts callers
        # receive, so a recorded decision is replayable evidence
        self.recorder = recorder
        # False = shadow-replay mode (istio_tpu/canary/replay.py): no
        # stage histograms, no e2e/live-p99 feeds, no rule-telemetry
        # folds, no chaos seam, no recorder tap — a canary replay must
        # not pollute the serving metrics it is judged against
        self.observe = observe
        # prewarmed serving batch shapes: device work OUTSIDE the
        # batcher (the fused report resolve) pads to these so arbitrary
        # arrival counts never compile in-band
        self.buckets = tuple(sorted(buckets))
        # any ATTRIBUTE_GENERATOR action configured? (when False the
        # server skips the per-request preprocess resolve entirely)
        self.has_apa = any(
            snapshot.actions_for(r, Variety.ATTRIBUTE_GENERATOR)
            for r in range(len(snapshot.rules)))

    def _handler_for(self, hc) -> Handler | None:
        """Built handler for a HandlerConfig (single home of the
        namespace-qualification rule, see config._qualify)."""
        from istio_tpu.runtime.config import _qualify
        return self.handlers.get(_qualify(hc.name, hc.namespace))

    # ------------------------------------------------------------------
    # resolution
    # ------------------------------------------------------------------

    def _grants_for_rows(self, ns_ids) -> list | None:
        """Per-row (ttl_s, use_count) from the grant policy — one
        policy round per DISTINCT namespace in the batch (uniform
        traffic: one or two lock acquisitions per batch). None when
        grants are off."""
        if self.grants is None:
            return None
        inv = self._ns_name_of
        if inv is None:
            inv = {v: k for k, v in
                   self.snapshot.ruleset.ns_ids.items()}
            self._ns_name_of = inv
        # ns_ids is the host-side id list built at tensorize time —
        # never a device buffer, so this asarray copies host memory
        uniq, inverse = np.unique(np.asarray(ns_ids),  # hotpath: sync-ok host id list
                                  return_inverse=True)
        gs = self.grants.grants_for(
            [inv.get(int(u), "") for u in uniq])
        return [gs[i] for i in inverse]

    def _apply_grants(self, bags: Sequence[Bag], responses) -> None:
        """Generic/oracle-path grant fold (per-bag namespace lookup —
        these paths are host-bound anyway). min() like every other
        TTL source: a grant only shortens a cache budget."""
        if self.grants is None:
            return
        for bag, resp in zip(bags, responses):
            ttl, uses = self.grants.grant(
                _namespace_of(bag, self.identity_attr))
            resp.valid_duration_s = min(resp.valid_duration_s, ttl)
            resp.valid_use_count = min(resp.valid_use_count, uses)

    def _request_ns_ids(self, bags: Sequence[Bag]) -> np.ndarray:
        return np.asarray([self.snapshot.ruleset.namespace_id(
            _namespace_of(bag, self.identity_attr)) for bag in bags],
            np.int32)

    def _ns_ids_from_batch(self, batch) -> np.ndarray:
        """destAndNamespace from the tensorized identity-attr column —
        the wire path extracts namespaces without decoding the bags."""
        rs = self.snapshot.ruleset
        slot = rs.layout.slots.get(self.identity_attr)
        n = batch.ids.shape[0]
        if slot is None:
            return np.zeros(n, np.int32)
        # hotpath: sync-ok — tensorizer output is host numpy
        ids = np.asarray(batch.ids[:, slot])      # hotpath: sync-ok
        present = np.asarray(batch.present[:, slot])  # hotpath: sync-ok
        interner = rs.interner
        out = np.zeros(n, np.int32)
        # vectorized over DISTINCT service ids — per-row python here
        # was O(B) work per batch on the batcher's only thread
        uniq, inverse = np.unique(ids, return_inverse=True)
        ns_of = np.zeros(uniq.shape[0], np.int32)
        for u, vid in enumerate(uniq):
            v = batch.value_of(int(vid), interner)
            parts = v.split(".") if isinstance(v, str) else []
            ns = parts[1] if len(parts) >= 2 and parts[1] else ""
            ns_of[u] = rs.namespace_id(ns)
        out = np.where(present, ns_of[inverse], 0).astype(np.int32)
        return out

    def _tensorize_for_device(self, bags: Sequence[Bag]):
        """(batch, ns_ids) via the C++ wire decoder when every bag
        carries wire bytes, else the python tensorizer."""
        plan = self.fused
        wires = [getattr(bag, "wire", None) for bag in bags]
        if plan.native is not None and all(w is not None
                                           for w in wires):
            batch = plan.native.tensorize_wire(wires)
            if self.overlap_h2d:
                # h2d begins NOW — the transfer runs while
                # _ns_ids_from_batch does its host-side decode
                batch = self._stage_h2d(plan, batch)
            ns_ids = self._ns_ids_from_batch(batch)
        else:
            batch = self.snapshot.tensorizer.tensorize(bags)
            ns_ids = self._request_ns_ids(bags)
        return batch, ns_ids

    @staticmethod
    def _stage_h2d(plan, batch):
        """Overlapped h2d from the pinned staging: narrow the byte
        plane to its serve tier FIRST (so the staged shape is exactly
        the compiled shape), then start the async device_put. The
        returned batch's str_bytes is a committed device array —
        packed_check's own narrow/transfer become no-ops for it. Fail-
        soft: any staging error serves the host-numpy batch as before."""
        import dataclasses as _dc

        import jax
        try:
            nb = plan.narrow_batch(batch)
            return _dc.replace(nb,
                               str_bytes=jax.device_put(nb.str_bytes))
        except Exception:
            return batch

    def _overlay_active(self, packed: np.ndarray, bags: Sequence[Bag],
                        ns_ids: np.ndarray, observe: bool = False
                        ) -> tuple[np.ndarray, dict]:
        """Decode the packed step's bitpacked overlay plane →
        (ns-masked active bits [len(bags), n_overlay_cols], rule idx →
        column position). Host-fallback rules' bits are oracle-patched;
        device + host resolve errors are accounted. `bags`/`ns_ids`
        must already be trimmed of padding rows. `observe`: feed
        host-fallback hits/errors into the rule-telemetry plane — set
        only by the CHECK path (the device accumulators can't see
        fallback rules, so their counts patch in here, exactly where
        their verdicts do)."""
        plan, rs = self.fused, self.snapshot.ruleset
        n_err = int(packed[4, 0]) if packed.shape[1] else 0
        if n_err and self.observe:   # replay mode: no counter feeds
            monitor.RESOLVE_ERRORS.inc(n_err)
        cols = plan.overlay_cols
        if not len(cols):
            return np.zeros((len(bags), 0), bool), {}
        from istio_tpu.runtime.fused import unpack_word_rows
        n_words = plan.n_ref_words
        n_ov_words = plan.n_overlay_words
        n_real = len(bags)
        active_sub = unpack_word_rows(
            packed[5 + n_words:5 + n_words + n_ov_words, :n_real],
            len(cols))
        col_pos = {int(r): i for i, r in enumerate(cols)}
        rns = rs.rule_ns[cols]
        ns_ok_sub = (rns[None, :] == rs.ns_ids[""]) | \
                    (rns[None, :] == ns_ids[:, None])
        host_errs = 0
        fb_cols: list[int] = []
        fb_pos: list[int] = []
        err_by_rule: dict[int, int] = {}
        for ridx in rs.host_fallback:
            pos = col_pos.get(ridx)
            if pos is None:   # rbac pseudo-rule row: no overlay col
                continue
            fb_cols.append(ridx)
            fb_pos.append(pos)
            vis_errs = 0
            # ONLY ns-visible (bag, rule) pairs are oracle-evaluated:
            # the ns mask below zeroes invisible bits regardless, so a
            # slow fallback predicate (attribute pulls, extern calls)
            # must never run for traffic that can never see its rule —
            # and the generic path's error accounting is (err & ns_ok),
            # so skipping keeps RESOLVE_ERRORS oracle-identical (it
            # over-counted invisible errors before)
            for b in np.nonzero(ns_ok_sub[:, pos])[0]:
                m, _, e = rs.host_eval(ridx, bags[b])
                active_sub[b, pos] = m
                if e:
                    vis_errs += 1
            if vis_errs:
                err_by_rule[ridx] = vis_errs
                host_errs += vis_errs
        if host_errs and self.observe:
            monitor.RESOLVE_ERRORS.inc(host_errs)
        active_sub &= ns_ok_sub
        tele = plan.telemetry
        if observe and tele is not None and (fb_cols or err_by_rule):
            tele.add_host(fb_cols, active_sub[:, fb_pos],
                          err_by_rule, tele.ns_slots(ns_ids))
        return active_sub, col_pos

    def _overlay_fallback(self, matched: np.ndarray, err: np.ndarray,
                          ns_ids: np.ndarray, bags: Sequence[Bag]
                          ) -> tuple[np.ndarray, np.ndarray]:
        """Patch host-fallback rules' verdicts into the device output and
        account namespace-visible errors; returns (active, ns_ok),
        clipped to config rules (ruleset rows past len(snapshot.rules)
        are rbac pseudo-rules — no actions behind them, and their errs
        are adapter-level, not resolve-level)."""
        rs = self.snapshot.ruleset
        n_cfg = len(self.snapshot.rules)
        for ridx in rs.host_fallback:
            if ridx >= n_cfg:
                continue
            for b, bag in enumerate(bags):
                m, _, e = rs.host_eval(ridx, bag)
                matched[b, ridx] = m
                err[b, ridx] = e
        matched = matched[:, :n_cfg]
        err = err[:, :n_cfg]
        # hotpath: sync-ok — generic path's designated ns-mask pull
        ns_ok = np.asarray(rs.namespace_mask(ns_ids))[:, :n_cfg]  # hotpath: sync-ok
        n_err = int((err & ns_ok).sum())   # hotpath: sync-ok (host numpy)
        if n_err:
            monitor.RESOLVE_ERRORS.inc(n_err)
        return matched & ns_ok, ns_ok

    def _resolve(self, bags: Sequence[Bag], observe: bool = False
                 ) -> tuple[list[list[int]], list[list[int]]]:
        """Batched rule matching → per-bag (active, namespace-visible)
        rule index lists. One device step for the whole batch; fallback
        + namespace masking applied host-side (cheap: bool arrays).
        `observe`: feed the CHECK stage histograms — only the check
        path sets it; report/quota/APA resolves share this code but
        must not pollute the Check() decomposition."""
        snap = self.snapshot
        if snap.ruleset.n_rules == 0:   # device arrays are padded to ≥1
            empty: list[list[int]] = [[] for _ in bags]
            return empty, [[] for _ in bags]
        with monitor.resolve_timer():
            t0 = time.perf_counter()
            batch = snap.tensorizer.tensorize(bags)
            t1 = time.perf_counter()
            if observe:
                monitor.observe_stage("tensorize", t1 - t0)
                # chaos seam at the generic path's device boundary
                # (check traffic only — observe gates out report/
                # quota/APA resolves), mirroring packed_check's
                from istio_tpu.runtime.resilience import CHAOS
                CHAOS.device_step()
            matched, _, err = snap.ruleset(batch)
            # hotpath: sync-ok — the generic path's designated pull
            matched = np.array(matched)    # hotpath: sync-ok
            err = np.array(err)            # hotpath: sync-ok
            if observe:
                monitor.observe_stage("device_step",
                                      time.perf_counter() - t1)
        ns_ids = self._request_ns_ids(bags)
        active, ns_ok = self._overlay_fallback(matched, err, ns_ids, bags)
        return ([list(np.nonzero(active[b])[0]) for b in range(len(bags))],
                [list(np.nonzero(ns_ok[b])[0]) for b in range(len(bags))])

    # ------------------------------------------------------------------
    # varieties
    # ------------------------------------------------------------------

    def check(self, bags: Sequence[Bag], instep: Any = None,
              pre_tensorized: Any = None,
              deadline: float | None = None) -> list[CheckResponse]:
        """`instep`: optional (q_arrays, counts, on_dispatch, on_pull)
        from an in-step quota session (device_quota.
        InlineQuotaSession) — the quota alloc rides the check
        program's trip; `on_dispatch(new_counts)` fires the moment
        the program is in flight (the session swaps the pool onto the
        device future and releases its token, letting the next trip
        chain on-device) and `on_pull(granted, gate)` right after the
        pull, before any per-row response python. `pre_tensorized`:
        (batch, ns_ids) computed by the caller (outside the token);
        must correspond to `bags` exactly. Both require the fused
        path. `deadline`: the batch's min remaining absolute
        perf_counter instant (threaded from the batcher) — host
        adapter actions inherit it via the executor plane; None =
        unbounded (plus any configured per-action timeout)."""
        if self.fused is not None:
            return self._check_fused(bags, instep=instep,
                                     pre_tensorized=pre_tensorized,
                                     deadline=deadline)
        actives, visibles = self._resolve(bags, observe=self.observe)
        t_respond = time.perf_counter()
        out = []
        for bag, rule_idxs, vis in zip(bags, actives, visibles):
            out.append(self._check_one(bag, rule_idxs, vis))
        self._apply_grants(bags, out)
        if self.observe:
            monitor.observe_stage("respond",
                                  time.perf_counter() - t_respond)
        # NO recorder tap here: the generic path's statuses include
        # host-adapter results the shadow replay (empty handlers,
        # device surface only) can never reproduce — a corpus recorded
        # on a non-fused server would diff as permanently divergent
        # against an identical config. Canary recording is fused-only,
        # like the replay itself.
        return out

    def _check_fused(self, bags: Sequence[Bag], instep: Any = None,
                     pre_tensorized: Any = None,
                     deadline: float | None = None
                     ) -> list[CheckResponse]:
        """Fused serving path: ONE device step computes rule matching +
        denier/list verdicts + TTLs for the whole batch; the host loop
        below only touches rules with non-fusable actions (and rules
        whose predicate fell back to the host oracle). Status merge is
        lowest-rule-index-wins on both sides, so host results from a
        lower rule index override the device candidate and vice versa —
        the two paths provably pick the same rule's status."""
        from istio_tpu.utils import tracing

        snap, plan = self.snapshot, self.fused
        tr = tracing.get_tracer()
        # real (non-padding) prefix length, known BEFORE the device
        # call: the telemetry fold masks padding rows on device, and
        # every host-side pass below runs on the real prefix only
        from istio_tpu.runtime.batcher import trim_pads
        n_real = len(trim_pads(bags))
        observe = self.observe
        bridged = False
        with (monitor.resolve_timer() if observe
              else contextlib.nullcontext()):
            if pre_tensorized is not None:
                batch, ns_ids = pre_tensorized
            else:
                t_tz = time.perf_counter()
                with tr.span("serve.tensorize", batch=len(bags)):
                    # C++ wire→tensor decode when possible: no
                    # per-request python work
                    batch, ns_ids = self._tensorize_for_device(bags)
                if observe:
                    monitor.observe_stage("tensorize",
                                          time.perf_counter() - t_tz)
            # swap-warm oracle bridge: while a background warm is
            # still compiling this shape's program (a config swap
            # deferred the shapes live traffic was NOT serving), the
            # batch serves through the CPU oracle — the new snapshot's
            # semantics apply immediately and no request pays the
            # in-band XLA trace. Serving path only (shadow replay
            # keeps the device surface; the in-step quota path has no
            # oracle equivalent and compiles through). Bridged
            # responses carry no device activity bits, so a quota
            # riding one falls back to the host adapter path.
            if observe and instep is None \
                    and plan.swap_warm_pending(batch):
                bridged = True
            else:
                # ONE device→host pull for the whole verdict: each
                # extra pull costs a full RTT (~120ms behind the axon
                # tunnel), and plane-by-plane conversion was 6 RTTs
                # per batch
                with tr.span("serve.device"):
                    if instep is not None:
                        t_d = time.perf_counter()
                        q_arrays, counts, on_dispatch, on_pull = instep
                        packed_dev, new_counts = \
                            plan.packed_check_instep(
                                batch, ns_ids, q_arrays, counts,
                                n_real=n_real)
                        # the program is IN FLIGHT: on_dispatch swaps
                        # the pool onto the device-future counters and
                        # drops the token, so the next trip chains
                        # on-device while this one's pull is still
                        # outstanding
                        on_dispatch(new_counts)
                        t_pull = time.perf_counter()
                        monitor.observe_stage("h2d", t_pull - t_d)
                        packed = np.asarray(packed_dev)   # the pull — hotpath: sync-ok
                        monitor.observe_stage(
                            "device_step",
                            time.perf_counter() - t_pull)
                        # granted/gate are the LAST two rows;
                        # everything the overlay decode reads sits
                        # before them
                        on_pull(packed[-2], packed[-1] != 0)
                    else:
                        packed = plan.packed_check(batch, ns_ids,
                                                   observe=observe,
                                                   n_real=n_real)
                status = packed[0]
                dur = packed[1].view(np.float32)
                uses = packed[2]
                deny_rule = packed[3]
        if bridged:
            return self.check_host_oracle(bags)
        t_overlay = time.perf_counter()
        rs = snap.ruleset

        # bucket-padding rows carry no caller: every host-side pass
        # below runs on the real prefix only (the batcher appends
        # PadBags at the tail and zips results against real requests)
        # — at small arrival rates a 512-bucket batch is mostly
        # padding, and per-row python here is the serving CPU budget
        bags = bags[:n_real]
        ns_ids = ns_ids[:n_real]

        # referenced-attribute item bits (rows 5..5+W): the device
        # computed predicate + instance attr uses per request; the
        # host just decodes set bits into names
        n_words = plan.n_ref_words
        if n_words:
            from istio_tpu.runtime.fused import unpack_word_rows
            ref_bits = unpack_word_rows(packed[5:5 + n_words, :n_real],
                                        len(plan.item_names))

        # Only plan.overlay_cols of the [B, R] matched plane are ever
        # inspected host-side (the rows after the ref bits);
        # converting the full plane (16MB/batch at B=2048, R=10k) was
        # the original serving bottleneck. Namespace masking for the
        # subset happens in numpy; host-fallback rules are
        # oracle-evaluated into their subset positions
        # (_overlay_active, shared with the fused report path).
        active_sub, col_pos = self._overlay_active(packed, bags, ns_ids,
                                                   observe=observe)
        # hotpath: sync-ok x2 — tensorizer planes are host numpy
        present_np = np.asarray(batch.present)[:n_real]        # hotpath: sync-ok
        map_present_np = np.asarray(batch.map_present)[:n_real]  # hotpath: sync-ok
        lay = rs.layout

        ha = plan.host_rule_idx
        ha_pos = np.asarray([col_pos[int(r)] for r in ha], np.int64)
        qa_rules = sorted({qa[0] for qa in plan.quota_actions})
        qa_pos = [col_pos[r] for r in qa_rules]

        # adapter-executor plane (runtime/executor.py): submit every
        # host action NOW, so adapter calls run on their handler
        # bulkhead lanes WHILE the fold below decodes the referenced/
        # presence planes — the response loop then claims results in
        # rule order, bounded by the request deadline. One list per
        # row, entries (rule idx, HostAction | final CheckResult) in
        # exactly the order the inline loop would have executed them,
        # so lowest-rule-index-wins merging is byte-identical.
        ex = self.executor
        host_pending: list[list] | None = None
        if ex is not None and len(ha):
            from istio_tpu.runtime.config import _qualify
            from istio_tpu.runtime.executor import check_fallback
            host_pending = []
            for b, bag in enumerate(bags):
                row: list = []
                for ridx in ha[active_sub[b, ha_pos]]:
                    ridx = int(ridx)
                    for hc, template, inst_names in \
                            plan.host_actions[ridx]:
                        handler = self._handler_for(hc)
                        if handler is None:
                            continue
                        hq = _qualify(hc.name, hc.namespace)
                        for iname in inst_names:
                            try:
                                instance = \
                                    snap.instances[iname].build(bag)
                            except EvalError as exc:
                                # instance build stays on this thread
                                # (_safe_check parity: EvalError →
                                # INTERNAL, counted as a dispatch
                                # error)
                                monitor.DISPATCH_ERRORS.inc()
                                row.append((ridx, CheckResult(
                                    status_code=INTERNAL,
                                    status_message=str(exc))))
                                continue
                            row.append((ridx, ex.submit(
                                hq,
                                self._bound_check(handler, template,
                                                  instance),
                                check_fallback)))
                host_pending.append(row)

        # Any exception from here to the claims must not leak
        # submitted-but-unclaimed actions: the conservation ledger
        # (submitted == resolved) is a smoke/bench gate, and a
        # ResilientChecker retry of this batch would re-submit
        # every action while the first generation dangled.
        try:
            # Referenced/presence construction deduplicated across the
            # batch: uniform traffic produces a handful of distinct
            # (referenced bits, presence bits) signatures, and building
            # the name tuples + presence dicts per ROW was milliseconds of
            # python per request — seconds per 2048-batch, single-threaded
            # in the batcher worker. Shared objects are read-only by
            # contract (the gRPC layer only serializes them).
            ref_of = None
            if n_words:
                signature = np.concatenate(
                    [ref_bits[:, :len(plan.item_names)],
                     present_np.astype(np.uint8),
                     map_present_np.astype(np.uint8),
                     active_sub.astype(np.uint8)], axis=1)
                uniq, inverse = np.unique(signature, axis=0,
                                          return_inverse=True)
                names = plan.item_names
                n_items = len(names)
                shared: list[tuple[tuple, dict]] = []
                for u in range(uniq.shape[0]):
                    row = uniq[u]
                    referenced = {names[j]
                                  for j in np.nonzero(row[:n_items])[0]}
                    act_row = row[n_items + present_np.shape[1] +
                                  map_present_np.shape[1]:]
                    for ridx, extra in plan.unmapped_instance_attrs.items():
                        if act_row[col_pos[ridx]]:
                            referenced |= extra
                    pres_row = row[n_items:n_items + present_np.shape[1]]
                    mp_row = row[n_items + present_np.shape[1]:
                                 n_items + present_np.shape[1] +
                                 map_present_np.shape[1]]
                    presence: dict = {}
                    for item in referenced:
                        if isinstance(item, tuple):
                            col = lay.derived_slots.get(item)
                            if col is not None:
                                presence[item] = bool(pres_row[col])
                        else:
                            col = lay.slots.get(item)
                            if col is not None:
                                presence[item] = bool(pres_row[col])
                            else:
                                mcol = lay.map_slots.get(item)
                                if mcol is not None:
                                    presence[item] = bool(mp_row[mcol])
                    shared.append((tuple(sorted(referenced, key=str)),
                                   presence))
                ref_of = [shared[i] for i in inverse]
            elif plan.unmapped_instance_attrs:
                # no layout items at all, but some rules still carry
                # instance attrs — merge them per row from the overlaid
                # activity bits (presence is unknowable without a layout)
                ref_of = []
                for b in range(n_real):
                    referenced: set = set()
                    for ridx, extra in plan.unmapped_instance_attrs.items():
                        if active_sub[b, col_pos[ridx]]:
                            referenced |= extra
                    ref_of.append((tuple(sorted(referenced, key=str)), {}))
            # fold = packed-plane decode (overlay bits, referenced/presence
            # signature dedup); respond = the per-row CheckResponse loop —
            # together they are the span the serve.overlay emit reports
            t_respond = time.perf_counter()
            if observe:
                monitor.observe_stage("fold", t_respond - t_overlay)
            # decision exemplars: denied/errored rows reservoir-sample into
            # the telemetry plane (host-side, post-fold, from the already-
            # decoded verdict) with the batch's active span so a
            # /debug/rulestats entry links to its RingReporter trace; the
            # canary recorder shares the span so its samples join traces
            tele = plan.telemetry if observe else None
            tele_span = tr._current() \
                if tele is not None or self.recorder is not None else None
            # server-issued check-cache grants: one (ttl, uses) pair
            # per distinct namespace, min-folded into every response
            # below (allow AND deny — a delta that flips a cached
            # DENY must revoke it too). The flight-recorder tape gets
            # the grant decision as its own stage (a post-revocation
            # policy stampede must be attributable).
            t_grant = time.perf_counter()
            grant_of = self._grants_for_rows(ns_ids)
            if observe and self.grants is not None:
                from istio_tpu.runtime import forensics
                forensics.RECORDER.stage_mark(
                    "grant", time.perf_counter() - t_grant)
            out = []
            for b, bag in enumerate(bags):
                resp = CheckResponse()
                resp.valid_duration_s = min(resp.valid_duration_s,
                                            float(dur[b]))
                resp.valid_use_count = min(resp.valid_use_count,
                                           int(uses[b]))
                dev_rule = int(deny_rule[b])
                dev_applied = False
                host_active = ha[active_sub[b, ha_pos]] if len(ha) else ()
                pend = host_pending[b] if host_pending is not None else None
                pi = 0
                for ridx in host_active:
                    ridx = int(ridx)
                    # ties at ridx == dev_rule follow the rule's config
                    # action order: if its first CHECK action is fused, the
                    # device result applies before the host actions
                    if not dev_applied and (
                            ridx > dev_rule or
                            (ridx == dev_rule and
                             dev_rule in plan.fused_first_rules)):
                        self._apply_device_status(resp, plan, dev_rule,
                                                  int(status[b]))
                        dev_applied = True
                    if pend is not None:
                        # executor path: CLAIM this rule's pre-submitted
                        # results (same order the submit pass appended
                        # them), each wait bounded by the batch deadline —
                        # an unresolved action folds as its fail-policy
                        # verdict, never a held batch
                        while pi < len(pend) and pend[pi][0] == ridx:
                            item = pend[pi][1]
                            pi += 1
                            result = item if isinstance(item, CheckResult) \
                                else ex.resolve(item, deadline)
                            self._combine(resp, result)
                        continue
                    for hc, template, inst_names in plan.host_actions[ridx]:
                        handler = self._handler_for(hc)
                        if handler is None:
                            continue
                        for iname in inst_names:
                            ib = snap.instances[iname]
                            result = self._safe_check(handler, template, ib,
                                                      bag)
                            self._combine(resp, result)
                if not dev_applied:
                    self._apply_device_status(resp, plan, dev_rule,
                                              int(status[b]))
                if status[b] != OK:
                    resp.deny_rule = dev_rule
                    if tele is not None:
                        tele.sample(dev_rule, int(status[b]), bag,
                                    tele_span)
                # referenced/presence: precomputed per unique signature
                if ref_of is not None:
                    resp.referenced, resp.referenced_presence = ref_of[b]
                if qa_rules:
                    resp.active_quota_rules = tuple(
                        r for r, p in zip(qa_rules, qa_pos)
                        if active_sub[b, p])
                    resp.quota_context = self
                else:
                    resp.active_quota_rules = ()
                if grant_of is not None:
                    g_ttl, g_uses = grant_of[b]
                    resp.valid_duration_s = min(resp.valid_duration_s,
                                                g_ttl)
                    resp.valid_use_count = min(resp.valid_use_count,
                                               g_uses)
                out.append(resp)
            if observe:
                monitor.observe_stage("respond",
                                      time.perf_counter() - t_respond)
                tr.emit("serve.overlay", time.perf_counter() - t_overlay,
                        batch=len(bags))
            if self.recorder is not None:
                # canary tap: bags/out are already padding-trimmed; one
                # stride check per batch, bounded appends for sampled rows
                # (istio_tpu/canary/recorder.py — off the device path).
                # The DEVICE planes are recorded, not the merged response:
                # the shadow replay compares device-decidable decisions
                # (host adapters never fire in shadow)
                self.recorder.tap(bags, out, snap, self.identity_attr,
                                  tele_span,
                                  device=(status, dur, uses, deny_rule))
            return out
        except BaseException:
            if host_pending is not None:
                for _row in host_pending:
                    for _ridx, _item in _row:
                        if not isinstance(_item, CheckResult):
                            ex.abandon(_item)
            raise

    @staticmethod
    def _apply_device_status(resp: CheckResponse, plan, dev_rule: int,
                             dev_status: int) -> None:
        """Merge the device verdict like one more adapter result."""
        if dev_status == OK:
            return
        if resp.status_code == OK:
            resp.status_code = dev_status
            resp.status_message = plan.message_for(dev_rule, dev_status)
        else:
            resp.status_message = (resp.status_message + "; " +
                                   plan.message_for(dev_rule, dev_status)
                                   ).strip("; ")

    def check_host_oracle(self, bags: Sequence[Bag],
                          deadline: float | None = None
                          ) -> list[CheckResponse]:
        """Graceful-degradation check path: resolve every rule on the
        CPU via the whole-snapshot oracle (compiler/ruleset.py
        SnapshotOracle) and run the generic host adapter loop — NO
        device step anywhere, so a tripped circuit breaker
        (runtime/resilience.py) can keep answering correctly while the
        device is down. Deliberately does not feed the stage
        decomposition: fallback latency is not serving latency, and
        attributing it to device_step/tensorize would corrupt the
        decomposition the SLO gauges are judged against (the e2e
        histogram still covers these requests via the batcher)."""
        from istio_tpu.runtime.batcher import trim_pads

        bags = trim_pads(list(bags))
        oracle = self._oracle()
        out: list[CheckResponse] = []
        n_err = 0
        for bag in bags:
            ns = _namespace_of(bag, self.identity_attr)
            active, visible, errs = oracle.resolve(bag, ns)
            n_err += errs
            out.append(self._check_one(bag, active, visible))
        self._apply_grants(bags, out)
        if n_err:
            monitor.RESOLVE_ERRORS.inc(n_err)
        return out

    def _oracle(self):
        """Lazily-built whole-snapshot oracle, cached per dispatcher
        (per snapshot: a config swap publishes a fresh Dispatcher).
        Seeded with the ruleset's host-fallback programs so those
        rules never recompile. Only CONFIG rules participate — ruleset
        rows past len(snapshot.rules) are rbac pseudo-rules whose
        actions live on their owning config rule."""
        cached = getattr(self, "_snapshot_oracle", None)
        if cached is None:
            from istio_tpu.compiler.ruleset import SnapshotOracle
            rs = self.snapshot.ruleset
            n_cfg = len(self.snapshot.rules)
            cached = SnapshotOracle(
                rs.rules[:n_cfg], self.snapshot.finder,
                seed={r: p for r, p in rs.host_fallback.items()
                      if r < n_cfg})
            self._snapshot_oracle = cached
        return cached

    def _check_one(self, bag: Bag, rule_idxs: list[int],
                   visible: list[int]) -> CheckResponse:
        snap = self.snapshot
        resp = CheckResponse()
        # ReferencedAttributes: every namespace-visible rule's predicate
        # was EVALUATED for this request (protoBag.go:117 tracking →
        # compile-time bitmaps, SURVEY.md §2.2); matched rules add their
        # instances' attribute uses below.
        referenced: set = set()
        for ridx in visible:
            referenced |= snap.ruleset.attr_names[ridx]
        for ridx in rule_idxs:
            for hc, template, inst_names in snap.actions_for(
                    ridx, Variety.CHECK):
                handler = self._handler_for(hc)
                if handler is None:
                    continue
                for iname in inst_names:
                    ib = snap.instances[iname]
                    referenced |= ib.referenced_attrs
                    result = self._safe_check(handler, template, ib, bag)
                    self._combine(resp, result)
        resp.referenced = tuple(sorted(referenced, key=str))
        return resp

    @staticmethod
    def _bound_check(handler: Handler, template: str,
                     instance) -> Any:
        """Zero-arg adapter call for the executor plane — the worker
        side of _safe_check's dispatch leg (same counter accounting;
        exceptions resolve via the executor's retry + safeDispatch
        INTERNAL path, runtime/executor.py)."""
        def call():
            # DISPATCH_ERRORS for a failing action is counted ONCE in
            # check_fallback's error branch (the resolve-side single
            # accounting home) — counting per attempt here would
            # double-bill retried calls relative to the inline path
            with monitor.dispatch_timer():
                return handler.handle_check(template, instance)
        return call

    def _safe_check(self, handler: Handler, template: str, ib,
                    bag: Bag) -> CheckResult:
        with monitor.dispatch_timer():
            try:
                instance = ib.build(bag)
            except EvalError as exc:
                monitor.DISPATCH_ERRORS.inc()
                return CheckResult(status_code=INTERNAL,
                                   status_message=str(exc))
            try:
                return handler.handle_check(template, instance)
            except Exception as exc:   # safeDispatch (dispatcher.go:399)
                monitor.DISPATCH_ERRORS.inc()
                log.exception("adapter check failed")
                return CheckResult(status_code=INTERNAL,
                                   status_message=f"adapter panic: {exc}")

    @staticmethod
    def _combine(resp: CheckResponse, r: CheckResult) -> None:
        """combineResults (dispatcher.go:322): worst status, min TTLs."""
        if not r.ok and resp.status_code == OK:
            resp.status_code = r.status_code
            resp.status_message = r.status_message
        elif not r.ok:
            resp.status_message = \
                f"{resp.status_message}; {r.status_message}".strip("; ")
        resp.valid_duration_s = min(resp.valid_duration_s,
                                    r.valid_duration_s)
        resp.valid_use_count = min(resp.valid_use_count,
                                   r.valid_use_count)

    def report(self, bags: Sequence[Bag]) -> None:
        from istio_tpu.runtime.batcher import trim_pads
        from istio_tpu.runtime.config import _qualify

        # defensive vs padded callers (BatchCheck-style fronts hand
        # bucket-shaped batches): padding rows carry no caller and
        # must not fire empty-match report rules
        bags = trim_pads(bags)
        if not bags:
            return
        fctx = None
        if self.fused is not None:
            if not self.fused.report_rules:
                return      # no REPORT rules configured: nothing to do
            # rows already contain ONLY active report-rule indices;
            # fctx carries device-built instance fields (VERDICT r4
            # item 3 — per-record expr eval off the host)
            actives, fctx = self._report_active_fused(bags)
        else:
            actives, _ = self._resolve(bags)
        rl = self.fused.report_lowering if self.fused is not None \
            else None
        observe = self.observe
        # adapter_dispatch accumulates ONLY handle_report wall time
        # (the documented stage semantics): host instance builds for
        # unlowerable instances run in this loop too and must not be
        # blamed on exporters — that ambiguity is what the
        # per-exporter accounting exists to remove
        adapter_s = 0.0
        tmpl_records: dict[str, int] = {}
        for b, (bag, rule_idxs) in enumerate(zip(bags, actives)):
            for ridx in rule_idxs:
                for hc, template, inst_names in self.snapshot.actions_for(
                        ridx, Variety.REPORT):
                    handler = self._handler_for(hc)
                    if handler is None:
                        continue
                    instances = []
                    for iname in inst_names:
                        if fctx is not None and iname in rl.specs:
                            inst = fctx.materialize(iname, b)
                            if inst is None:
                                # device-invalid field: the EvalError
                                # abort, same accounting as the host
                                monitor.DISPATCH_ERRORS.inc()
                                log.warning("instance %s: field "
                                            "evaluation failed", iname)
                            else:
                                instances.append(inst)
                            continue
                        try:
                            instances.append(
                                self.snapshot.instances[iname].build(bag))
                        except EvalError as exc:
                            monitor.DISPATCH_ERRORS.inc()
                            log.warning("instance %s: %s", iname, exc)
                    if instances:
                        t_h = time.perf_counter()
                        failed = False
                        with monitor.dispatch_timer():
                            try:
                                handler.handle_report(template, instances)
                            except Exception:
                                failed = True
                                monitor.DISPATCH_ERRORS.inc()
                                log.exception("adapter report failed")
                        adapter_s += time.perf_counter() - t_h
                        if observe:
                            # per-exporter delivery/drop/lag gauges
                            # (adapter-export backpressure accounting
                            # — a slow or throwing exporter must be
                            # attributable from /debug/report)
                            monitor.note_adapter_export(
                                _qualify(hc.name, hc.namespace),
                                template, len(instances),
                                time.perf_counter() - t_h,
                                error=failed)
                            if not failed:
                                tmpl_records[template] = \
                                    tmpl_records.get(template, 0) + \
                                    len(instances)
        if observe:
            if adapter_s > 0 or tmpl_records:
                monitor.observe_report_stage("adapter_dispatch",
                                             adapter_s)
            for template, n in tmpl_records.items():
                monitor.REPORT_TEMPLATE_RECORDS.inc(n,
                                                    template=template)

    def _report_active_fused(self, bags: Sequence[Bag]
                             ) -> tuple[list[list[int]], Any]:
        """Per-bag ACTIVE REPORT-rule indices via the fused packed
        step: one device pull of the bitpacked overlay plane instead of
        the full [B, R] matched plane + host ns-masking (the generic
        _resolve path cost ~90ms/RPC in [B, R] transfer alone at 10k
        rules behind the tunnel). Shares the check path's tensorize and
        overlay decode (incl. fallback patching, ns masking and
        resolve-error accounting). Record counts pad to the prewarmed
        serving bucket shapes, and oversize batches run in
        largest-bucket CHUNKS — arbitrary (client-controlled) report
        sizes must never compile a fresh XLA program in-band (the
        variable-shape pathology device_quota.py documents).

        When the snapshot's report instances lowered
        (plan.report_lowering), the SAME pull additionally carries
        every instance-field value/valid plane (packed_report); the
        returned ReportFieldCtx materializes finished instances so
        report() skips InstanceBuilder.build entirely for them."""
        from istio_tpu.runtime.batcher import pad_to_bucket
        from istio_tpu.runtime.report_lower import ReportFieldCtx

        plan = self.fused
        rl = plan.report_lowering
        fctx = ReportFieldCtx(rl, self.snapshot.ruleset.interner) \
            if rl is not None else None
        # field rows live after the head + ref-bit + overlay words
        # (FusedPlan.packed_report row layout)
        base = 5 + plan.n_ref_words + plan.n_overlay_words
        rcols = None
        cap = self.buckets[-1] if self.buckets else len(bags) or 1
        out: list[list[int]] = []
        observe = self.observe
        for lo in range(0, len(bags), cap):
            chunk = bags[lo:lo + cap]
            padded = pad_to_bucket(chunk, self.buckets) \
                if self.buckets else chunk
            with monitor.resolve_timer():
                t_tz = time.perf_counter()
                batch, ns_ids = self._tensorize_for_device(padded)
                t_dev = time.perf_counter()
                packed = plan.packed_report(batch, ns_ids) \
                    if rl is not None \
                    else plan.packed_check(batch, ns_ids,
                                           observe=False)
                t_done = time.perf_counter()
                if observe:
                    # report-pipeline stages, per chunk (the report
                    # analog of tensorize/h2d+device_step — the
                    # packed_report call carries dispatch AND pull)
                    monitor.observe_report_stage("tensorize",
                                                 t_dev - t_tz)
                    monitor.observe_report_stage("device_field_eval",
                                                 t_done - t_dev)
            active_sub, col_pos = self._overlay_active(
                packed, chunk,
                np.asarray(ns_ids)[:len(chunk)])  # hotpath: sync-ok (host ids)
            if rcols is None:
                rcols = [(ridx, col_pos[ridx])
                         for ridx in sorted(plan.report_rules)
                         if ridx in col_pos]
            t_dec = time.perf_counter()
            if fctx is not None:
                # skip the unique-id decode for chunks with no active
                # report rule anywhere — their planes are never read
                any_active = bool(rcols) and bool(   # hotpath: sync-ok
                    active_sub[:, [p for _, p in rcols]].any())
                fctx.add_chunk(packed, base, len(chunk), batch,
                               decode=any_active)
                if observe:
                    monitor.observe_report_stage(
                        "intern_decode",
                        time.perf_counter() - t_dec)
            out.extend(
                [ridx for ridx, pos in rcols if active_sub[b, pos]]
                for b in range(len(chunk)))
        if fctx is not None:
            fctx.seal()
        return out, fctx

    def quota(self, bag: Bag, quota_name: str,
              args: QuotaArgs,
              deadline: float | None = None) -> QuotaResult:
        """Dispatches to at most ONE handler (dispatcher.go:242-260).
        With an executor attached the adapter call runs on its handler
        lane (bulkheaded, deadline-bounded — the shared-quota backend
        may be a genuinely remote side effect); inline otherwise."""
        actives = self._resolve([bag])[0][0]
        for ridx in actives:
            for hc, template, inst_names in self.snapshot.actions_for(
                    ridx, Variety.QUOTA):
                for iname in inst_names:
                    if iname.split(".")[0] != quota_name and \
                            iname != quota_name:
                        continue
                    handler = self._handler_for(hc)
                    if handler is None:
                        continue
                    try:
                        instance = self.snapshot.instances[iname].build(bag)
                    except EvalError as exc:
                        monitor.DISPATCH_ERRORS.inc()
                        return QuotaResult(granted_amount=0,
                                           status_code=INTERNAL,
                                           status_message=str(exc))
                    except Exception as exc:
                        # safeDispatch parity: a malformed attribute
                        # value must degrade to a typed INTERNAL
                        # denial, never fail the whole RPC untyped
                        monitor.DISPATCH_ERRORS.inc()
                        log.exception("quota instance build failed")
                        return QuotaResult(granted_amount=0,
                                           status_code=INTERNAL,
                                           status_message=str(exc))
                    ex = self.executor
                    if ex is not None:
                        from istio_tpu.runtime.config import _qualify
                        from istio_tpu.runtime.executor import \
                            quota_fallback
                        amount = args.quota_amount
                        act = ex.submit(
                            _qualify(hc.name, hc.namespace),
                            self._bound_quota(handler, template,
                                              instance, args),
                            lambda policy, reason, _a=amount:
                                quota_fallback(policy, reason, _a))
                        return ex.resolve(act, deadline)
                    try:
                        with monitor.dispatch_timer():
                            return handler.handle_quota(template, instance,
                                                        args)
                    except Exception as exc:
                        monitor.DISPATCH_ERRORS.inc()
                        log.exception("adapter quota failed")
                        return QuotaResult(granted_amount=0,
                                           status_code=INTERNAL,
                                           status_message=str(exc))
        # no matching quota rule: grant freely (reference returns empty)
        return QuotaResult(granted_amount=args.quota_amount)

    @staticmethod
    def _bound_quota(handler: Handler, template: str, instance,
                     args: QuotaArgs) -> Any:
        def call():
            with monitor.dispatch_timer():
                return handler.handle_quota(template, instance, args)
        return call

    def preprocess(self, bag: Bag) -> Bag:
        """APA phase (dispatcher.go:285): run ATTRIBUTE_GENERATOR
        actions, bind outputs into a child bag."""
        actives = self._resolve([bag])[0][0]
        child = MutableBag(parent=bag)
        for ridx in actives:
            for hc, template, inst_names in self.snapshot.actions_for(
                    ridx, Variety.ATTRIBUTE_GENERATOR):
                handler = self._handler_for(hc)
                if handler is None:
                    continue
                for iname in inst_names:
                    ib = self.snapshot.instances[iname]
                    try:
                        instance = ib.build(bag)
                        outputs = handler.generate_attributes(template,
                                                              instance)
                    except EvalError as exc:
                        monitor.DISPATCH_ERRORS.inc()
                        log.warning("APA %s: %s", iname, exc)
                        continue
                    except Exception:
                        monitor.DISPATCH_ERRORS.inc()
                        log.exception("APA adapter failed")
                        continue
                    bindings = getattr(ib, "attribute_bindings", None)
                    if bindings:
                        for attr, ref in bindings.items():
                            key = str(ref).removeprefix("$out.")
                            if key in outputs:
                                child.set(attr, outputs[key])
                    else:
                        for key, value in outputs.items():
                            child.set(key.replace("_", "."), value)
        return child
