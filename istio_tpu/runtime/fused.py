"""Fused serving plan — wire the PolicyEngine into the check path.

The reference server assembles the same runtime it benchmarks
(mixer/pkg/server/server.go:92); this module is that assembly step for
the TPU build: given a validated Snapshot, extract every CHECK action
the fused device step can absorb (denier → DenySpec, id-exact string
lists → ListEntrySpec) and build one PolicyEngine per snapshot —
REUSING the snapshot's compiled RuleSetProgram, so a config swap pays
rule compilation once. Everything that cannot lower (rbac/opa/apikey
handlers, regex/CIDR/case-insensitive lists, refreshable list
providers, rules whose predicate fell back to the host oracle) is
collected into `host_actions` for the dispatcher to overlay per
request.

Quota is deliberately NOT fused on the serving path: the gRPC quota
loop (grpcServer.go:188-230) requires dedup-id replay semantics, which
live in the host memquota adapter. The engine's device quota path
remains the flagship all-device benchmark step.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import numpy as np

from istio_tpu.models.policy_engine import (DenySpec, ListEntrySpec,
                                            PolicyEngine, OK,
                                            PERMISSION_DENIED)
from istio_tpu.runtime.config import Snapshot
from istio_tpu.templates import Variety
from istio_tpu.utils.log import scope

log = scope("runtime.fused")

_FUSABLE_LIST_TYPES = ("STRINGS",)


@dataclasses.dataclass
class FusedPlan:
    """Per-snapshot serving plan: device engine + host overlay map."""
    engine: PolicyEngine
    # rule idx → CHECK actions the device cannot absorb (same tuples as
    # Snapshot.actions_for); host-fallback rules carry ALL their actions
    host_actions: dict[int, list]
    host_rule_idx: np.ndarray          # sorted keys of host_actions
    # per rule: attrs referenced by its CHECK instances (generic-path
    # ReferencedAttributes parity: active rules add instance attr uses)
    instance_attrs: list[frozenset]
    deny_info: dict[int, tuple[int, str]]   # rule → (code, message)
    list_rules: frozenset
    # C++ wire→tensor decoder (istio_tpu/native); None when the
    # toolchain is unavailable — python Tensorizer serves instead
    native: Any = None
    # rules whose FIRST check action is fused — device status wins ties
    # against host-overlay actions of the same rule (config action order)
    fused_first_rules: frozenset = frozenset()
    # the only rule columns the host ever inspects per request: rules
    # with host-overlay actions, host-fallback predicates, or non-empty
    # instance attribute sets. The dispatcher converts JUST these
    # columns of the [B, R] matched plane — at 10k rules the full-plane
    # copy was the serving bottleneck.
    overlay_cols: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64))
    fused_deny: int = 0
    fused_lists: int = 0
    _ns_pred_cache: dict = dataclasses.field(default_factory=dict)

    def pred_attrs_for_ns(self, ns_id: int) -> frozenset:
        """Union of predicate attr uses over rules visible to ns_id —
        every visible rule's predicate is evaluated for the request
        (protoBag.go:117 tracking → compile-time bitmaps)."""
        cached = self._ns_pred_cache.get(ns_id)
        if cached is not None:
            return cached
        rs = self.engine.ruleset
        default = rs.ns_ids[""]
        out: set = set()
        for ridx in range(rs.n_rules):
            if rs.rule_ns[ridx] == default or rs.rule_ns[ridx] == ns_id:
                out |= rs.attr_names[ridx]
        frozen = frozenset(out)
        self._ns_pred_cache[ns_id] = frozen
        return frozen

    def prewarm(self, buckets) -> None:
        """Trace/compile the engine step for every serving batch shape.

        Called by the controller BEFORE the atomic dispatcher swap
        (SURVEY hard-part #5; resolver refcount-swap semantics,
        mixer/pkg/runtime/resolver.go:240-247): the old snapshot keeps
        serving while the new one's jit cache fills, so no request pays
        multi-second trace time in-band after a config change."""
        import jax
        from istio_tpu.compiler.layout import AttributeBatch

        lay = self.engine.ruleset.layout
        for b in sorted(set(buckets)):
            batch = AttributeBatch(
                ids=np.zeros((b, lay.n_columns), np.int32),
                present=np.zeros((b, lay.n_columns), bool),
                map_present=np.zeros((b, max(lay.n_maps, 1)), bool),
                str_bytes=np.zeros((b, max(lay.n_byte_slots, 1),
                                    lay.max_str_len), np.uint8),
                str_lens=np.zeros((b, max(lay.n_byte_slots, 1)),
                                  np.int32))
            verdict = self.engine.check(batch, np.zeros(b, np.int32))
            jax.block_until_ready(verdict.status)

    def message_for(self, rule_idx: int, status: int) -> str:
        """Best-effort status message for a device-produced denial."""
        info = self.deny_info.get(rule_idx)
        if info is not None and info[0] == status:
            return info[1]
        if rule_idx in self.list_rules:
            name = self.engine.ruleset.rules[rule_idx].name
            return f"rejected by list check (rule {name})"
        return "denied by policy"


def build_fused_plan(snapshot: Snapshot) -> FusedPlan | None:
    """Extract fusable CHECK actions and build the snapshot's engine."""
    rs = snapshot.ruleset
    if rs.n_rules == 0:
        return None
    layout = rs.layout

    deny_by_rule: dict[int, DenySpec] = {}
    deny_info: dict[int, tuple[int, str]] = {}
    lists: list[ListEntrySpec] = []
    list_rules: set[int] = set()
    host_actions: dict[int, list] = {}
    instance_attrs: list[frozenset] = []

    def add_host(ridx: int, action) -> None:
        host_actions.setdefault(ridx, []).append(action)

    fused_first: set[int] = set()
    for ridx in range(rs.n_rules):
        attrs: set = set()
        for pos, action in enumerate(
                snapshot.actions_for(ridx, Variety.CHECK)):
            hc, template, inst_names = action
            for iname in inst_names:
                attrs |= snapshot.instances[iname].referenced_attrs
            if ridx in rs.host_fallback:
                # device matched==False for fallback rules; their fused
                # contributions would be inert — run everything on host
                add_host(ridx, action)
                continue
            if hc.adapter == "denier":
                if pos == 0:
                    fused_first.add(ridx)
                code = int(hc.params.get("status_code", PERMISSION_DENIED))
                msg = str(hc.params.get("status_message", "denied"))
                dur = float(hc.params.get("valid_duration_s", 5.0))
                uses = int(hc.params.get("valid_use_count", 10_000))
                prev = deny_by_rule.get(ridx)
                if prev is None:
                    deny_by_rule[ridx] = DenySpec(
                        rule=ridx, status=code, valid_duration_s=dur,
                        valid_use_count=uses)
                    deny_info[ridx] = (code, msg)
                else:   # merged denier actions: first status, min TTLs
                    deny_by_rule[ridx] = DenySpec(
                        rule=ridx, status=prev.status,
                        valid_duration_s=min(prev.valid_duration_s, dur),
                        valid_use_count=min(prev.valid_use_count, uses))
                continue
            if hc.adapter == "list" and template == "listentry":
                fused, host = _split_list_instances(
                    snapshot, hc, inst_names, layout)
                if pos == 0 and fused and not host:
                    fused_first.add(ridx)
                for iname, value_attr in fused:
                    lists.append(ListEntrySpec(
                        rule=ridx, value_attr=value_attr,
                        entries=list(hc.params.get("overrides", ())),
                        blacklist=bool(hc.params.get("blacklist", False)),
                        valid_duration_s=float(
                            hc.params.get("caching_ttl_s", 300.0)),
                        valid_use_count=int(
                            hc.params.get("caching_use_count", 10_000))))
                    list_rules.add(ridx)
                if host:
                    add_host(ridx, (hc, template, host))
                continue
            add_host(ridx, action)
        instance_attrs.append(frozenset(attrs))

    engine = PolicyEngine(ruleset=rs, finder=snapshot.finder,
                          deny=list(deny_by_rule.values()), lists=lists,
                          quotas=(), jit=True)
    native = None
    try:
        from istio_tpu.native.tensorizer import NativeTensorizer
        native = NativeTensorizer(rs.layout, rs.interner)
    except Exception as exc:   # toolchain missing → python tensorize
        log.warning("native tensorizer unavailable, serving with the "
                    "python wire decoder: %s", exc)
    log.info("fused plan: %d deny rules, %d lists, %d host-overlay rules"
             ", native=%s", len(deny_by_rule), len(lists),
             len(host_actions), native is not None)
    overlay = set(host_actions) | set(rs.host_fallback) | \
        {r for r in range(rs.n_rules) if instance_attrs[r]}
    return FusedPlan(engine=engine, native=native,
                     host_actions=host_actions,
                     host_rule_idx=np.asarray(sorted(host_actions),
                                              np.int64),
                     instance_attrs=instance_attrs,
                     deny_info=deny_info,
                     list_rules=frozenset(list_rules),
                     fused_first_rules=frozenset(fused_first),
                     overlay_cols=np.asarray(sorted(overlay), np.int64),
                     fused_deny=len(deny_by_rule), fused_lists=len(lists))


def _split_list_instances(snapshot: Snapshot, hc, inst_names, layout
                          ) -> tuple[list, list]:
    """(fused [(iname, value_attr)], host [iname]) for a list action.

    Fusable: case-sensitive exact-string lists from static overrides
    whose instance value is a bare attribute reference with a layout
    slot. CIDR/regex/case-insensitive entries and refreshable providers
    keep list.go's host semantics (mixer/adapter/list/list.go:115-247).
    """
    p: Mapping[str, Any] = hc.params
    if (p.get("entry_type", "STRINGS") not in _FUSABLE_LIST_TYPES
            or p.get("provider") is not None
            or p.get("provider_url")):
        return [], list(inst_names)
    if not all(isinstance(e, str) for e in p.get("overrides", ())):
        return [], list(inst_names)
    fused, host = [], []
    for iname in inst_names:
        ref = snapshot.instances[iname].value_attr_ref()
        slot_ok = ref is not None and (
            ref in layout.derived_slots if isinstance(ref, tuple)
            else ref in layout.slots)
        if slot_ok:
            fused.append((iname, ref))
        else:
            host.append(iname)
    return fused, host
