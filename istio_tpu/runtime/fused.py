"""Fused serving plan — wire the PolicyEngine into the check path.

The reference server assembles the same runtime it benchmarks
(mixer/pkg/server/server.go:92); this module is that assembly step for
the TPU build: given a validated Snapshot, extract every CHECK action
the fused device step can absorb (denier → DenySpec, id-exact string
lists → ListEntrySpec) and build one PolicyEngine per snapshot —
REUSING the snapshot's compiled RuleSetProgram, so a config swap pays
rule compilation once. Everything that cannot lower (rbac/opa/apikey
handlers, regex/CIDR/case-insensitive lists, refreshable list
providers, rules whose predicate fell back to the host oracle) is
collected into `host_actions` for the dispatcher to overlay per
request.

Quota IS on the served device path: QUOTA-variety actions are wired
into `quota_actions`, the check step's activity bits say which quota
rules matched each request (no re-resolve), and allocations ride the
per-handler device counter pools in runtime/device_quota.py — a host
dedup-replay cache in front (memquota.go:259 buildWithDedup
semantics), batched device scatter-add allocation behind it. The host
memquota adapter remains the fallback for non-memquota quota handlers
and the generic (non-fused) dispatch path.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Mapping

import numpy as np

from istio_tpu.models.policy_engine import (DenySpec, INTERNAL,
                                            ListEntrySpec, PolicyEngine,
                                            OK, PERMISSION_DENIED,
                                            RbacSpec)
from istio_tpu.runtime.config import Snapshot
from istio_tpu.templates import Variety
from istio_tpu.utils.log import scope

log = scope("runtime.fused")

_FUSABLE_LIST_TYPES = ("STRINGS", "REGEX", "IP_ADDRESSES")

# latency-tier byte-plane width: batches whose every string fits this
# many bytes serve through a str_bytes plane sliced to it (see
# FusedPlan.narrow_batch) — the worst-case max_str_len plane is paid
# only by batches that actually carry long strings
STR_TIER_MIN = 32


def str_tiers(layout, interner=None) -> tuple[int, ...]:
    """Byte-plane length tiers for a snapshot: (STR_TIER_MIN, L) when
    the layout carries real byte slots wider than the small tier, else
    the single full width. Each tier is one extra jit trace per bucket
    (prewarmed like buckets are), bought back on every easy batch: the
    H2D bytes and every full-width byte op (prefix/suffix/exact
    compares, lex_cmp) shrink L/STR_TIER_MIN-fold.

    `interner`: the snapshot's InternTable, consulted AFTER every
    program compiled (its max_byte_const_len is grow-only). A tier
    below the longest compiled byte CONSTANT is unsound — narrowing
    slices constant rows, and a constant longer than the tier loses
    real tail bytes (e.g. the subject of `"long...".endsWith(attr)`),
    flipping verdicts the runtime str_lens check cannot catch — so the
    small tier only exists when every constant fits it."""
    L = layout.max_str_len
    min_safe = STR_TIER_MIN
    if interner is not None:
        min_safe = max(min_safe,
                       int(getattr(interner, "max_byte_const_len", 0)))
    if layout.n_byte_slots and L > min_safe:
        return (min_safe, L)
    return (L,)


def pack_bool_rows(flags, n_words: int):
    """[B, n_words*32] bool → int32 word rows [n_words, B]: THE wire
    convention for every bitpacked plane of the packed pull (ref bits,
    overlay bits, report-field valid bits) — little-endian bit order
    within each 32-bit word, transposed so words stack as rows. Device
    side; `unpack_word_rows` is the host inverse."""
    import jax.numpy as jnp
    from jax import lax

    b = flags.shape[0]
    bit_w = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    words = jnp.sum(flags.reshape(b, n_words, 32).astype(jnp.uint32)
                    * bit_w[None, None, :], axis=2)
    return lax.bitcast_convert_type(words, jnp.int32).T


def unpack_word_rows(rows: np.ndarray, n_bits: int) -> np.ndarray:
    """Host inverse of pack_bool_rows: int32 word rows [W, B] (a slice
    of the packed pull) → bool [B, n_bits]."""
    return np.unpackbits(
        np.ascontiguousarray(rows.T).view(np.uint8), axis=1,
        bitorder="little")[:, :n_bits].astype(bool)


@dataclasses.dataclass
class FusedPlan:
    """Per-snapshot serving plan: device engine + host overlay map."""
    engine: PolicyEngine
    # rule idx → CHECK actions the device cannot absorb (same tuples as
    # Snapshot.actions_for); host-fallback rules carry ALL their actions
    host_actions: dict[int, list]
    host_rule_idx: np.ndarray          # sorted keys of host_actions
    # per rule: attrs referenced by its CHECK instances (generic-path
    # ReferencedAttributes parity: active rules add instance attr uses)
    instance_attrs: list[frozenset]
    deny_info: dict[int, tuple[int, str]]   # rule → (code, message)
    list_rules: frozenset
    # rules whose rbac action is fused (device pseudo-rule NFA,
    # compiler/rbac_lower.py) — for status messages + diagnostics
    rbac_rules: frozenset = frozenset()
    # why list actions stayed host-side, e.g. "CASE_INSENSITIVE_STRINGS",
    # "provider-refreshed", "REGEX:unsupported-pattern" (bench
    # enumeration of the unfusable envelope)
    unfused_list_kinds: tuple = ()
    # rules carrying REPORT-variety actions: their activity bits ride
    # overlay_cols so dispatcher.report reads ONE bitpacked pull
    # instead of the full [B, R] matched plane (r4)
    report_rules: frozenset = frozenset()
    # QUOTA-variety wiring for the served quota loop
    # (grpcServer.go:188-230): [(rule idx, handler qname, instance
    # qname, accepted quota names)] in rule order. The rules' activity
    # bits ride overlay_cols so the gRPC quota loop never re-resolves
    # (runtime/device_quota.py).
    quota_actions: tuple = ()
    # C++ wire→tensor decoder (istio_tpu/native); None when the
    # toolchain is unavailable — python Tensorizer serves instead
    native: Any = None
    # rules whose FIRST check action is fused — device status wins ties
    # against host-overlay actions of the same rule (config action order)
    fused_first_rules: frozenset = frozenset()
    # the only rule columns the host ever inspects per request: rules
    # with host-overlay actions, host-fallback predicates, or non-empty
    # instance attribute sets. The dispatcher converts JUST these
    # columns of the [B, R] matched plane — at 10k rules the full-plane
    # copy was the serving bottleneck.
    overlay_cols: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64))
    fused_deny: int = 0
    fused_lists: int = 0
    # referenced-attribute items: item j < n_columns is the column's
    # slot/derived attr, item n_columns + m is map slot m's attr name.
    # The device computes the FULL per-request referenced bitmap
    # (predicate attrs of ns-visible rules + instance attrs of active
    # rules) and ships it bitpacked — at 10k rules the host-side
    # per-request set unions and the [B, R] overlay pull were the
    # serving bottleneck behind the tunnel (~5MB/batch at ~4MB/s).
    item_names: list = dataclasses.field(default_factory=list)
    inst_mask: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0, 0), np.int8))
    pred_map_mask: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0, 0), np.int8))
    # rule → instance attrs with no layout item (rare); such rules stay
    # in overlay_cols and their names merge host-side
    unmapped_instance_attrs: dict = dataclasses.field(default_factory=dict)
    _ns_pred_cache: dict = dataclasses.field(default_factory=dict)
    _packer: Any = None
    # compiled REPORT instance-field programs (runtime/report_lower.py)
    # — None when no report instance lowered; the dispatcher then keeps
    # the host InstanceBuilder.build for every instance
    report_lowering: Any = None
    # on-device per-rule hit/deny/err accumulators + exemplar
    # reservoirs (runtime/rulestats.RuleTelemetry) — None when rule
    # telemetry is disabled (ServerArgs.rule_telemetry=False / bench
    # off-phase). Folded by packed_check/packed_check_instep on check
    # batches only; drained off the hot path by the aggregator.
    telemetry: Any = None
    _report_packer: Any = None
    _instep_packer: Any = None
    # byte-plane length tiers (str_tiers(layout)): serving batches whose
    # strings all fit the small tier compile/serve at the sliced shape
    str_tiers: tuple = ()
    # observed-check tier usage: byte-plane width actually served →
    # batch count (GIL-atomic int bumps; /debug/roofline judges the
    # live device_step p50 against the dominant width, not the
    # worst-case max_str_len plane)
    _tier_served: dict = dataclasses.field(default_factory=dict)
    # observed-check (bucket rows, byte width) → batch count: the
    # shapes live traffic actually serves. A config swap warms THESE
    # synchronously pre-swap (swap latency scales with what traffic
    # uses, not the full bucket × tier product) and defers the rest to
    # a post-swap background warm.
    _shape_served: dict = dataclasses.field(default_factory=dict)
    # (bucket rows, byte width) pairs whose serving programs are
    # compiled (prewarm dummies and organic trips both register)
    _warmed_shapes: set = dataclasses.field(default_factory=set)
    # a background warm is still filling _warmed_shapes: batches at
    # missing shapes bridge to the host oracle (Dispatcher._check_fused)
    # instead of tracing in-band
    _warm_pending: bool = False
    # completed prewarm_instep (buckets, counts-shape) combinations
    _instep_warmed: set = dataclasses.field(default_factory=set)

    @property
    def n_ref_words(self) -> int:
        return (len(self.item_names) + 31) // 32

    def narrow_batch(self, batch):
        """Latency-tier bucket specialization (byte-plane axis): when
        every string in the batch fits the small tier, slice str_bytes
        to it so the engine step + packer run (and prewarm) a tighter
        XLA shape instead of riding the max_str_len worst case.
        Verdict-identical by construction: sliced lanes are zero
        padding past every row's length, and the truncation contract
        compares str_lens against layout.max_str_len — which narrowing
        never changes (a row truncated at ingest has len == max_str_len
        and keeps the full-width shape). Host-side numpy only."""
        w = self._serve_width(batch)   # single home of tier routing
        if not isinstance(batch.str_bytes, np.ndarray) \
                or w >= int(batch.str_bytes.shape[2]):
            return batch
        return dataclasses.replace(
            batch,
            str_bytes=np.ascontiguousarray(batch.str_bytes[:, :, :w]))

    @property
    def n_overlay_words(self) -> int:
        return (len(self.overlay_cols) + 31) // 32

    def packed_check(self, batch, ns_ids, observe: bool = True,
                     n_real: int | None = None) -> np.ndarray:
        """engine.check + device-side packing into ONE int32 array
        [5 + W + C, B] pulled with a single host↔device sync (W =
        n_ref_words, C = len(overlay_cols)). Pulling plane-by-plane
        costs one ~100ms tunnel RTT per plane, and the unpacked
        referenced/overlay planes cost seconds of D2H streaming.

        Rows: 0 status, 1 valid_duration_s (f32 bits), 2
        valid_use_count, 3 deny_rule, 4 err_count (broadcast),
        5..5+W referenced-item bits (little-endian within each int32),
        then matched[:, overlay_cols] BITPACKED the same way (raw,
        ns-unmasked) — a 1k-column overlay plane shipped as int32 was
        8 MB/batch of D2H, ~1.6 s behind the tunnel.

        `n_real`: count of non-padding rows (the leading prefix);
        rows past it are bucket padding the rule-telemetry fold must
        ignore. None = every row is real."""
        import jax

        from istio_tpu.runtime import monitor

        batch = self.narrow_batch(batch)   # latency-tier byte plane
        if self._packer is None:
            self._packer = jax.jit(self._base_packer())
        if observe:
            w = int(batch.str_bytes.shape[2])
            self._tier_served[w] = self._tier_served.get(w, 0) + 1
            key = (int(batch.ids.shape[0]), w)
            self._shape_served[key] = self._shape_served.get(key, 0) + 1
            # fault-injection seam at the device boundary (chaos suite
            # + scripts/chaos_smoke.py): an injected exception here
            # unwinds exactly like a real device-step failure. Gated
            # on observe so prewarm dummy trips and the fused report
            # fallback never trip the breaker.
            from istio_tpu.runtime.resilience import CHAOS
            CHAOS.device_step()
        # h2d = host->device staging + async program dispatch;
        # device_step = the blocking pull (execution + D2H transfer,
        # carries the transport RTT). Together they decompose the trip
        # the serve.device span reports as one number. `observe=False`
        # for non-Check callers (prewarm dummy batches — a compile
        # would dwarf every real observation — and the fused report
        # fallback): only check trips feed the Check() decomposition.
        t0 = time.perf_counter()
        verdict = self.engine.check(batch, ns_ids)
        ns_arr = np.asarray(ns_ids)        # hotpath: sync-ok (host ids)
        if observe and self.telemetry is not None:
            # per-rule hit/deny/err fold into the resident device
            # accumulators — async dispatch only, the drain thread
            # pays the pull. Check traffic only (observe gates out
            # prewarm dummies and the fused report fallback).
            b = ns_arr.shape[0]
            real = np.arange(b) < (b if n_real is None else n_real)
            self.telemetry.observe(verdict, ns_arr, real)
        dev = self._packer(verdict, ns_arr)
        t1 = time.perf_counter()
        # the single host<->device sync — hotpath: sync-ok
        out = np.asarray(dev)              # hotpath: sync-ok
        # this (bucket, width) shape's programs are compiled now —
        # the swap-warm oracle bridge stops routing it away
        self._warmed_shapes.add((int(batch.ids.shape[0]),
                                 int(batch.str_bytes.shape[2])))
        if observe:
            monitor.observe_stage("h2d", t1 - t0)
            monitor.observe_stage("device_step",
                                  time.perf_counter() - t1)
        return out

    def _base_packer(self):
        """The pack(verdict, req_ns) closure shared by packed_check and
        packed_report (which appends report-field planes)."""
        import jax.numpy as jnp
        from jax import lax

        from istio_tpu.ops.bytes_ops import pack_bits, unpack_bits
        rs = self.engine.ruleset
        cols = jnp.asarray(self.overlay_cols, jnp.int32)
        rule_ns = jnp.asarray(rs.rule_ns)
        default_ns = rs.ns_ids[""]
        # instance/predicate-map literal masks ride bit-packed and
        # unpack to int8 on device per step (pack_bits discipline —
        # one bit of information per cell, 1/8 the resident int8 bytes)
        inst_bits = jnp.asarray(pack_bits(self.inst_mask))
        pred_map_bits = jnp.asarray(pack_bits(self.pred_map_mask))
        n_items = len(self.item_names)
        n_words = self.n_ref_words
        n_cols = rs.layout.n_columns
        n_maps_used = self.pred_map_mask.shape[1]
        dims = (((1,), (0,)), ((), ()))

        def pack(verdict, req_ns):
            b = verdict.status.shape[0]
            dur_bits = lax.bitcast_convert_type(
                verdict.valid_duration_s, jnp.int32)
            head = jnp.stack([
                verdict.status, dur_bits, verdict.valid_use_count,
                verdict.deny_rule,
                jnp.broadcast_to(verdict.err_count.astype(jnp.int32),
                                 (b,))])
            parts = [head]
            if n_items:
                ns_ok = (rule_ns[None, :] == default_ns) | \
                        (rule_ns[None, :] == req_ns[:, None])
                active = verdict.matched & ns_ok
                items = jnp.zeros((b, n_words * 32), bool)
                # predicate columns: the engine already ns-masks
                # (referenced is [B, max(n_cols, 1)] — slice off
                # the 0-column placeholder when the layout is empty)
                items = items.at[:, :n_cols].set(
                    verdict.referenced[:, :n_cols])
                if n_maps_used:
                    pred_map_j = unpack_bits(
                        pred_map_bits, n_maps_used).astype(jnp.int8)
                    pred_maps = lax.dot_general(
                        ns_ok.astype(jnp.int8), pred_map_j, dims,
                        preferred_element_type=jnp.int32) > 0
                    items = items.at[
                        :, n_cols:n_cols + n_maps_used].set(
                            items[:, n_cols:n_cols + n_maps_used]
                            | pred_maps)
                inst_mask_j = unpack_bits(
                    inst_bits, n_items).astype(jnp.int8)
                inst = lax.dot_general(
                    active.astype(jnp.int8), inst_mask_j, dims,
                    preferred_element_type=jnp.int32) > 0
                items = items.at[:, :n_items].set(
                    items[:, :n_items] | inst)
                parts.append(pack_bool_rows(items, n_words))
            if cols.size:
                ov = jnp.take(verdict.matched, cols, axis=1)
                n_ov_words = (cols.shape[0] + 31) // 32
                ov_pad = jnp.zeros((b, n_ov_words * 32), bool)
                ov_pad = ov_pad.at[:, :cols.shape[0]].set(ov)
                parts.append(pack_bool_rows(ov_pad, n_ov_words))
            return jnp.concatenate(parts, axis=0) \
                if len(parts) > 1 else head

        return pack

    def packed_report(self, batch, ns_ids,
                      observe: bool = True) -> np.ndarray:
        """packed_check's rows PLUS the report instance-field planes in
        the SAME single device pull (VERDICT r4 item 3 — one RTT per
        report batch, never one per plane): after the overlay words
        come F int32 value rows (intern ids; 0/1 for BOOL fields) and
        ceil(F/32) bitpacked field-valid words, F =
        report_lowering.n_fields. Falls back to packed_check when no
        instance lowered. `observe=False` for prewarm dummy trips —
        they must not feed the served-shape set below."""
        if self.report_lowering is None or \
                self.report_lowering.n_fields == 0:
            # zero field programs (e.g. reportnothing-only): the check
            # rows alone serve; ReportFieldCtx slices empty planes.
            # observe=False: this is REPORT traffic — it must not feed
            # the Check() stage decomposition. Narrow ONCE and pass
            # the narrowed batch down (packed_check's own narrow then
            # early-returns — no second byte-plane copy).
            batch = self.narrow_batch(batch)
            if observe:
                key = (int(batch.ids.shape[0]),
                       int(batch.str_bytes.shape[2]))
                self._shape_served[key] = \
                    self._shape_served.get(key, 0) + 1
            return self.packed_check(batch, ns_ids, observe=False)
        import jax

        batch = self.narrow_batch(batch)   # latency-tier byte plane
        # report traffic feeds the served-shape set too: the pre-swap
        # warm must cover the shapes the report coalescer serves (its
        # packer compiles per shape like the check packer's), or the
        # first post-swap report trip pays an in-band trace — there is
        # no oracle bridge on the report path
        if observe:
            key = (int(batch.ids.shape[0]),
                   int(batch.str_bytes.shape[2]))
            self._shape_served[key] = \
                self._shape_served.get(key, 0) + 1
        if self._report_packer is None:
            import jax.numpy as jnp
            pack = self._base_packer()
            rl = self.report_lowering
            n_f = rl.n_fields
            n_w = rl.n_valid_words

            def packr(verdict, req_ns, fbatch):
                head = pack(verdict, req_ns)
                vals, valid = rl.field_planes(fbatch)
                b = vals.shape[1]
                vpad = jnp.zeros((b, n_w * 32), bool)
                vpad = vpad.at[:, :n_f].set(valid.T)
                return jnp.concatenate(
                    [head, vals, pack_bool_rows(vpad, n_w)], axis=0)

            self._report_packer = jax.jit(packr)
        verdict = self.engine.check(batch, ns_ids)
        return np.asarray(                 # hotpath: sync-ok (the pull)
            self._report_packer(
                verdict,
                np.asarray(ns_ids),        # hotpath: sync-ok (host ids)
                batch))

    def packed_check_instep(self, batch, ns_ids, q: Mapping[str, Any],
                            counts,
                            n_real: int | None = None) -> tuple[Any, Any]:
        """packed_check's rows PLUS an IN-STEP quota allocation in the
        SAME device program — the quota-carrying batch pays ONE trip
        instead of check-trip + pool-flush-trip serialized on the
        transport (the bench's no-quota windows measure ~2x the mixed
        rate for exactly this reason).

        Narrowed to the batch's byte tier like packed_check.

        `q` carries the staged per-row alloc arrays from
        device_quota.InlineQuotaSession (buckets/amounts/be/mx/active/
        ticks/lasts/rolling, plus rule_idx — the ruleset row whose
        ns-masked matched bit gates the alloc by zeroing its amount;
        the roll runs for every staged row). `counts` is the pool's
        counter buffer; returns DEVICE handles (packed, new_counts) —
        packed's last TWO rows are granted and gate once pulled."""
        import jax

        batch = self.narrow_batch(batch)   # latency-tier byte plane
        if n_real is None or n_real > 0:   # prewarm dummies pass 0
            w = int(batch.str_bytes.shape[2])
            self._tier_served[w] = self._tier_served.get(w, 0) + 1
            key = (int(batch.ids.shape[0]), w)
            self._shape_served[key] = self._shape_served.get(key, 0) + 1
        if self._instep_packer is None:
            import jax.numpy as jnp
            from istio_tpu.models.quota_alloc import \
                make_rolling_alloc_step
            pack = self._base_packer()
            rs = self.engine.ruleset
            rule_ns = jnp.asarray(rs.rule_ns)
            default_ns = rs.ns_ids[""]
            n_buckets, k_ticks = counts.shape
            # the general contended-mixed kernel unconditionally: the
            # fast/unit variants are host-selected shape optimizations
            # the merged program cannot branch on
            seg = make_rolling_alloc_step(int(n_buckets), int(k_ticks),
                                          jit=False)[3]

            def packq(verdict, req_ns, cnt, buckets, amounts, be, mx,
                      active, ticks, lasts, rolling, rule_idx):
                head = pack(verdict, req_ns)
                rows = jnp.arange(buckets.shape[0])
                safe_rule = jnp.clip(rule_idx, 0,
                                     rule_ns.shape[0] - 1)
                rn = rule_ns[safe_rule]
                ns_ok = (rn == default_ns) | (rn == req_ns)
                # the reference's quota loop runs ONLY on successful
                # checks (grpcServer.go:188) — a denied row must not
                # consume. Device status IS the final status here:
                # instep_quota_target refuses snapshots with host
                # overlay actions or host-fallback predicates. The
                # gate zeroes AMOUNTS (consume nothing) while the ROLL
                # runs for every STAGED row — the session's optimistic
                # host tick bookkeeping depends on rolls being
                # unconditional (chained-trip staging).
                gate = active & (rule_idx >= 0) & ns_ok & \
                    (verdict.status == 0) & \
                    verdict.matched[rows, safe_rule]
                amt = jnp.where(gate, amounts, 0)
                granted, new_cnt = seg(cnt, buckets, amt, be, mx,
                                       active, ticks, lasts, rolling)
                extra = jnp.stack([granted.astype(jnp.int32),
                                   gate.astype(jnp.int32)])
                return jnp.concatenate([head, extra], axis=0), new_cnt

            self._instep_packer = jax.jit(packq)
        verdict = self.engine.check(batch, ns_ids)
        ns_arr = np.asarray(ns_ids)        # hotpath: sync-ok (host ids)
        if self.telemetry is not None:
            # in-step quota batches ARE check traffic — same per-rule
            # fold as packed_check (prewarm_instep passes n_real=0 so
            # its dummy trips fold all-masked, counting nothing)
            b = ns_arr.shape[0]
            real = np.arange(b) < (b if n_real is None else n_real)
            self.telemetry.observe(verdict, ns_arr, real)
        # DEVICE handles, not host arrays: the caller swaps the pool
        # onto new_counts at dispatch (the next trip chains on-device)
        # and pulls `packed` with the counter token already released
        out = self._instep_packer(
            verdict,
            ns_arr,
            counts,
            q["buckets"], q["amounts"], q["be"], q["mx"], q["active"],
            q["ticks"], q["lasts"], q["rolling"], q["rule_idx"])
        self._warmed_shapes.add((int(batch.ids.shape[0]),
                                 int(batch.str_bytes.shape[2])))
        return out

    def pred_attrs_for_ns(self, ns_id: int) -> frozenset:
        """Union of predicate attr uses over rules visible to ns_id —
        every visible rule's predicate is evaluated for the request
        (protoBag.go:117 tracking → compile-time bitmaps)."""
        cached = self._ns_pred_cache.get(ns_id)
        if cached is not None:
            return cached
        rs = self.engine.ruleset
        default = rs.ns_ids[""]
        out: set = set()
        for ridx in range(rs.n_rules):
            if rs.rule_ns[ridx] == default or rs.rule_ns[ridx] == ns_id:
                out |= rs.attr_names[ridx]
        frozen = frozenset(out)
        self._ns_pred_cache[ns_id] = frozen
        return frozen

    def cache_stats(self) -> dict:
        """Compiled-program cache occupancy per packer (one entry per
        warmed bucket shape) — the /debug/cache payload's compile-cache
        half. A serving bucket missing here means the next batch at
        that shape pays an in-band XLA trace."""
        out: dict[str, Any] = {}
        for name in ("_packer", "_report_packer", "_instep_packer"):
            f = getattr(self, name, None)
            if f is None:
                continue
            size = getattr(f, "_cache_size", None)
            out[name.lstrip("_") + "_entries"] = \
                int(size()) if callable(size) else None
        out["ns_pred_cache_entries"] = len(self._ns_pred_cache)
        return out

    def prewarm(self, buckets, should_stop=None, backoff=None) -> None:
        """Trace/compile the engine step for every serving batch shape.

        Called by the controller BEFORE the atomic dispatcher swap
        (SURVEY hard-part #5; resolver refcount-swap semantics,
        mixer/pkg/runtime/resolver.go:240-247): the old snapshot keeps
        serving while the new one's jit cache fills, so no request pays
        multi-second trace time in-band after a config change.

        `should_stop`: zero-arg callable polled between shapes — the
        controller's BACKGROUND initial prewarm passes its shutdown
        flag so a closing server never leaves a daemon thread compiling
        into interpreter teardown (C++ abort on exit). `backoff`: see
        warm_shapes."""
        self.warm_shapes(self.all_warm_shapes(buckets),
                         should_stop=should_stop, backoff=backoff)

    def all_warm_shapes(self, buckets) -> list:
        """Every (bucket rows, byte tier) pair narrow_batch can route
        a served batch to — the full shape product prewarm compiles."""
        lay = self.engine.ruleset.layout
        tiers = sorted(set(self.str_tiers or (lay.max_str_len,)))
        return [(b, t) for b in sorted(set(buckets)) for t in tiers]

    def served_shapes(self) -> set:
        """(bucket rows, byte width) pairs live traffic has actually
        served through this plan — the pre-swap warm priority set."""
        return set(self._shape_served)

    def map_served_shapes(self, buckets, served) -> list:
        """Old plan's observed (bucket, width) pairs mapped onto THIS
        plan's warmable (bucket, tier) pairs (width → smallest tier
        that holds it). Empty/unmappable `served` returns the full
        product — the conservative first-swap behavior."""
        pairs = self.all_warm_shapes(buckets)
        if not served:
            return pairs
        tiers = sorted({t for _, t in pairs})
        bset = {b for b, _ in pairs}
        out: list = []
        for b, w in sorted(served):
            if b not in bset:
                continue
            t = next((t for t in tiers if t >= w), tiers[-1])
            if (b, t) not in out:
                out.append((b, t))
        return out or pairs

    def warm_shapes(self, pairs, should_stop=None,
                    backoff=None) -> None:
        """Compile the SERVING entry (engine step + packer — the
        packer gather is its own XLA program — plus the report packer
        when report instances lowered) for each (bucket, byte-tier)
        pair. `backoff` is called between shapes: the config-swap path
        passes a serving-latency yield (controller._serving_backoff)
        so a loaded single core keeps serving while this thread traces
        jaxprs — the warm yields to traffic, never the reverse."""
        from istio_tpu.runtime import forensics
        for b, tier in pairs:
            if should_stop is not None and should_stop():
                return
            # mesh event timeline: prewarm start/end per shape — the
            # compile whose GIL hold a swap-window p99 spike blames
            forensics.record_event("prewarm", shape=f"{b}x{tier}",
                                   phase="start")
            t_w0 = time.perf_counter()
            batch = self._dummy_batch(b, tier)
            self.packed_check(batch, np.zeros(b, np.int32),
                              observe=False)
            if self.report_lowering is not None and \
                    self.report_rules:
                self.packed_report(batch, np.zeros(b, np.int32),
                                   observe=False)
            forensics.record_event(
                "prewarm", shape=f"{b}x{tier}", phase="end",
                wall_ms=round((time.perf_counter() - t_w0) * 1e3, 1))
            if backoff is not None:
                backoff()

    def begin_warm(self) -> None:
        """A warm phase is running (or queued) for this plan: serving
        batches at not-yet-compiled shapes bridge to the host oracle
        (Dispatcher._check_fused) instead of tracing in-band. Pair
        with end_warm() in a finally — a plan left warm-pending would
        oracle-serve its missing shapes forever."""
        self._warm_pending = True

    def end_warm(self) -> None:
        self._warm_pending = False

    def swap_warm_pending(self, batch) -> bool:
        """True while a warm is still filling this batch's (bucket,
        byte-tier) program slot — the dispatcher then serves the batch
        through the CPU oracle: the new snapshot's semantics apply
        immediately and no request pays the in-band XLA trace."""
        if not self._warm_pending:
            return False
        b = int(batch.ids.shape[0])
        return (b, self._serve_width(batch)) not in self._warmed_shapes

    def _serve_width(self, batch) -> int:
        """The byte-plane width this batch serves at — THE tier-routing
        decision (narrow_batch slices to it, swap_warm_pending keys on
        it; one implementation so the two can never drift). Host numpy
        only."""
        w = int(batch.str_bytes.shape[2])
        tiers = self.str_tiers
        if len(tiers) < 2 or not isinstance(batch.str_bytes, np.ndarray) \
                or not isinstance(batch.str_lens, np.ndarray):
            return w
        t = tiers[0]
        if w <= t or not batch.str_lens.size:
            return w
        m = int(batch.str_lens.max())   # hotpath: sync-ok (host numpy)
        return t if m <= t else w

    def _dummy_batch(self, b: int, tier: int):
        """Dummy AttributeBatch routed to exactly one byte-plane tier
        of bucket size `b`. The dummy MUST flatten to the same pytree
        treedef as served batches (hash_ids included) — a treedef
        mismatch compiles a cache entry serving never hits, silently
        un-doing the prewarm."""
        from istio_tpu.compiler.layout import AttributeBatch

        lay = self.engine.ruleset.layout
        tiers = sorted(set(self.str_tiers or (lay.max_str_len,)))
        # lens pinned AT the tier so narrow_batch routes the dummy to
        # exactly this tier's compiled shape (0 → small tier;
        # max_str_len → the full-width worst case)
        lens = 0 if tier == min(tiers) else tier
        return AttributeBatch(
            ids=np.zeros((b, lay.n_columns), np.int32),
            present=np.zeros((b, lay.n_columns), bool),
            map_present=np.zeros((b, max(lay.n_maps, 1)), bool),
            str_bytes=np.zeros((b, max(lay.n_byte_slots, 1),
                                lay.max_str_len), np.uint8),
            str_lens=np.full((b, max(lay.n_byte_slots, 1)),
                             lens, np.int32),
            hash_ids=np.zeros((b, lay.n_columns), np.int32))

    def _prewarm_batches(self, b: int) -> list:
        """Dummy AttributeBatches covering every byte-plane tier for
        bucket size `b` (prewarm_instep's shape walk)."""
        lay = self.engine.ruleset.layout
        tiers = sorted(set(self.str_tiers or (lay.max_str_len,)))
        return [self._dummy_batch(b, tier) for tier in tiers]

    def prewarm_instep(self, buckets, counts, should_stop=None) -> None:
        """Compile the in-step quota program for every serving bucket
        (ServerArgs.quota_in_step fronts call this before taking
        traffic — a first-quota-batch compile mid-serve stalls every
        row behind it; RuntimeServer wires it on every publish).
        `counts` only supplies the counter SHAPE; the dummy trips
        never touch the pool's live buffer. `should_stop` is polled
        between shapes like prewarm's — a closing server must be able
        to stop a background warm before interpreter teardown.

        Completed (buckets, counts-shape) combinations are memoized:
        the post-publish backstop re-invokes this after the pre-swap
        hook already warmed, and re-executing every bucket × tier
        dummy trip would contend with live traffic for the device."""
        import jax.numpy as jnp

        key = (tuple(sorted(set(buckets))), tuple(counts.shape))
        if key in self._instep_warmed:
            return
        zero_counts = jnp.zeros_like(counts)
        for b in sorted(set(buckets)):
            for batch in self._prewarm_batches(b):
                if should_stop is not None and should_stop():
                    return
                q = {"buckets": np.zeros(b, np.int32),
                     "amounts": np.zeros(b, np.int32),
                     "be": np.zeros(b, bool),
                     "mx": np.zeros(b, np.int32),
                     "active": np.zeros(b, bool),
                     "ticks": np.zeros(b, np.int32),
                     "lasts": np.zeros(b, np.int32),
                     "rolling": np.zeros(b, bool),
                     "rule_idx": np.full(b, -1, np.int32)}
                packed, _cnt = self.packed_check_instep(
                    batch, np.zeros(b, np.int32), q, zero_counts,
                    n_real=0)   # dummies must not feed rule telemetry
                np.asarray(packed)   # force compile + execute
        # only a COMPLETED warm counts (not stopped)
        self._instep_warmed.add(key)

    def message_for(self, rule_idx: int, status: int) -> str:
        """Best-effort status message for a device-produced denial."""
        info = self.deny_info.get(rule_idx)
        if info is not None and info[0] == status:
            return info[1]
        if rule_idx in self.rbac_rules:
            if status == PERMISSION_DENIED:
                return "RBAC: permission denied"   # rbac.go:241
            if status == INTERNAL:
                return "authorization instance evaluation failed"
        if rule_idx in self.list_rules:
            if status == INTERNAL:
                # absent/malformed value: the host path's EvalError /
                # adapter-panic shape, not a membership rejection
                return "list instance evaluation failed"
            name = self.engine.ruleset.rules[rule_idx].name
            return f"rejected by list check (rule {name})"
        return "denied by policy"


def build_fused_plan(snapshot: Snapshot,
                     mesh=None,
                     rule_telemetry: bool = True) -> FusedPlan | None:
    """Extract fusable CHECK actions and build the snapshot's engine.

    `mesh` (jax.sharding.Mesh, dp×mp) re-jits the engine step under the
    multi-chip serving layout (parallel/mesh.py shard_engine_check):
    requests shard over dp, rule rows over mp, one psum on the verdict
    fold — the SAME serving path, scaled across chips.

    `rule_telemetry` wires per-rule hit/deny/err accumulators
    (runtime/rulestats.RuleTelemetry) into the packed check step."""
    rs = snapshot.ruleset
    if rs.n_rules == 0:
        return None
    layout = rs.layout

    deny_by_rule: dict[int, DenySpec] = {}
    deny_info: dict[int, tuple[int, str]] = {}
    lists: list[ListEntrySpec] = []
    list_rules: set[int] = set()
    unfused_kinds: set[str] = set()
    rbacs: list[RbacSpec] = []
    rbac_rules: set[int] = set()
    host_actions: dict[int, list] = {}
    instance_attrs: list[frozenset] = []
    # ruleset rows beyond the config rules are rbac pseudo-rules — they
    # carry no actions and never appear in overlays or host fallbacks
    n_real = len(snapshot.rules)

    def add_host(ridx: int, action) -> None:
        host_actions.setdefault(ridx, []).append(action)

    fused_first: set[int] = set()
    for ridx in range(n_real):
        attrs: set = set()
        for pos, action in enumerate(
                snapshot.actions_for(ridx, Variety.CHECK)):
            hc, template, inst_names = action
            for iname in inst_names:
                attrs |= snapshot.instances[iname].referenced_attrs
            if ridx in rs.host_fallback:
                # device matched==False for fallback rules; their fused
                # contributions would be inert — run everything on host
                add_host(ridx, action)
                continue
            if hc.adapter == "rbac" and template == "authorization":
                from istio_tpu.runtime.config import _qualify
                handler_ref = _qualify(hc.name, hc.namespace)
                fused_insts, host_insts = [], []
                for iname in inst_names:
                    g = snapshot.rbac_groups.get((handler_ref, iname))
                    if g is not None and g.lowered:
                        fused_insts.append((iname, g))
                    else:
                        host_insts.append(iname)
                if fused_insts and pos == 0 and not host_insts:
                    fused_first.add(ridx)
                for iname, g in fused_insts:
                    rbacs.append(RbacSpec(
                        rule=ridx, allow_rows=g.allow_rows,
                        guard_row=g.guard_row,
                        valid_duration_s=float(
                            hc.params.get("caching_ttl_s", 60.0))))
                    rbac_rules.add(ridx)
                if host_insts:
                    add_host(ridx, (hc, template, host_insts))
                continue
            if hc.adapter == "denier":
                if pos == 0:
                    fused_first.add(ridx)
                code = int(hc.params.get("status_code", PERMISSION_DENIED))
                msg = str(hc.params.get("status_message", "denied"))
                dur = float(hc.params.get("valid_duration_s", 5.0))
                uses = int(hc.params.get("valid_use_count", 10_000))
                prev = deny_by_rule.get(ridx)
                if prev is None:
                    deny_by_rule[ridx] = DenySpec(
                        rule=ridx, status=code, valid_duration_s=dur,
                        valid_use_count=uses)
                    deny_info[ridx] = (code, msg)
                else:   # merged denier actions: first status, min TTLs
                    deny_by_rule[ridx] = DenySpec(
                        rule=ridx, status=prev.status,
                        valid_duration_s=min(prev.valid_duration_s, dur),
                        valid_use_count=min(prev.valid_use_count, uses))
                continue
            if hc.adapter == "list" and template == "listentry":
                fused, host = _split_list_instances(
                    snapshot, hc, inst_names, layout, unfused_kinds)
                if pos == 0 and fused and not host:
                    fused_first.add(ridx)
                for iname, value_attr in fused:
                    lists.append(ListEntrySpec(
                        rule=ridx, value_attr=value_attr,
                        entries=list(hc.params.get("overrides", ())),
                        blacklist=bool(hc.params.get("blacklist", False)),
                        valid_duration_s=float(
                            hc.params.get("caching_ttl_s", 300.0)),
                        valid_use_count=int(
                            hc.params.get("caching_use_count", 10_000)),
                        entry_type=str(hc.params.get("entry_type",
                                                     "STRINGS"))))
                    list_rules.add(ridx)
                if host:
                    add_host(ridx, (hc, template, host))
                continue
            add_host(ridx, action)
        instance_attrs.append(frozenset(attrs))

    # QUOTA-variety actions: recorded (in rule order) so the served
    # quota loop can reuse the check step's activity bits instead of
    # re-resolving (dispatcher.quota dispatches to at most ONE handler,
    # matching by instance name — dispatcher.go:242-260)
    quota_actions: list = []
    quota_rules: set[int] = set()
    for ridx in range(n_real):
        for hc, template, inst_names in snapshot.actions_for(
                ridx, Variety.QUOTA):
            from istio_tpu.runtime.config import _qualify
            for iname in inst_names:
                names = frozenset({iname, iname.split(".")[0]})
                quota_actions.append(
                    (ridx, _qualify(hc.name, hc.namespace), iname,
                     names))
                quota_rules.add(ridx)

    engine = PolicyEngine(ruleset=rs, finder=snapshot.finder,
                          deny=list(deny_by_rule.values()), lists=lists,
                          quotas=(), rbacs=rbacs, jit=True,
                          count_rules=n_real)
    if mesh is not None:
        from istio_tpu.parallel.mesh import shard_engine_check
        engine._step = shard_engine_check(mesh, engine)
    native = None
    try:
        from istio_tpu.native.tensorizer import NativeTensorizer
        native = NativeTensorizer(rs.layout, rs.interner)
    except Exception as exc:   # toolchain missing → python tensorize
        log.warning("native tensorizer unavailable, serving with the "
                    "python wire decoder: %s", exc)
    log.info("fused plan: %d deny rules, %d lists, %d rbac actions "
             "(%d pseudo-rules), %d host-overlay rules, native=%s",
             len(deny_by_rule), len(lists), len(rbacs),
             rs.n_rules - n_real, len(host_actions), native is not None)

    # referenced-attribute item space: every layout column (slot or
    # derived) plus every map slot. Instance attrs that map to an item
    # flow through the device bitmap; the rare unmappable ones keep
    # their rule in the host overlay.
    n_cols, n_maps = layout.n_columns, layout.n_maps
    item_names: list = [None] * (n_cols + n_maps)
    item_of: dict = {}
    for name, col in layout.slots.items():
        item_names[col] = name
        item_of[name] = col
    for pair, col in layout.derived_slots.items():
        item_names[col] = pair
        item_of[pair] = col
    for name, mcol in layout.map_slots.items():
        item_names[n_cols + mcol] = name
        item_of[name] = n_cols + mcol
    n_items = len(item_names)
    n_rows = int(rs.rule_ns.shape[0])   # incl. mp-sharding padding
    inst_mask = np.zeros((n_rows, n_items), np.int8)
    unmapped: dict[int, frozenset] = {}
    for ridx, attrs in enumerate(instance_attrs):
        if ridx in rs.host_fallback:
            # the device never knows whether a host-fallback rule
            # matched — its instance attrs merge host-side from the
            # oracle-overlaid activity bits
            if attrs:
                unmapped[ridx] = attrs
            continue
        missing = []
        for item in attrs:
            idx = item_of.get(item)
            if idx is None:
                missing.append(item)
            else:
                inst_mask[ridx, idx] = 1
        if missing:
            unmapped[ridx] = frozenset(missing)
    # predicate MAP-name uses (e.g. `ar["k"]` references "ar" too) —
    # the engine's referenced plane covers columns only
    pred_map_mask = np.zeros((n_rows, max(n_maps, 1)), np.int8)
    for ridx in range(rs.n_rules):
        for item in rs.attr_names[ridx]:
            if isinstance(item, str) and item in layout.map_slots:
                pred_map_mask[ridx, layout.map_slots[item]] = 1

    report_rules = {ridx for ridx in range(n_real)
                    if snapshot.actions_for(ridx, Variety.REPORT)}
    report_lowering = None
    if report_rules:
        try:
            from istio_tpu.runtime.report_lower import \
                build_report_lowering
            report_lowering = build_report_lowering(snapshot)
        except Exception:
            log.exception("report lowering failed; report instances "
                          "build on host")
    real_fallback = {r for r in rs.host_fallback if r < n_real}
    overlay = set(host_actions) | real_fallback | set(unmapped) \
        | quota_rules | report_rules
    telemetry = None
    if rule_telemetry:
        try:
            from istio_tpu.runtime.rulestats import RuleTelemetry
            telemetry = RuleTelemetry(rs, n_real)
        except Exception:
            log.exception("rule telemetry unavailable; serving "
                          "without per-rule accumulators")
    return FusedPlan(engine=engine, native=native,
                     telemetry=telemetry,
                     # AFTER every compile above (engine, report
                     # lowering): the interner's constant-length max
                     # is grow-only and now complete for this snapshot
                     str_tiers=str_tiers(layout, rs.interner),
                     host_actions=host_actions,
                     host_rule_idx=np.asarray(sorted(host_actions),
                                              np.int64),
                     instance_attrs=instance_attrs,
                     deny_info=deny_info,
                     list_rules=frozenset(list_rules),
                     rbac_rules=frozenset(rbac_rules),
                     quota_actions=tuple(quota_actions),
                     fused_first_rules=frozenset(fused_first),
                     overlay_cols=np.asarray(sorted(overlay), np.int64),
                     fused_deny=len(deny_by_rule), fused_lists=len(lists),
                     item_names=item_names,
                     inst_mask=inst_mask,
                     pred_map_mask=pred_map_mask[:, :n_maps]
                     if n_maps else np.zeros((n_rows, 0), np.int8),
                     unmapped_instance_attrs=unmapped,
                     unfused_list_kinds=tuple(sorted(unfused_kinds)),
                     report_rules=frozenset(report_rules),
                     report_lowering=report_lowering)


def _split_list_instances(snapshot: Snapshot, hc, inst_names, layout,
                          unfused_kinds: set | None = None
                          ) -> tuple[list, list]:
    """(fused [(iname, value_attr)], host [iname]) for a list action.

    Fusable entry types (each with its own device lowering in
    models/policy_engine.py ListEntrySpec):
      STRINGS       — static overrides, exact case-sensitive match
      REGEX         — every pattern inside the DFA-compilable subset
                      (ops/regex_dfa); value needs a byte slot
      IP_ADDRESSES  — every entry a parseable CIDR/address; value must
                      be an IP_ADDRESS/BYTES-typed attribute
                      (string-rendered IPs keep host semantics) with a
                      byte slot
    CASE_INSENSITIVE_STRINGS and refreshable providers keep list.go's
    host semantics (mixer/adapter/list/list.go:115-247); `unfused_kinds`
    collects why an action stayed host-side (bench enumeration,
    VERDICT r3 item 3)."""
    p: Mapping[str, Any] = hc.params
    et = p.get("entry_type", "STRINGS")

    def reject(reason: str) -> tuple[list, list]:
        if unfused_kinds is not None:
            unfused_kinds.add(reason)
        return [], list(inst_names)

    if et not in _FUSABLE_LIST_TYPES:
        return reject(et)
    if p.get("provider") is not None or p.get("provider_url"):
        return reject("provider-refreshed")
    entries = p.get("overrides", ())
    if et == "STRINGS":
        if not all(isinstance(e, str) for e in entries):
            return reject("STRINGS:non-string-entries")
    elif et == "REGEX":
        from istio_tpu.ops.regex_dfa import compile_regex
        try:
            for e in entries:
                compile_regex(str(e))
        except Exception:
            return reject("REGEX:unsupported-pattern")
    elif et == "IP_ADDRESSES":
        import ipaddress
        try:
            for e in entries:
                ipaddress.ip_network(str(e), strict=False)
        except ValueError:
            return reject("IP_ADDRESSES:bad-cidr")
    from istio_tpu.attribute.types import ValueType
    fused, host = [], []
    for iname in inst_names:
        ref = snapshot.instances[iname].value_attr_ref()
        slot_ok = ref is not None and (
            ref in layout.derived_slots if isinstance(ref, tuple)
            else ref in layout.slots)
        if et in ("REGEX", "IP_ADDRESSES"):
            slot_ok = slot_ok and ref in layout.byte_slots
        if et == "IP_ADDRESSES":
            # the device compares RAW IP BYTES against binary CIDR
            # prefixes — only IP_ADDRESS-typed attrs carry those.
            # Map-derived (tuple) refs are utf-8 TEXT ("10.1.2.3");
            # fusing them would compare text bytes against binary
            # prefixes and flip verdicts — host parses instead.
            if isinstance(ref, tuple) or \
                    layout.manifest.get(ref) != ValueType.IP_ADDRESS:
                slot_ok = False
        elif not isinstance(ref, tuple) and \
                layout.manifest.get(ref) == ValueType.IP_ADDRESS:
            # STRINGS/REGEX over an IP-typed value: the host adapter
            # normalizes the bytes to a textual IP before matching
            # (list_adapter.handle_check); the device id scan interns
            # bytes and strings under different tags and the byte plane
            # carries binary — no lowering matches, keep host
            slot_ok = False
        if slot_ok:
            fused.append((iname, ref))
        else:
            host.append(iname)
            if unfused_kinds is not None:
                unfused_kinds.add(f"{et}:value-not-lowerable")
    return fused, host
