"""Check batcher — coalesce concurrent Check() calls into device steps.

The design piece with no reference counterpart (SURVEY.md §7 layer 4):
the reference evaluates per request on CPU; the TPU path amortizes one
device dispatch over a window of concurrent requests. Requests enqueue
(bag, Future); the flusher thread drains up to `max_batch` per step,
waiting at most `window_s` after the first request of a batch. Batch
shapes are BUCKETED (pad to the next power of two) so jit re-traces a
handful of shapes, not one per batch size.

p99 story: window (≤300µs) + step (~1-2ms small batches) keeps tail
latency in the BASELINE budget while throughput scales with load —
under light load a request waits at most window_s; under heavy load
batches fill instantly and the window never matters.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Sequence

from istio_tpu.attribute.bag import Bag
from istio_tpu.runtime import monitor
from istio_tpu.runtime.resilience import (DeadlineExceededError,
                                          ResourceExhaustedError,
                                          UnavailableError)

log = logging.getLogger("istio_tpu.runtime.batcher")


def default_buckets(max_batch: int) -> tuple[int, ...]:
    """Few, coarse bucket shapes: every bucket is one jit trace the
    server must pay (seconds on TPU), so a small fixed set beats
    power-of-two granularity — padding a 3-request batch to 256 rows
    costs microseconds of MXU time, a 12th trace costs seconds.
    Includes the 64-wide LATENCY TIER (profiled r4: B=64 lands under
    the 1 ms budget at 10k rules where B=256 does not) so light-load
    batches compile to a tight shape instead of padding to 256."""
    out = sorted({min(64, max_batch), min(256, max_batch), max_batch})
    return tuple(out)


def bucket_size(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def pad_to_bucket(bags, buckets: tuple[int, ...]) -> list:
    """`bags` + PadBags up to the bucket for len(bags) — the single
    home of bucket padding (batcher, BatchCheck front, fused report
    resolve). Caller chunks to buckets[-1] first; an over-bucket
    length returns the bags unpadded."""
    target = bucket_size(len(bags), buckets)
    return list(bags) + [PadBag() for _ in range(target - len(bags))]


def trim_pads(bags):
    """`bags` without their trailing PadBag rows — the single inverse
    of pad_to_bucket (padding is always appended at the tail)."""
    n = len(bags)
    while n and isinstance(bags[n - 1], PadBag):
        n -= 1
    return bags[:n] if n < len(bags) else bags


class PadBag(Bag):
    """Empty bag used to pad a batch to its bucket size."""

    # empty CompressedAttributes — keeps a padded batch on the C++
    # wire-decode path (dispatcher._check_fused requires every row to
    # carry wire bytes)
    wire = b""

    def get(self, name: str):
        return None, False

    def names(self):
        return []


class CheckBatcher:
    """check(bag) blocks until its batch's device step completes.

    `run_batch(bags) -> list[result]` is the dispatcher hook; padding
    rows are PadBags whose results are discarded.
    """

    def __init__(self, run_batch: Callable[[Sequence[Bag]], Sequence[Any]],
                 window_s: float = 0.0003, max_batch: int = 1024,
                 pipeline: int = 4,
                 buckets: tuple[int, ...] | None = None,
                 hold_at: int | None = None,
                 size_hist=None,
                 pad_batches: bool = True,
                 observe_latency: bool = True,
                 max_queue: int | None = None,
                 brownout: bool = False,
                 stage_observer: Callable[[float], None] | None = None,
                 continuous: bool = False,
                 continuous_depth: int = 2):
        self.run_batch = run_batch
        # continuous batching (the latency lane): the flusher
        # dispatches a batch the moment an in-flight slot under
        # `continuous_depth` is free — it absorbs whatever is ALREADY
        # queued but never waits for a window to expire or a batch to
        # fill. In-flight step pipelining stays bounded (default 2:
        # one step executing, one dispatching) so continuous mode
        # can't flood the device with 1-row trips while a fat batch
        # queues behind them. False = the occupancy-fill policy
        # (throughput-optimal on serialized transports).
        self.continuous = bool(continuous)
        self._continuous_depth = max(int(continuous_depth), 1)
        # deadline propagation (the adapter-executor plane): hooks
        # that accept it get the batch's min remaining deadline, so
        # host adapter actions inherit the request budget end to end
        from istio_tpu.runtime.resilience import _takes_deadline
        self._run_takes_deadline = _takes_deadline(run_batch)
        # bounded admission (DAGOR-style front-door shedding): a submit
        # that would push the queue past max_queue resolves
        # RESOURCE_EXHAUSTED instead of growing queue_wait without
        # bound. None = unbounded (the seed behavior; RuntimeServer
        # passes a cap).
        self.max_queue = max_queue if max_queue and max_queue > 0 \
            else None
        # brownout: while the LIVE p99 gauge is over the SLO target and
        # the queue is already half full, shed the newest arrivals
        # first — protecting the requests already queued instead of
        # growing everyone's tail (Tail at Scale §"latency-induced
        # brownout"). Off by default: it reads the global p99 window,
        # which is only meaningful on the check path.
        self.brownout = brownout
        self._p99_refreshed = 0.0
        # False for non-Check coalescers (the report batcher): their
        # batches must not feed the Check() stage decomposition or the
        # live p99 window
        self._observe_latency = observe_latency
        # queue-wait observer for coalescers with their OWN stage
        # decomposition (the report batcher feeds coalesce_wait into
        # the report pipeline histograms instead of the Check stages)
        self._stage_observer = stage_observer
        # False for hooks whose downstream re-pads anyway (the report
        # batcher: dispatcher._report_active_fused pads per chunk) —
        # skips allocate-then-trim churn on every light-load batch
        self._pad_batches = pad_batches
        # batch-size histogram to observe (default: the check path's;
        # the report batcher passes monitor.REPORT_BATCH_SIZE so the
        # two coalescers stay separately diagnosable)
        self._size_hist = size_hist if size_hist is not None \
            else monitor.CHECK_BATCH_SIZE
        self.window_s = window_s
        self.max_batch = max_batch
        # occupancy threshold for the adaptive window (see _loop):
        # batches accumulate while >= hold_at trips are in flight.
        # Default 1: on every rig measured (serialized tunnel,
        # 1-core CPU) fat batches beat trip overlap — host prep is
        # ~0.3ms against a 110ms tunnel trip, and concurrent steps
        # contend for the device/core anyway (CPU rig: 756/s at 1 vs
        # 520/s at 2 vs 203/s at pipeline=8). A transport that truly
        # executes trips in parallel can raise it.
        self._hold_at = max(hold_at if hold_at is not None else 1, 1)
        self.buckets = tuple(sorted(buckets)) if buckets \
            else default_buckets(max_batch)
        if self.buckets[-1] < max_batch:
            # every collectable batch size must land in a pre-warmable
            # bucket, or over-bucket batches run at arbitrary unpadded
            # shapes and re-trace in-band
            self.buckets = self.buckets + (max_batch,)
        self._queue: "queue.Queue[tuple[Bag, Future] | None]" = queue.Queue()
        # Bounded batch pipelining: the flusher hands each batch to a
        # worker and immediately starts collecting the next, so the
        # host↔device sync of batch N overlaps batch N+1's window and
        # dispatch. Essential when the device sits behind a high-RTT
        # transport (the axon TPU tunnel syncs in ~100ms); harmless
        # (slightly better tail) when colocated. pipeline=1 restores
        # strictly serial batches.
        from concurrent.futures import ThreadPoolExecutor
        self._pipeline = max(pipeline, 1)
        self._pool = ThreadPoolExecutor(max_workers=self._pipeline,
                                        thread_name_prefix="check-step")
        self._inflight = threading.Semaphore(self._pipeline)
        # occupancy counter for the adaptive window (the semaphore
        # can't be read): >0 → a device trip is in flight
        self._inflight_n = 0
        self._inflight_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop_guard,
                                        daemon=True,
                                        name="check-batcher")
        self._closed = False
        # admission stopped (graceful shutdown step 1): new submits
        # resolve typed UNAVAILABLE; queued/in-flight work drains
        self._draining = False
        # watchdog: set to the fatal exception if the flusher thread
        # ever dies — submit() then fails fast (an orphaned Future
        # would block its caller forever) and /healthz goes unhealthy
        self._dead: BaseException | None = None
        self._thread.start()

    def check(self, bag: Bag, deadline: float | None = None) -> Any:
        return self.submit(bag, deadline=deadline).result()

    def healthy(self) -> tuple[bool, str]:
        """(ok, reason) for /healthz: the flusher thread must be alive
        (or deliberately closed) and must not have died on an
        exception."""
        if self._dead is not None:
            return False, (f"check-batcher flusher died: "
                           f"{type(self._dead).__name__}: {self._dead}")
        if not self._closed and not self._thread.is_alive():
            return False, "check-batcher flusher thread not running"
        return True, ""

    def _admission_error(self, deadline: float | None
                         ) -> Exception | None:
        """Front-door shedding decision for one submit(). Returns the
        typed rejection to resolve the future with, or None to admit.
        Counter increments are gated on _observe_latency so the report
        coalescer (which shares this class) never pollutes the CHECK
        resilience counters."""
        observe = self._observe_latency
        if self._draining:
            # ordered shutdown: admission is OFF — a typed rejection
            # the fronts map to UNAVAILABLE (clients retry a peer),
            # while already-admitted work keeps draining below
            if observe:
                monitor.CHECK_SHED.labels(reason="draining").inc()
            return UnavailableError("server shutting down")
        if self._dead is not None or \
                (not self._closed and not self._thread.is_alive()):
            if observe:
                monitor.CHECK_SHED.labels(reason="batcher_dead").inc()
            return UnavailableError(
                "check batcher flusher thread is dead")
        if deadline is not None and time.perf_counter() >= deadline:
            if observe:
                monitor.CHECK_DEADLINE_EXPIRED.inc()
            return DeadlineExceededError(
                "deadline expired before enqueue")
        depth = self._queue.qsize()
        if self.max_queue is not None and depth >= self.max_queue:
            if observe:
                monitor.CHECK_SHED.labels(reason="queue_full").inc()
            return ResourceExhaustedError(
                f"check queue full ({depth} >= {self.max_queue})")
        if self.brownout and self._brownout_active(depth):
            if observe:
                monitor.CHECK_SHED.labels(reason="brownout").inc()
            return ResourceExhaustedError(
                "brownout: live p99 over SLO target, shedding newest")
        return None

    def _brownout_active(self, depth: int) -> bool:
        """Brownout trips only when BOTH hold: the queue is past its
        soft threshold (half the cap, or half a max_batch when
        uncapped) AND the live p99 gauge is over the SLO target. The
        gauge refresh (a window sort) runs at most every 50ms, never
        per submit."""
        soft = (self.max_queue // 2) if self.max_queue is not None \
            else max(self.max_batch // 2, 1)
        if depth < soft:
            return False
        now = time.perf_counter()
        if now - self._p99_refreshed > 0.05:
            self._p99_refreshed = now
            monitor.refresh_latency_gauges()
        return monitor.CHECK_P99_MS.value() > monitor.CHECK_P99_TARGET_MS

    def submit(self, bag: Bag, trace: Any = None,
               deadline: float | None = None) -> Future:
        """`trace`: the caller's root span dict (API-layer rpc.check) —
        the batch span parents under it so queue-wait is attributed to
        a request, not a batch. None captures the submitting thread's
        current span (the sync fronts, which submit inside their root
        span's `with` block). `deadline`: absolute time.perf_counter()
        instant after which this request must not be dispatched —
        expired requests resolve DEADLINE_EXCEEDED before tensorize,
        and admission-control sheds resolve RESOURCE_EXHAUSTED; both
        surface on the returned future, never as a hang."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        fut: Future = Future()
        err = self._admission_error(deadline)
        if err is not None:
            fut.set_exception(err)
            return fut
        fut._t_enq = time.perf_counter()   # queue-wait span tag
        fut._deadline = deadline
        if trace is None:
            try:
                from istio_tpu.utils import tracing
                tr = tracing.get_tracer()
                if tr.reporter is not None:
                    trace = tr._current()
            except Exception:
                trace = None   # tracing must never break submission
        fut._trace = trace
        self._queue.put((bag, fut))
        # TOCTOU vs the watchdog: the flusher may have died (and
        # drained the queue) between the admission check above and the
        # put — a future landing in a consumer-less queue would hang
        # its caller forever, the exact failure the watchdog exists to
        # prevent. InvalidStateError means the drain already got it
        # (and already counted the shed).
        if self._dead is not None:
            try:
                fut.set_exception(UnavailableError(
                    "check batcher flusher thread is dead"))
            except InvalidStateError:
                pass
            else:
                if self._observe_latency:
                    monitor.CHECK_SHED.labels(
                        reason="batcher_dead").inc()
        return fut

    def _loop_guard(self) -> None:
        """Flusher-thread watchdog: the loop must never die silently —
        an orphaned queue blocks every future submitter forever. On a
        fatal loop exception, mark the batcher dead (healthz +
        fail-fast submits) and resolve everything still queued."""
        try:
            self._loop()
        except BaseException as exc:   # noqa: BLE001 — watchdog belt
            self._dead = exc
            log.exception("check-batcher flusher thread died")
            err = UnavailableError(
                f"check batcher flusher died: "
                f"{type(exc).__name__}: {exc}")
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    return
                if item is None:
                    continue
                try:
                    item[1].set_exception(err)
                except InvalidStateError:
                    pass
                else:
                    # every client-visible rejection must show in the
                    # shed counters — an on-call diagnosing this exact
                    # incident reads them first
                    if self._observe_latency:
                        monitor.CHECK_SHED.labels(
                            reason="batcher_dead").inc()

    @staticmethod
    def _min_deadline(current: float | None, item) -> float | None:
        """Running-minimum fold over batch items' deadlines — O(1) per
        appended item (rescanning the batch per hold iteration was
        O(max_batch²) on the only flusher thread)."""
        d = getattr(item[1], "_deadline", None)
        if d is None:
            return current
        return d if current is None or d < current else current

    def _loop(self) -> None:
        """Collect batches under an OCCUPANCY-ADAPTIVE window: with
        fewer than `hold_at` trips in flight a batch sails after the
        fixed window (light-load latency = one trip), at or past that
        occupancy it keeps accumulating until a slot frees —
        dispatching a 1-row trip behind a busy transport wastes a trip
        slot the queued batch-mates then wait out (VERDICT r4 item 6:
        half of all saturation batches carried ≤2 rows while 1024
        clients were blocked). See __init__ for the hold_at default's
        measured rationale."""
        hold_at = min(self._pipeline, self._hold_at)
        depth = min(self._continuous_depth, self._pipeline)
        while True:
            item = self._queue.get()
            if item is None:
                self._drain_on_close()
                return
            batch = [item]
            dmin = self._min_deadline(None, item)
            if self.continuous:
                if self._collect_continuous(batch, dmin, depth):
                    self._flush(batch)
                    self._drain_on_close()
                    return
                self._flush(batch)
                continue
            deadline = time.perf_counter() + self.window_s
            while len(batch) < self.max_batch:
                busy = self._inflight_n >= hold_at
                timeout = deadline - time.perf_counter()
                if timeout <= 0:
                    if not busy:
                        break
                    # busy: hold, re-check occupancy — but NEVER hold a
                    # request past its deadline: flush while the
                    # earliest batch deadline still has a hold quantum
                    # of slack (flushing AT expiry would guarantee the
                    # row is shed in _run_one instead of served), and
                    # never sleep past that flush point
                    timeout = 0.002
                    if dmin is not None:
                        slack = dmin - time.perf_counter()
                        if slack <= 0.002:
                            break
                        timeout = min(timeout, slack - 0.002)
                try:
                    nxt = self._queue.get(timeout=timeout)
                except queue.Empty:
                    if busy and len(batch) < self.max_batch:
                        continue
                    break
                if nxt is None:
                    self._flush(batch)
                    self._drain_on_close()
                    return
                batch.append(nxt)
                dmin = self._min_deadline(dmin, nxt)
            self._flush(batch)

    def _collect_continuous(self, batch: list, dmin: float | None,
                            depth: int) -> bool:
        """Latency-lane collection: greedily absorb whatever is
        ALREADY queued, then dispatch the moment an in-flight slot
        under `depth` is free — never wait for fill or a window (a
        request never waits for a batch to fill). While every slot is
        busy, hold in fine quanta and keep absorbing arrivals, but
        never past the earliest row deadline. Returns True when the
        close sentinel arrived (the caller flushes, then drains)."""
        while len(batch) < self.max_batch:
            if self._inflight_n < depth:
                try:   # a step slot is free: take what's here and go
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    return False
            else:
                timeout = 0.0005
                if dmin is not None:
                    slack = dmin - time.perf_counter()
                    if slack <= 0.0005:
                        # dispatch now: _flush blocks on the pipeline
                        # semaphore at worst — holding longer would
                        # guarantee the row sheds in _run_one
                        return False
                    timeout = min(timeout, slack)
                try:
                    nxt = self._queue.get(timeout=timeout)
                except queue.Empty:
                    continue
            if nxt is None:
                return True
            batch.append(nxt)
            dmin = self._min_deadline(dmin, nxt)
        return False

    def _drain_on_close(self) -> None:
        """Requests that raced past close() must still resolve — flush
        whatever is left behind the sentinel instead of abandoning the
        futures (callers block forever otherwise)."""
        leftovers = []
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                leftovers.append(item)
        if leftovers:
            self._flush(leftovers)

    def _flush(self, batch: list[tuple[Bag, Future]]) -> None:
        self._inflight.acquire()
        with self._inflight_lock:
            self._inflight_n += 1
        try:
            self._pool.submit(self._run_one, batch)
        except BaseException as exc:
            # pool.submit can fail (shutdown race, thread-spawn
            # failure) — the in-hand futures must resolve before the
            # exception propagates to the watchdog, or their callers
            # block forever on a batch nobody owns
            with self._inflight_lock:
                self._inflight_n -= 1
            self._inflight.release()
            err = UnavailableError(
                f"check batch dispatch failed: "
                f"{type(exc).__name__}: {exc}")
            for _, fut in batch:
                try:
                    fut.set_exception(err)
                except InvalidStateError:
                    pass
                else:
                    if self._observe_latency:
                        monitor.CHECK_SHED.labels(
                            reason="batcher_dead").inc()
            raise

    def _shed_stale(self, batch: list[tuple[Bag, Future]]
                    ) -> list[tuple[Bag, Future]]:
        """Drop rows that must not reach tensorize: futures the caller
        already cancelled (an aio client disconnect — tensorizing and
        dispatching them is pure waste) and rows whose deadline expired
        in the queue (resolved DEADLINE_EXCEEDED; dispatching work the
        caller already timed out on only steals device time from live
        requests)."""
        now = time.perf_counter()
        keep: list[tuple[Bag, Future]] = []
        for bag, fut in batch:
            if fut.cancelled():
                if self._observe_latency:
                    monitor.CHECK_CANCELLED_SHED.inc()
                continue
            dl = getattr(fut, "_deadline", None)
            if dl is not None and now >= dl:
                if self._observe_latency:
                    monitor.CHECK_DEADLINE_EXPIRED.inc()
                try:
                    fut.set_exception(DeadlineExceededError(
                        "deadline expired in the check queue"))
                except InvalidStateError:
                    pass
                continue
            keep.append((bag, fut))
        return keep

    def _run_one(self, batch: list[tuple[Bag, Future]]) -> None:
        try:
            batch = self._shed_stale(batch)
            if not batch:
                return
            # flight-recorder tape (runtime/forensics.py): opened per
            # batch on this worker thread; the monitor.observe_stage
            # calls below and in the dispatcher feed it, and the
            # completion note captures a slow exemplar only when the
            # batch's slowest request crossed the threshold. Check
            # path only — report batches carry their own stages.
            if self._observe_latency:
                from istio_tpu.runtime import forensics
                forensics.RECORDER.batch_begin()
            self._size_hist.observe(len(batch))
            bags = [bag for bag, _ in batch]
            padded = pad_to_bucket(bags, self.buckets) \
                if self._pad_batches else bags
            # the span's bucket field always reports the DEVICE shape
            # (even when a downstream re-padder owns the padding) so
            # size-vs-bucket keeps measuring pad overhead
            bucket_n = len(padded) if self._pad_batches \
                else bucket_size(len(bags), self.buckets)
            # queue-wait = oldest enqueue -> batch start (decomposable
            # served latency; pkg/tracing interceptor role)
            from istio_tpu.utils import tracing
            now = time.perf_counter()
            waits = [now - t for t in
                     (getattr(f, "_t_enq", None) for _, f in batch)
                     if t is not None]
            if self._observe_latency:
                monitor.observe_stage("queue_wait",
                                      max(waits, default=0.0))
            elif self._stage_observer is not None:
                self._stage_observer(max(waits, default=0.0))
            # parent under the OLDEST request's rpc root span — the
            # request whose queue-wait the batch's wait tag reports
            parent = next((t for t in
                           (getattr(f, "_trace", None)
                            for _, f in batch) if t is not None), None)
            span_ctx = tracing.get_tracer().span(
                "serve.batch", parent=parent, size=len(batch),
                bucket=bucket_n,
                queue_wait_ms=round(max(waits, default=0.0) * 1e3, 3))
            try:
                with span_ctx:
                    if self._run_takes_deadline:
                        # min over the batch's row deadlines: the fold
                        # must never hold ANY row past its own budget
                        dmin = None
                        for _, f in batch:
                            dmin = self._min_deadline(dmin, (None, f))
                        results = self.run_batch(padded, deadline=dmin)
                    else:
                        results = self.run_batch(padded)
            except Exception as exc:
                # failed batches are excluded from the stage
                # decomposition by design — this counter is their only
                # trace in /metrics
                if self._observe_latency:
                    monitor.CHECK_BATCH_FAILURES.inc()
                for _, fut in batch:
                    try:
                        fut.set_exception(exc)
                    except InvalidStateError:
                        pass                     # caller cancelled
                return
            if len(results) < len(batch):
                # zip() would silently truncate and hang the trailing
                # callers — route a contract violation through the belt
                raise RuntimeError(
                    f"run_batch returned {len(results)} results for a "
                    f"{len(batch)}-request batch")
            # a caller may cancel its future mid-batch (an aio client
            # disconnect) — even between a cancelled() check and the
            # set; one cancelled future must never abort result
            # distribution for its batch-mates
            for (_, fut), result in zip(batch, results):
                try:
                    fut.set_result(result)
                except InvalidStateError:
                    pass
            # per-request end-to-end (enqueue -> result delivered):
            # feeds the e2e histogram + sliding-window p99 tracker
            if self._observe_latency:
                done = time.perf_counter()
                e2e_max, slow_fut = 0.0, None
                for _, fut in batch:
                    t = getattr(fut, "_t_enq", None)
                    if t is not None:
                        e2e = done - t
                        monitor.observe_check_e2e(e2e)
                        if e2e > e2e_max:
                            e2e_max, slow_fut = e2e, fut
                # one exemplar per batch at most: batch-mates share
                # the stage timeline the tape recorded above
                from istio_tpu.runtime import forensics
                forensics.RECORDER.note_batch(
                    e2e_max, len(batch),
                    getattr(slow_fut, "_trace", None))
        except Exception as exc:
            # belt over the inner handler: NO failure in batch prep or
            # result distribution may abandon the futures — an
            # unresolved future hangs its caller forever (observed r4:
            # a NameError in the tracing-span line left every request
            # of the batch timing out)
            if self._observe_latency:
                monitor.CHECK_BATCH_FAILURES.inc()
            for _, fut in batch:
                try:
                    fut.set_exception(exc)
                except InvalidStateError:
                    pass
        finally:
            with self._inflight_lock:
                self._inflight_n -= 1
            self._inflight.release()

    def stats(self) -> dict:
        """Point-in-time queue/pipeline state for the introspect
        server's /debug/queues (reference: ControlZ's process state
        pages). `oldest_wait_ms` is the head-of-queue request's age —
        the wait the NEXT batch will report."""
        oldest_wait_ms = 0.0
        with self._queue.mutex:
            depth = len(self._queue.queue)
            head = self._queue.queue[0] if self._queue.queue else None
        if head is not None:
            t = getattr(head[1], "_t_enq", None)
            if t is not None:
                oldest_wait_ms = (time.perf_counter() - t) * 1e3
        healthy, health_err = self.healthy()
        return {
            "depth": depth,
            "oldest_wait_ms": round(oldest_wait_ms, 3),
            "in_flight": self._inflight_n,
            "pipeline": self._pipeline,
            "hold_at": self._hold_at,
            "window_s": self.window_s,
            "max_batch": self.max_batch,
            "buckets": list(self.buckets),
            "closed": self._closed,
            "draining": self._draining,
            "continuous": self.continuous,
            "continuous_depth": self._continuous_depth,
            "max_queue": self.max_queue,
            "brownout": self.brownout,
            "healthy": healthy,
            "health_error": health_err,
        }

    def quiesce(self) -> None:
        """Graceful-shutdown step 1: stop admission. Every submit from
        here on resolves a typed UNAVAILABLE immediately; queued and
        in-flight batches are unaffected (drain() waits them out)."""
        self._draining = True
        from istio_tpu.runtime import forensics
        forensics.record_event(
            "quiesce",
            lane="check" if self._observe_latency else "report")

    def drain(self, deadline: float | None = 5.0) -> bool:
        """Block until the queue is empty and no batch is in flight
        (bounded by `deadline` seconds; None = wait forever). Returns
        True when fully drained — False means the deadline expired
        with work still pending (close() then resolves the leftovers,
        never abandons them)."""
        end = None if deadline is None \
            else time.perf_counter() + deadline
        while True:
            if self._dead is not None:
                return False   # watchdog already resolved the queue
            with self._queue.mutex:
                empty = not self._queue.queue
            if empty and self._inflight_n == 0:
                return True
            if end is not None and time.perf_counter() >= end:
                return False
            time.sleep(0.005)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._queue.put(None)
            self._thread.join(timeout=5)
            self._pool.shutdown(wait=True)
