"""Runtime self-metrics (reference: mixer/pkg/runtime/monitor.go:34-88
prometheus counters/histograms for resolve + dispatch).

Two registries live here by design: the prometheus_client REGISTRY
below (the reference's promhttp role) and the homegrown
`utils/metrics.py` default_registry, which carries the serving-path
STAGE decomposition added for the <1ms-p99 north star — per-batch
stage histograms (queue_wait / tensorize / h2d / device_step / fold /
respond), a per-request end-to-end histogram, and a sliding-window
live p50/p95/p99 tracker with an SLO gauge (`check_p99_under_target`)
against the 1ms target. The introspect server
(istio_tpu/introspect/) merges both into one /metrics exposition."""
from __future__ import annotations

import collections
import contextlib
import threading
import time

import prometheus_client

from istio_tpu.utils import metrics as hostmetrics

REGISTRY = prometheus_client.CollectorRegistry()

RESOLVE_COUNT = prometheus_client.Counter(
    "mixer_runtime_resolve_count", "resolution batches", registry=REGISTRY)
RESOLVE_DURATION = prometheus_client.Histogram(
    "mixer_runtime_resolve_duration_s", "resolution latency",
    registry=REGISTRY)
RESOLVE_ERRORS = prometheus_client.Counter(
    "mixer_runtime_resolve_errors", "rule predicates that errored",
    registry=REGISTRY)
DISPATCH_COUNT = prometheus_client.Counter(
    "mixer_runtime_dispatch_count", "adapter dispatches",
    registry=REGISTRY)
DISPATCH_DURATION = prometheus_client.Histogram(
    "mixer_runtime_dispatch_duration_s", "adapter dispatch latency",
    registry=REGISTRY)
DISPATCH_ERRORS = prometheus_client.Counter(
    "mixer_runtime_dispatch_errors", "adapter/instance failures",
    registry=REGISTRY)
CONFIG_GENERATION = prometheus_client.Gauge(
    "mixer_runtime_config_generation", "active snapshot revision",
    registry=REGISTRY)
CHECK_BATCH_SIZE = prometheus_client.Histogram(
    "mixer_runtime_check_batch_size", "coalesced check batch sizes",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048),
    registry=REGISTRY)
REPORT_BATCH_SIZE = prometheus_client.Histogram(
    "mixer_runtime_report_batch_size",
    "coalesced report record batch sizes (records from concurrent "
    "Report RPCs share one packed device trip)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048),
    registry=REGISTRY)
# gRPC serving-path counters (grpcServer.go's monitoring role): a
# failed perf run must be diagnosable from these alone — how many
# requests were decoded vs answered, and how batch formation went.
CHECK_REQUESTS = prometheus_client.Counter(
    "mixer_grpc_check_requests", "Check RPCs decoded", registry=REGISTRY)
CHECK_RESPONSES = prometheus_client.Counter(
    "mixer_grpc_check_responses", "Check responses sent",
    registry=REGISTRY)

# -- overload-resilience counters (runtime/resilience.py + batcher
# admission control). Per-REQUEST counts except batch_failures (per
# batch); label series are pre-touched below so every reason exposes
# at zero from the first scrape (a dashboard must distinguish "never
# shed" from "counter missing").
CHECK_SHED_REASONS = ("queue_full", "brownout", "batcher_dead",
                      "draining")
CHECK_FALLBACK_REASONS = ("breaker_open", "device_error", "fail_open")
CHECK_SHED = prometheus_client.Counter(
    "mixer_check_shed_total",
    "check requests shed by admission control (RESOURCE_EXHAUSTED / "
    "UNAVAILABLE), by reason", ["reason"], registry=REGISTRY)
CHECK_DEADLINE_EXPIRED = prometheus_client.Counter(
    "mixer_check_deadline_expired_total",
    "check requests rejected DEADLINE_EXCEEDED before tensorize",
    registry=REGISTRY)
CHECK_FALLBACK = prometheus_client.Counter(
    "mixer_check_fallback_total",
    "check requests answered off the device path (CPU oracle "
    "fallback, or fail-open OK), by reason", ["reason"],
    registry=REGISTRY)
CHECK_BATCH_FAILURES = prometheus_client.Counter(
    "mixer_check_batch_failures_total",
    "check batches that failed outright (excluded from the stage "
    "decomposition by design — this counter is their only trace)",
    registry=REGISTRY)
CHECK_CANCELLED_SHED = prometheus_client.Counter(
    "mixer_check_cancelled_shed_total",
    "check rows dropped at batch build because the caller already "
    "cancelled (aio client disconnect)", registry=REGISTRY)
CHECK_DEVICE_RETRIES = prometheus_client.Counter(
    "mixer_check_device_retries_total",
    "device check steps retried after a transient failure",
    registry=REGISTRY)
BREAKER_STATE = prometheus_client.Gauge(
    "mixer_check_breaker_state",
    "device circuit breaker state: 0=closed 1=half_open 2=open",
    registry=REGISTRY)
BREAKER_TRANSITIONS = prometheus_client.Counter(
    "mixer_check_breaker_transitions_total",
    "device circuit breaker state transitions, by target state",
    ["to"], registry=REGISTRY)
for _r in CHECK_SHED_REASONS:
    CHECK_SHED.labels(reason=_r)
for _r in CHECK_FALLBACK_REASONS:
    CHECK_FALLBACK.labels(reason=_r)
for _s in ("closed", "half_open", "open"):
    BREAKER_TRANSITIONS.labels(to=_s)


def resilience_counters() -> dict:
    """Resilience counter snapshot as one JSON-able dict — read by
    /debug/resilience, the chaos smoke and bench.py (per served
    scenario, so overload behavior lands in the BENCH artifact)."""
    shed = {r: int(CHECK_SHED.labels(reason=r)._value.get())
            for r in CHECK_SHED_REASONS}
    fb = {r: int(CHECK_FALLBACK.labels(reason=r)._value.get())
          for r in CHECK_FALLBACK_REASONS}
    return {
        "shed": shed,
        "shed_total": sum(shed.values()),
        "expired_total": int(CHECK_DEADLINE_EXPIRED._value.get()),
        "fallback": fb,
        "fallback_total": sum(fb.values()),
        "batch_failures_total": int(CHECK_BATCH_FAILURES._value.get()),
        "cancelled_shed_total": int(CHECK_CANCELLED_SHED._value.get()),
        "device_retries_total": int(CHECK_DEVICE_RETRIES._value.get()),
        "breaker_state": int(BREAKER_STATE._value.get()),
    }


# -- adapter-executor plane (runtime/executor.py) --------------------
#
# Conservation invariant (the report plane's doctrine applied to host
# actions): every host adapter call SUBMITTED to the executor resolves
# with EXACTLY one outcome — ok (adapter result used), error (adapter
# exception → safeDispatch INTERNAL), shed (bulkhead queue full /
# closed lane), expired (request deadline gone before the wait),
# overrun (still running at the deadline → fail-policy verdict),
# breaker_open (lane breaker short-circuit) — so
# submitted == sum(outcomes) holds at quiescence. A worker finishing
# an action the fold already abandoned counts late_{ok,error}
# SEPARATELY: late results are accounting, never verdicts.
HOST_ACTION_OUTCOMES = ("ok", "error", "shed", "expired", "overrun",
                        "breaker_open")
HOST_ACTIONS_SUBMITTED = hostmetrics.default_registry.counter(
    "mixer_host_actions_submitted_total",
    "host adapter calls submitted to the executor plane, by handler")
HOST_ACTIONS = hostmetrics.default_registry.counter(
    "mixer_host_actions_total",
    "host adapter calls resolved, by handler and outcome (see "
    "runtime/monitor.py HOST_ACTION_OUTCOMES)")
HOST_ACTION_SECONDS = hostmetrics.default_registry.histogram(
    "mixer_host_action_seconds",
    "wall seconds of completed host adapter calls, by handler")
HOST_ACTION_LATE = hostmetrics.default_registry.counter(
    "mixer_host_action_late_total",
    "host adapter calls completing AFTER their fold abandoned them "
    "(outcome already counted overrun/expired), by handler and result")
HOST_ACTION_RETRIES = hostmetrics.default_registry.counter(
    "mixer_host_action_retries_total",
    "host adapter calls retried after a transient exception")
HOST_ACTIONS_SUBMITTED.inc(0)   # zero-series before the first action
HOST_ACTIONS.inc(0)
HOST_ACTION_LATE.inc(0)
HOST_ACTION_RETRIES.inc(0)

# provider refresh (the executor's maintenance lane driving
# list_adapter's TTL loop): attempts vs failures + per-provider age
# in /debug/executor — a stale list must be visible, not silent
LIST_REFRESH_TOTAL = prometheus_client.Counter(
    "mixer_list_provider_refresh_total",
    "list provider refresh attempts (maintenance lane)",
    registry=REGISTRY)
LIST_REFRESH_FAILURES = prometheus_client.Counter(
    "mixer_list_provider_refresh_failures",
    "list provider refresh attempts that failed (the last good list "
    "keeps serving)", registry=REGISTRY)


def note_host_action_submitted(handler: str) -> None:
    HOST_ACTIONS_SUBMITTED.inc(1, handler=handler)


def note_host_action(handler: str, outcome: str,
                     seconds: float | None = None) -> None:
    """One resolved host action (runtime/executor.AdapterExecutor.
    resolve — the single accounting home)."""
    HOST_ACTIONS.inc(1, handler=handler, outcome=outcome)
    if seconds is not None:
        HOST_ACTION_SECONDS.observe(seconds, handler=handler)


def note_host_action_late(handler: str, result: str) -> None:
    HOST_ACTION_LATE.inc(1, handler=handler, result=result)


def note_host_action_retry(handler: str) -> None:
    HOST_ACTION_RETRIES.inc(1, handler=handler)


def host_action_counters() -> dict:
    """Executor-plane counter snapshot as one JSON-able dict — read by
    /debug/executor, the executor smoke and bench.py. `exact` is the
    conservation check (True whenever nothing is in flight)."""
    by_handler: dict[str, dict] = {}
    submitted_total = 0
    with HOST_ACTIONS_SUBMITTED._lock:
        sub = dict(HOST_ACTIONS_SUBMITTED._values)
    for labels, v in sub.items():
        h = dict(labels).get("handler")
        if h is None:
            continue
        by_handler.setdefault(h, {"submitted": 0, "outcomes": {}})
        by_handler[h]["submitted"] += int(v)
        submitted_total += int(v)
    resolved_total = 0
    outcome_totals = {o: 0 for o in HOST_ACTION_OUTCOMES}
    with HOST_ACTIONS._lock:
        res = dict(HOST_ACTIONS._values)
    for labels, v in res.items():
        lab = dict(labels)
        h, o = lab.get("handler"), lab.get("outcome")
        if h is None or o is None:
            continue
        by_handler.setdefault(h, {"submitted": 0, "outcomes": {}})
        by_handler[h]["outcomes"][o] = \
            by_handler[h]["outcomes"].get(o, 0) + int(v)
        outcome_totals[o] = outcome_totals.get(o, 0) + int(v)
        resolved_total += int(v)
    late = {"ok": 0, "error": 0}
    with HOST_ACTION_LATE._lock:
        for labels, v in dict(HOST_ACTION_LATE._values).items():
            r = dict(labels).get("result")
            if r in late:
                late[r] += int(v)
    with HOST_ACTION_RETRIES._lock:
        retries = sum(int(v) for labels, v in
                      dict(HOST_ACTION_RETRIES._values).items()
                      if dict(labels).get("handler") is not None)
    return {
        "submitted": submitted_total,
        "resolved": resolved_total,
        "in_flight": submitted_total - resolved_total,
        "outcomes": outcome_totals,
        "late": late,
        "retries": retries,
        "by_handler": by_handler,
        "exact": submitted_total == resolved_total,
        "refresh_total": int(LIST_REFRESH_TOTAL._value.get()),
        "refresh_failures": int(LIST_REFRESH_FAILURES._value.get()),
    }


# -- tail-latency forensics plane (runtime/forensics.py) --------------
#
# Two bounded rings back the forensics surfaces: the flight recorder's
# slow-request exemplar ring (/debug/slow) and the mesh event timeline
# (/debug/events). Overflow on either is bounded AND typed — the
# dropped family below is zero-shaped per ring before the first drop
# (a dashboard must distinguish "never dropped" from "counter
# missing"), exactly the promtext doctrine the shed counters follow.
FORENSICS_RINGS = ("slow", "events")
FORENSICS_DROPPED = prometheus_client.Counter(
    "mixer_forensics_dropped_total",
    "forensics ring entries evicted by overflow, by ring "
    "(slow = flight-recorder exemplars, events = mesh event "
    "timeline)", ["ring"], registry=REGISTRY)
FORENSICS_SLOW = prometheus_client.Counter(
    "mixer_forensics_slow_exemplars_total",
    "slow-request exemplars captured by the flight recorder "
    "(one per over-threshold batch)", registry=REGISTRY)
FORENSICS_EVENTS = prometheus_client.Counter(
    "mixer_forensics_events_total",
    "control-plane events recorded on the mesh event timeline",
    registry=REGISTRY)
for _r in FORENSICS_RINGS:
    FORENSICS_DROPPED.labels(ring=_r)


def note_forensics_drop(ring: str) -> None:
    if ring not in FORENSICS_RINGS:
        ring = "slow"
    FORENSICS_DROPPED.labels(ring=ring).inc()


def forensics_counters() -> dict:
    """Forensics counter snapshot as one JSON-able dict — read by
    /debug/slow, the forensics smoke and bench.py (per served
    scenario: tail_* keys delta against a baseline of this)."""
    return {
        "slow_captured": int(FORENSICS_SLOW._value.get()),
        "events_recorded": int(FORENSICS_EVENTS._value.get()),
        "dropped": {r: int(FORENSICS_DROPPED.labels(
            ring=r)._value.get()) for r in FORENSICS_RINGS},
    }


# -- secure plane: workload identity + mTLS admission ----------------
#
# Lifecycle counters for the WorkloadIdentity rotation loop
# (istio_tpu/secure/identity.py) and the mTLS admission boundary on
# the serving fronts. Zero-shaped per the promtext doctrine: every
# (event, outcome) series exposes at 0 from the first scrape.
IDENTITY_EVENTS_KINDS = ("issue", "rotate", "expiry")
IDENTITY_OUTCOMES = ("ok", "failed")
IDENTITY_EVENTS = prometheus_client.Counter(
    "mixer_identity_events_total",
    "workload-identity lifecycle transitions (issue = first obtain, "
    "rotate = renewal, expiry = cert died before renewal), by "
    "outcome", ["event", "outcome"], registry=REGISTRY)
IDENTITY_UNAUTHENTICATED = prometheus_client.Counter(
    "mixer_identity_unauthenticated_total",
    "requests rejected typed UNAUTHENTICATED at strict-mTLS "
    "admission (no verified peer SPIFFE identity)",
    registry=REGISTRY)
IDENTITY_AUTHENTICATED = prometheus_client.Counter(
    "mixer_identity_authenticated_checks_total",
    "check admissions whose attribute bag carried a verified peer "
    "SPIFFE identity (source.user from the client cert)",
    registry=REGISTRY)
for _e in IDENTITY_EVENTS_KINDS:
    for _o in IDENTITY_OUTCOMES:
        IDENTITY_EVENTS.labels(event=_e, outcome=_o)


def note_identity(event: str, outcome: str) -> None:
    if event not in IDENTITY_EVENTS_KINDS:
        event = "issue"
    if outcome not in IDENTITY_OUTCOMES:
        outcome = "failed"
    IDENTITY_EVENTS.labels(event=event, outcome=outcome).inc()


def identity_counters() -> dict:
    """Secure-plane counter snapshot — /debug/identity, the mtls
    smoke and bench.py secure_* keys read this."""
    events = {e: {o: int(IDENTITY_EVENTS.labels(
        event=e, outcome=o)._value.get())
        for o in IDENTITY_OUTCOMES} for e in IDENTITY_EVENTS_KINDS}
    return {
        "events": events,
        "rotations_ok": events["rotate"]["ok"],
        "unauthenticated_total":
            int(IDENTITY_UNAUTHENTICATED._value.get()),
        "authenticated_checks_total":
            int(IDENTITY_AUTHENTICATED._value.get()),
    }


# -- end-to-end Check() latency decomposition ------------------------
#
# Stage semantics (one observation per BATCH per stage; e2e is one
# observation per REQUEST, so sum-of-stage-sums <= sum-of-e2e holds
# whenever batches carry >= 1 request):
#   queue_wait  — oldest enqueue -> batch start (batcher) or entry ->
#                 dispatch (pre-batched check_many fronts)
#   tensorize   — bags -> AttributeBatch (+ns ids), host side
#   h2d         — host->device staging + program dispatch (the
#                 non-blocking half of the device call)
#   device_step — the blocking device->host pull (program execution +
#                 transfer; carries the full transport RTT)
#   fold        — packed-plane decode: overlay bits, referenced /
#                 presence signature dedup
#   respond     — per-row CheckResponse construction
CHECK_STAGES = ("queue_wait", "tensorize", "h2d", "device_step",
                "fold", "respond")
CHECK_P99_TARGET_MS = 1.0   # BASELINE north star: <1ms p99 at 10k rules

CHECK_STAGE_SECONDS = hostmetrics.default_registry.histogram(
    "mixer_check_stage_seconds",
    "per-batch serving stage latency (label: stage)")
CHECK_E2E_SECONDS = hostmetrics.default_registry.histogram(
    "mixer_check_e2e_seconds",
    "per-request served check latency, enqueue to response")
CHECK_WINDOW = hostmetrics.SlidingWindow(4096)
CHECK_P50_MS = hostmetrics.default_registry.gauge(
    "mixer_check_p50_ms", "sliding-window served check p50 (ms)")
CHECK_P95_MS = hostmetrics.default_registry.gauge(
    "mixer_check_p95_ms", "sliding-window served check p95 (ms)")
CHECK_P99_MS = hostmetrics.default_registry.gauge(
    "mixer_check_p99_ms", "sliding-window served check p99 (ms)")
CHECK_SLO_GAUGE = hostmetrics.default_registry.gauge(
    "check_p99_under_target",
    f"1 when the sliding-window check p99 is under the "
    f"{CHECK_P99_TARGET_MS}ms target (vacuously 1 while the window is "
    f"empty — mask alerts on mixer_check_e2e_seconds_count), else 0")


# forensics stage tap (runtime/forensics.py registers the flight
# recorder's thread-local tape here at import): every check stage
# observation ALSO lands on the open batch tape, so the recorder needs
# no second set of timers on the hot path. None until forensics loads.
_STAGE_TAP = None


def set_stage_tap(fn) -> None:
    global _STAGE_TAP
    _STAGE_TAP = fn


def observe_stage(stage: str, seconds: float) -> None:
    CHECK_STAGE_SECONDS.observe(seconds, stage=stage)
    if _STAGE_TAP is not None:
        _STAGE_TAP(stage, seconds)


def observe_check_e2e(seconds: float) -> None:
    """Per-request end-to-end observation; gauges refresh lazily via
    refresh_latency_gauges() (sorting the window per request would put
    an O(n log n) on the hot path)."""
    CHECK_E2E_SECONDS.observe(seconds)
    CHECK_WINDOW.observe(seconds)


def refresh_latency_gauges() -> dict:
    """Recompute the sliding-window percentile gauges + SLO gauge from
    the current window. Called by scrape-rate readers (the introspect
    /metrics handler, bench, the smoke script) — never per request."""
    p50, p95, p99 = CHECK_WINDOW.quantiles((0.50, 0.95, 0.99))
    p50_ms, p95_ms, p99_ms = p50 * 1e3, p95 * 1e3, p99 * 1e3
    CHECK_P50_MS.set(p50_ms)
    CHECK_P95_MS.set(p95_ms)
    CHECK_P99_MS.set(p99_ms)
    # empty window → vacuously under target: an idle/fresh server is
    # not violating its SLO, and alerting on ==0 must not fire before
    # the first request (mask on the e2e count for 'no data')
    under = not len(CHECK_WINDOW) or p99_ms <= CHECK_P99_TARGET_MS
    CHECK_SLO_GAUGE.set(1.0 if under else 0.0)
    return {"p50_ms": p50_ms, "p95_ms": p95_ms, "p99_ms": p99_ms,
            "n_window": len(CHECK_WINDOW),
            "n_total": CHECK_WINDOW.total,
            "target_ms": CHECK_P99_TARGET_MS,
            "under_target": under}


def reset_latency_window() -> None:
    """Drop windowed observations (bench scenario boundaries — a
    saturation phase's queueing tail must not pollute the light
    phase's live p99). Histograms keep accumulating; only the
    sliding-window gauges reset."""
    CHECK_WINDOW.reset()


def stage_baseline() -> dict:
    """Subtraction token for latency_snapshot(since=...): the stage +
    e2e histogram states at a window's start. The histograms are
    process-lifetime cumulative (prometheus semantics); per-SCENARIO
    readings (bench phases) must delta against a baseline or the
    previous phase's ~10k batches drown the window's few hundred."""
    token = {stage: CHECK_STAGE_SECONDS.state(stage=stage)
             for stage in CHECK_STAGES}
    token["__e2e__"] = CHECK_E2E_SECONDS.state()
    return token


def _delta(state, base):
    counts, total, n = state
    bcounts, btotal, bn = base
    if bcounts:
        counts = [c - b for c, b in zip(counts, bcounts)] \
            if counts else []
    return counts, total - btotal, n - bn


def latency_snapshot(since: dict | None = None) -> dict:
    """Stage decomposition + live percentiles as one JSON-able dict —
    what bench.py appends to the BENCH artifact after each served
    scenario and /debug/queues consumers read. `since`: a
    stage_baseline() token; readings then cover only the window after
    it (quantiles computed from delta bucket counts)."""
    from istio_tpu.utils.metrics import quantile_from_counts

    empty = ([], 0.0, 0)
    stages: dict[str, dict] = {}
    h = CHECK_STAGE_SECONDS
    for stage in CHECK_STAGES:
        counts, total, n = h.state(stage=stage)
        if since is not None:
            counts, total, n = _delta((counts, total, n),
                                      since.get(stage, empty))
        if not n:
            continue
        stages[stage] = {
            "count": n,
            "sum_ms": round(total * 1e3, 3),
            "p50_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.5) * 1e3, 3),
            "p99_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.99) * 1e3, 3),
        }
    e2e = CHECK_E2E_SECONDS.state()
    if since is not None:
        e2e = _delta(e2e, since.get("__e2e__", empty))
    return {
        "stages": stages,
        "e2e_count": e2e[2],
        "e2e_sum_ms": round(e2e[1] * 1e3, 3),
        "live": refresh_latency_gauges(),
    }


def serving_counters() -> dict:
    """Snapshot of the serving-path counters as a plain dict (emitted
    into bench artifacts on success AND failure)."""
    hist: dict[str, int] = {}
    for i, b in enumerate(CHECK_BATCH_SIZE._upper_bounds):
        # prometheus_client stores per-bucket (non-cumulative) counts
        cur = int(CHECK_BATCH_SIZE._buckets[i].get())
        label = "inf" if b == float("inf") else str(int(b))
        if cur:
            hist[label] = cur
    decoded = int(CHECK_REQUESTS._value.get())
    sent = int(CHECK_RESPONSES._value.get())
    return {
        "requests_decoded": decoded,
        "responses_sent": sent,
        "in_flight": decoded - sent,
        "batches_formed": sum(hist.values()),
        "batch_rows": int(CHECK_BATCH_SIZE._sum.get()),
        "batch_size_hist": hist,
        "report_batch_rows": int(REPORT_BATCH_SIZE._sum.get()),
        "report_batches_formed": int(
            REPORT_BATCH_SIZE._buckets and sum(
                int(b.get()) for b in REPORT_BATCH_SIZE._buckets)),
    }


# -- telemetry ingestion plane (the REPORT half of Mixer's API) -------
#
# Stage semantics, mirroring the six-stage Check() decomposition above
# (one observation per unit of pipeline work; counts differ by design —
# wire_decode is per-RPC, coalesce_wait/tensorize/device_field_eval/
# intern_decode per coalesced batch/chunk, adapter_dispatch per
# dispatched batch):
#   wire_decode       — ReportRequest parse + per-record delta decode
#                       into bags (front side, per RPC)
#   coalesce_wait     — oldest record's enqueue -> batch start in the
#                       cross-RPC record coalescer (the report batcher)
#   tensorize         — record bags -> AttributeBatch (+ns ids)
#   device_field_eval — the packed_report device trip (rule resolve +
#                       every instance-field expression for every
#                       record in one pull)
#   intern_decode     — pulled id planes -> Python values (one
#                       unique-id pass per chunk) + seal
#   adapter_dispatch  — host adapter fan-out (handle_report calls)
REPORT_STAGES = ("wire_decode", "coalesce_wait", "tensorize",
                 "device_field_eval", "intern_decode",
                 "adapter_dispatch")

REPORT_STAGE_SECONDS = hostmetrics.default_registry.histogram(
    "mixer_report_stage_seconds",
    "per-unit report ingestion stage latency (label: stage; see "
    "runtime/monitor.py REPORT_STAGES for unit semantics)")

# Record conservation (the ingestion plane's correctness invariant):
# every record entering the plane ends in EXACTLY one of exported /
# rejected, so accepted == exported + rejected holds at quiescence and
# in_flight = accepted - exported - rejected is never negative.
# Unlabeled counters expose at zero from the first scrape; the labeled
# rejection family pre-touches its reasons below.
REPORT_REJECT_REASONS = ("queue_full", "unavailable", "deadline",
                         "error")
REPORT_REQUESTS = prometheus_client.Counter(
    "mixer_grpc_report_requests", "Report RPCs decoded (all fronts)",
    registry=REGISTRY)
REPORT_RESPONSES = prometheus_client.Counter(
    "mixer_grpc_report_responses",
    "Report responses sent (all fronts)", registry=REGISTRY)
REPORT_RECORDS_ACCEPTED = prometheus_client.Counter(
    "mixer_report_records_accepted_total",
    "report records entering the ingestion plane (pre-admission; "
    "conservation: accepted == exported + rejected at quiescence)",
    registry=REGISTRY)
REPORT_RECORDS_EXPORTED = prometheus_client.Counter(
    "mixer_report_records_exported_total",
    "report records whose batch completed adapter dispatch",
    registry=REGISTRY)
REPORT_RECORDS_REJECTED = prometheus_client.Counter(
    "mixer_report_records_rejected_total",
    "report records resolved with a typed rejection, by reason "
    "(queue_full=RESOURCE_EXHAUSTED shed, unavailable=draining/dead "
    "coalescer, deadline, error=batch failure)", ["reason"],
    registry=REGISTRY)
for _r in REPORT_REJECT_REASONS:
    REPORT_RECORDS_REJECTED.labels(reason=_r)

# per-template record counts (label appears on first dispatch; the
# family itself zero-exposes via the homegrown registry's counter)
REPORT_TEMPLATE_RECORDS = hostmetrics.default_registry.counter(
    "mixer_report_template_records_total",
    "report instances dispatched to adapters, by template")
REPORT_TEMPLATE_RECORDS.inc(0)   # zero-series before the first record

# adapter-export accounting, by exporter (qualified handler name):
# records delivered, drops (handler exceptions — safeDispatch absorbs
# them, this is their only trace besides the log), last dispatch wall
# seconds. Queue depth for the plane is the coalescer's (the export
# fan-out runs inside the report batch; /debug/report joins both).
REPORT_EXPORTER_RECORDS = hostmetrics.default_registry.counter(
    "mixer_report_exporter_records_total",
    "report instances delivered per exporter (qualified handler name)")
REPORT_EXPORTER_DROPS = hostmetrics.default_registry.counter(
    "mixer_report_exporter_drops_total",
    "report dispatches dropped by adapter exceptions, per exporter")
REPORT_EXPORTER_LAG_MS = hostmetrics.default_registry.gauge(
    "mixer_report_exporter_last_dispatch_ms",
    "wall milliseconds of the exporter's most recent handle_report")
REPORT_EXPORTER_RECORDS.inc(0)
REPORT_EXPORTER_DROPS.inc(0)
REPORT_EXPORTER_LAG_MS.set(0.0)

# recent drop reasons (bounded; /debug/report's "what got rejected
# lately" pane — a typed shed the client saw must be explainable from
# the server side without log spelunking)
_REPORT_DROPS: collections.deque = collections.deque(maxlen=32)
_REPORT_DROPS_LOCK = threading.Lock()

# per-exporter point-in-time stats for /debug/report (the counter
# families above are the scrape surface; this dict carries the
# JSON-able view: wall stamps don't belong in counters)
_EXPORTER_STATS: dict = {}


def observe_report_stage(stage: str, seconds: float) -> None:
    REPORT_STAGE_SECONDS.observe(seconds, stage=stage)


def report_accepted(n: int = 1) -> None:
    REPORT_RECORDS_ACCEPTED.inc(n)


def report_exported(n: int = 1) -> None:
    REPORT_RECORDS_EXPORTED.inc(n)


def report_rejected(n: int, reason: str, detail: str = "") -> None:
    if reason not in REPORT_REJECT_REASONS:
        reason = "error"
    REPORT_RECORDS_REJECTED.labels(reason=reason).inc(n)
    with _REPORT_DROPS_LOCK:
        _REPORT_DROPS.append({
            "wall": time.time(), "reason": reason,
            "records": int(n), "detail": detail[:200]})


def report_record_done(fut) -> None:
    """Single accounting home for coalesced report records: attached
    as a done-callback to every future the report coalescer returns,
    so every accepted record is counted exported or typed-rejected
    EXACTLY once — the conservation invariant is enforced where
    futures resolve, not re-derived per code path."""
    from istio_tpu.runtime import resilience

    try:
        exc = fut.exception()
    except BaseException as cancel:   # cancelled futures carry no exc
        report_rejected(1, "error",
                        f"cancelled: {type(cancel).__name__}")
        return
    if exc is None:
        report_exported(1)
    elif isinstance(exc, resilience.ResourceExhaustedError):
        report_rejected(1, "queue_full", str(exc))
    elif isinstance(exc, resilience.DeadlineExceededError):
        report_rejected(1, "deadline", str(exc))
    elif isinstance(exc, resilience.UnavailableError):
        report_rejected(1, "unavailable", str(exc))
    else:
        report_rejected(1, "error",
                        f"{type(exc).__name__}: {exc}")


def note_adapter_export(exporter: str, template: str, n_records: int,
                        seconds: float, error: bool = False) -> None:
    """One adapter handle_report outcome (dispatcher.report)."""
    if error:
        REPORT_EXPORTER_DROPS.inc(1, exporter=exporter)
    else:
        REPORT_EXPORTER_RECORDS.inc(n_records, exporter=exporter)
    REPORT_EXPORTER_LAG_MS.set(seconds * 1e3, exporter=exporter)
    with _REPORT_DROPS_LOCK:
        st = _EXPORTER_STATS.setdefault(exporter, {
            "records": 0, "drops": 0, "last_dispatch_ms": 0.0,
            "last_wall": 0.0, "templates": {}})
        if error:
            st["drops"] += 1
        else:
            st["records"] += n_records
            st["templates"][template] = \
                st["templates"].get(template, 0) + n_records
        st["last_dispatch_ms"] = round(seconds * 1e3, 3)
        st["last_wall"] = time.time()


def report_conservation(since: dict | None = None) -> dict:
    """The invariant, readable: accepted == exported + rejected at
    quiescence; in_flight is the (transient) difference. `exact` is
    True only when the plane is fully drained — the form the smoke
    gate and shutdown assertions check. `since`: a previous
    report_conservation() reading — the counters are process-lifetime
    cumulative, so per-scenario checks (bench phases, tests sharing a
    process) must delta against their own baseline."""
    accepted = int(REPORT_RECORDS_ACCEPTED._value.get())
    exported = int(REPORT_RECORDS_EXPORTED._value.get())
    rejected = {r: int(REPORT_RECORDS_REJECTED.labels(
        reason=r)._value.get()) for r in REPORT_REJECT_REASONS}
    if since is not None:
        accepted -= since.get("accepted", 0)
        exported -= since.get("exported", 0)
        base_rej = since.get("rejected", {})
        rejected = {r: v - base_rej.get(r, 0)
                    for r, v in rejected.items()}
    rej_total = sum(rejected.values())
    return {
        "accepted": accepted,
        "exported": exported,
        "rejected": rejected,
        "rejected_total": rej_total,
        "in_flight": accepted - exported - rej_total,
        "exact": accepted == exported + rej_total,
    }


def report_stage_baseline() -> dict:
    """Subtraction token for report_latency_snapshot(since=...) — same
    delta-window discipline as stage_baseline()."""
    return {stage: REPORT_STAGE_SECONDS.state(stage=stage)
            for stage in REPORT_STAGES}


def report_latency_snapshot(since: dict | None = None) -> dict:
    """Six-stage report pipeline decomposition (p50/p95/p99 per stage)
    as one JSON-able dict — what /debug/report serves and bench.py
    scrapes into the BENCH artifact per served scenario."""
    from istio_tpu.utils.metrics import quantile_from_counts

    empty = ([], 0.0, 0)
    stages: dict[str, dict] = {}
    h = REPORT_STAGE_SECONDS
    for stage in REPORT_STAGES:
        counts, total, n = h.state(stage=stage)
        if since is not None:
            counts, total, n = _delta((counts, total, n),
                                      since.get(stage, empty))
        if not n:
            continue
        stages[stage] = {
            "count": n,
            "sum_ms": round(total * 1e3, 3),
            "p50_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.5) * 1e3, 3),
            "p95_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.95) * 1e3, 3),
            "p99_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.99) * 1e3, 3),
        }
    return {"stages": stages}


def report_counters() -> dict:
    """Ingestion-plane snapshot for /debug/report and bench artifacts:
    conservation + per-template record counts + per-exporter stats +
    recent drop reasons. Always JSON-able; zero-shaped before the
    first record (the view must serve on an idle server)."""
    with _REPORT_DROPS_LOCK:
        drops = list(_REPORT_DROPS)
        exporters = {k: {**v, "templates": dict(v["templates"])}
                     for k, v in _EXPORTER_STATS.items()}
    templates = {}
    with REPORT_TEMPLATE_RECORDS._lock:   # snapshot vs live inc()s
        tmpl_values = dict(REPORT_TEMPLATE_RECORDS._values)
    for labels, v in tmpl_values.items():
        name = dict(labels).get("template")
        if name:
            templates[name] = int(v)
    return {
        "rpcs_decoded": int(REPORT_REQUESTS._value.get()),
        "responses_sent": int(REPORT_RESPONSES._value.get()),
        "conservation": report_conservation(),
        "templates": templates,
        "exporters": exporters,
        "recent_drops": drops,
    }


# -- sharded serving plane (istio_tpu/sharding) ----------------------
#
# Stage semantics (one observation per unit of router work;
# bank_check is per (batch, bank) so a batch spanning B banks
# contributes B observations — the device-trip fan-out IS the cost
# being attributed):
#   shard_dispatch — namespace extraction + row bucketing, per batch
#   bank_check     — one bank's full fused check on its sub-batch
#                    (tensorize → device trip → overlay, the existing
#                    CHECK stages decompose it further)
#   fold           — response scatter back into row order + bank-local
#                    → global deny-index remap, per batch
SHARD_STAGES = ("shard_dispatch", "bank_check", "fold")

SHARD_STAGE_SECONDS = hostmetrics.default_registry.histogram(
    "mixer_shard_stage_seconds",
    "per-batch sharded-serving stage latency (label: stage; see "
    "runtime/monitor.py SHARD_STAGES for unit semantics)")
REPLICA_BATCH_SECONDS = hostmetrics.default_registry.histogram(
    "mixer_replica_batch_seconds",
    "per-replica served batch wall seconds (label: replica)")
REPLICA_ROWS = hostmetrics.default_registry.counter(
    "mixer_replica_rows_total",
    "check rows served per replica lane (label: replica)")
REPLICA_ROWS.inc(0)   # zero-series before the first routed batch


def observe_shard_stage(stage: str, seconds: float) -> None:
    SHARD_STAGE_SECONDS.observe(seconds, stage=stage)


def observe_replica_batch(replica: int, seconds: float,
                          rows: int) -> None:
    REPLICA_BATCH_SECONDS.observe(seconds, replica=str(replica))
    REPLICA_ROWS.inc(rows, replica=str(replica))


def shard_stage_baseline() -> dict:
    """Subtraction token for shard_latency_snapshot(since=...) — the
    same delta-window discipline as stage_baseline() (the fleet bench
    reads per-scenario stage attribution, not process-lifetime)."""
    return {stage: SHARD_STAGE_SECONDS.state(stage=stage)
            for stage in SHARD_STAGES}


def shard_latency_snapshot(since: dict | None = None) -> dict:
    """Sharded-path stage decomposition (count/sum/p50/p99 per stage)
    as one JSON-able dict — /debug/shards' `stages` pane and the fleet
    bench's per-stage attribution."""
    from istio_tpu.utils.metrics import quantile_from_counts

    empty = ([], 0.0, 0)
    stages: dict[str, dict] = {}
    h = SHARD_STAGE_SECONDS
    for stage in SHARD_STAGES:
        counts, total, n = h.state(stage=stage)
        if since is not None:
            counts, total, n = _delta((counts, total, n),
                                      since.get(stage, empty))
        if not n:
            continue
        stages[stage] = {
            "count": n,
            "sum_ms": round(total * 1e3, 3),
            "p50_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.5) * 1e3, 3),
            "p99_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.99) * 1e3, 3),
        }
    return {"stages": stages}


def replica_snapshot() -> dict:
    """Per-replica batch latency + row counts for /debug/shards —
    zero-shaped ({} lanes) before the first routed batch."""
    from istio_tpu.utils.metrics import quantile_from_counts

    out: dict[str, dict] = {}
    h = REPLICA_BATCH_SECONDS
    for lab in h.label_sets():
        rep = lab.get("replica")
        if rep is None:
            continue
        counts, total, n = h.state(replica=rep)
        if not n:
            continue
        out[rep] = {
            "batches": n,
            "sum_ms": round(total * 1e3, 3),
            "p50_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.5) * 1e3, 3),
            "p95_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.95) * 1e3, 3),
            "p99_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.99) * 1e3, 3),
        }
    return out


# -- pilot discovery serving plane (istio_tpu/pilot/discovery.py) -----
#
# Stage semantics (one observation per unit of discovery work; the
# units differ by design — the decomposition's job is attributing a
# slow publish or a slow poll to its stage):
#   snapshot_build — registry/config freeze + per-namespace content
#                    digests + per-host indexes, per publish
#   scope_plan     — namespace→shard delta planning (sharding/planner
#                    reuse), per publish
#   invalidate     — snapshot diff + scoped cache sweep + shard
#                    version bumps/wakeups, per publish
#   route_eval     — ONE batched source-admission device step shared
#                    by every pending node group (route_nfa.
#                    RouteScopeProgram.admit_rows), per batch
#   generate       — config JSON assembly + cache fill, per batch of
#                    node groups
#   serve          — cache lookup → response bytes, per endpoint call
DISCOVERY_STAGES = ("snapshot_build", "scope_plan", "invalidate",
                    "route_eval", "generate", "serve")

DISCOVERY_STAGE_SECONDS = hostmetrics.default_registry.histogram(
    "pilot_discovery_stage_seconds",
    "per-unit discovery serving stage latency (label: stage; see "
    "runtime/monitor.py DISCOVERY_STAGES for unit semantics)")
DISCOVERY_PUSH_FANOUT_SECONDS = hostmetrics.default_registry.histogram(
    "pilot_discovery_push_fanout_seconds",
    "delta-push fan-out latency: snapshot publish -> a parked "
    "version-watcher waking with the new generation (only watchers "
    "already waiting when the publish landed count — a late watcher "
    "measures its own arrival, not the push)")
# cache events, zero-shaped per the promtext doctrine: a dashboard
# must distinguish "never invalidated" from "counter missing".
#   hit/miss     — per endpoint call against the current generation
#   carried      — entries re-stamped to a new generation because
#                  their namespace deps did NOT change (the scoped-
#                  invalidation win, counted per publish sweep)
#   invalidated  — entries dropped by a publish sweep
DISCOVERY_CACHE_EVENTS = ("hit", "miss", "carried", "invalidated")
DISCOVERY_CACHE = hostmetrics.default_registry.counter(
    "pilot_discovery_cache_events_total",
    "discovery response-cache events, by event (hit/miss per call, "
    "carried/invalidated per publish sweep)")
DISCOVERY_GENERATION = hostmetrics.default_registry.gauge(
    "pilot_discovery_generation",
    "active discovery snapshot generation")
for _e in DISCOVERY_CACHE_EVENTS:
    DISCOVERY_CACHE.inc(0, event=_e)


def observe_discovery_stage(stage: str, seconds: float) -> None:
    DISCOVERY_STAGE_SECONDS.observe(seconds, stage=stage)


def observe_discovery_push(seconds: float) -> None:
    DISCOVERY_PUSH_FANOUT_SECONDS.observe(seconds)


def note_discovery_cache(event: str, n: int = 1) -> None:
    if n:
        DISCOVERY_CACHE.inc(n, event=event)


def set_discovery_generation(version: int) -> None:
    DISCOVERY_GENERATION.set(float(version))


def discovery_stage_baseline() -> dict:
    """Subtraction token for discovery_latency_snapshot(since=...) —
    the same delta-window discipline as stage_baseline()."""
    token = {stage: DISCOVERY_STAGE_SECONDS.state(stage=stage)
             for stage in DISCOVERY_STAGES}
    token["__push__"] = DISCOVERY_PUSH_FANOUT_SECONDS.state()
    return token


def discovery_latency_snapshot(since: dict | None = None) -> dict:
    """Discovery stage decomposition + push fan-out percentiles as one
    JSON-able dict — /debug/discovery's `stages` pane and the bench's
    per-scenario attribution."""
    from istio_tpu.utils.metrics import quantile_from_counts

    empty = ([], 0.0, 0)
    stages: dict[str, dict] = {}
    h = DISCOVERY_STAGE_SECONDS
    for stage in DISCOVERY_STAGES:
        counts, total, n = h.state(stage=stage)
        if since is not None:
            counts, total, n = _delta((counts, total, n),
                                      since.get(stage, empty))
        if not n:
            continue
        stages[stage] = {
            "count": n,
            "sum_ms": round(total * 1e3, 3),
            "p50_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.5) * 1e3, 3),
            "p99_ms": round(quantile_from_counts(
                h.buckets, counts, n, 0.99) * 1e3, 3),
        }
    ph = DISCOVERY_PUSH_FANOUT_SECONDS
    counts, total, n = ph.state()
    if since is not None:
        counts, total, n = _delta((counts, total, n),
                                  since.get("__push__", empty))
    push = {"count": n}
    if n:
        push.update({
            "p50_ms": round(quantile_from_counts(
                ph.buckets, counts, n, 0.5) * 1e3, 3),
            "p99_ms": round(quantile_from_counts(
                ph.buckets, counts, n, 0.99) * 1e3, 3),
        })
    return {"stages": stages, "push": push}


def discovery_cache_counters(since: dict | None = None) -> dict:
    """Cache-event snapshot (+hit_rate) as one JSON-able dict — read
    by /debug/discovery, the discovery smoke and bench.py. `since`: a
    previous reading (the counters are process-lifetime cumulative;
    per-scenario rates must delta against their own baseline)."""
    out = {}
    with DISCOVERY_CACHE._lock:
        vals = dict(DISCOVERY_CACHE._values)
    for e in DISCOVERY_CACHE_EVENTS:
        out[e] = 0
    for labels, v in vals.items():
        e = dict(labels).get("event")
        if e in out:
            out[e] += int(v)
    if since is not None:
        for e in DISCOVERY_CACHE_EVENTS:
            out[e] -= int(since.get(e, 0))
    calls = out["hit"] + out["miss"]
    out["hit_rate"] = round(out["hit"] / calls, 4) if calls else None
    out["generation"] = int(DISCOVERY_GENERATION.value())
    return out


# -- mesh audit plane (runtime/audit.py) -------------------------------
# Families for the background invariant auditor. Zero-shaped per the
# promtext doctrine: every (invariant, status) series a dashboard can
# alert on must exist BEFORE the first evaluation — "no audit data" and
# "audit never ran" are different incidents.
AUDIT_INVARIANTS = ("report_conservation", "check_accounting",
                    "quota_conservation", "grant_coherence",
                    "plane_agreement", "routing_conservation")
AUDIT_STATUSES = ("ok", "degraded", "violated")
FAULT_KINDS = ("wedge", "device", "oracle", "adapter", "quota",
               "discovery")

AUDIT_CHECKS = prometheus_client.Counter(
    "mixer_audit_checks", "audit evaluations per invariant per verdict",
    ["invariant", "status"], registry=REGISTRY)
AUDIT_VIOLATIONS = prometheus_client.Counter(
    "mixer_audit_violations",
    "transitions of an invariant into the violated state",
    ["invariant"], registry=REGISTRY)
AUDIT_EVALUATIONS = prometheus_client.Counter(
    "mixer_audit_evaluations", "full auditor passes", registry=REGISTRY)
AUDIT_HEALTHY = prometheus_client.Gauge(
    "mixer_audit_healthy",
    "1 while no mesh invariant is in the violated state (the "
    "/readyz-adjacent audit verdict)", registry=REGISTRY)
FAULT_INJECTIONS = prometheus_client.Counter(
    "mixer_fault_explainability_injections",
    "chaos injections registered with the explainability scorer",
    ["kind"], registry=REGISTRY)
FAULT_MATCHED = prometheus_client.Counter(
    "mixer_fault_explainability_matched",
    "chaos injections matched to a forensics exemplar/event in window",
    ["kind"], registry=REGISTRY)
FAULT_EXPLAINABILITY = prometheus_client.Gauge(
    "mixer_fault_explainability_rate",
    "matched / (matched + expired-unmatched) chaos injections; "
    "vacuously 1.0 with no injections", registry=REGISTRY)
for _inv in AUDIT_INVARIANTS:
    AUDIT_VIOLATIONS.labels(invariant=_inv)
    for _st in AUDIT_STATUSES:
        AUDIT_CHECKS.labels(invariant=_inv, status=_st)
for _k in FAULT_KINDS:
    FAULT_INJECTIONS.labels(kind=_k)
    FAULT_MATCHED.labels(kind=_k)
AUDIT_HEALTHY.set(1.0)
FAULT_EXPLAINABILITY.set(1.0)


def audit_counters() -> dict:
    """One JSON-able reading of the audit + explainability families —
    read by /debug/audit, the audit smoke and bench.py."""
    checks = {inv: {st: int(AUDIT_CHECKS.labels(
        invariant=inv, status=st)._value.get())
        for st in AUDIT_STATUSES} for inv in AUDIT_INVARIANTS}
    return {
        "evaluations": int(AUDIT_EVALUATIONS._value.get()),
        "healthy": bool(AUDIT_HEALTHY._value.get() >= 1.0),
        "checks": checks,
        "violations": {inv: int(AUDIT_VIOLATIONS.labels(
            invariant=inv)._value.get()) for inv in AUDIT_INVARIANTS},
        "explainability_rate": float(
            FAULT_EXPLAINABILITY._value.get()),
        "injections": {k: int(FAULT_INJECTIONS.labels(
            kind=k)._value.get()) for k in FAULT_KINDS},
        "matched": {k: int(FAULT_MATCHED.labels(
            kind=k)._value.get()) for k in FAULT_KINDS},
    }


@contextlib.contextmanager
def resolve_timer():
    RESOLVE_COUNT.inc()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        RESOLVE_DURATION.observe(time.perf_counter() - t0)


@contextlib.contextmanager
def dispatch_timer():
    DISPATCH_COUNT.inc()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        DISPATCH_DURATION.observe(time.perf_counter() - t0)
