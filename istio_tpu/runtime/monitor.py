"""Runtime self-metrics (reference: mixer/pkg/runtime/monitor.go:34-88
prometheus counters/histograms for resolve + dispatch)."""
from __future__ import annotations

import contextlib
import time

import prometheus_client

REGISTRY = prometheus_client.CollectorRegistry()

RESOLVE_COUNT = prometheus_client.Counter(
    "mixer_runtime_resolve_count", "resolution batches", registry=REGISTRY)
RESOLVE_DURATION = prometheus_client.Histogram(
    "mixer_runtime_resolve_duration_s", "resolution latency",
    registry=REGISTRY)
RESOLVE_ERRORS = prometheus_client.Counter(
    "mixer_runtime_resolve_errors", "rule predicates that errored",
    registry=REGISTRY)
DISPATCH_COUNT = prometheus_client.Counter(
    "mixer_runtime_dispatch_count", "adapter dispatches",
    registry=REGISTRY)
DISPATCH_DURATION = prometheus_client.Histogram(
    "mixer_runtime_dispatch_duration_s", "adapter dispatch latency",
    registry=REGISTRY)
DISPATCH_ERRORS = prometheus_client.Counter(
    "mixer_runtime_dispatch_errors", "adapter/instance failures",
    registry=REGISTRY)
CONFIG_GENERATION = prometheus_client.Gauge(
    "mixer_runtime_config_generation", "active snapshot revision",
    registry=REGISTRY)
CHECK_BATCH_SIZE = prometheus_client.Histogram(
    "mixer_runtime_check_batch_size", "coalesced check batch sizes",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048),
    registry=REGISTRY)
REPORT_BATCH_SIZE = prometheus_client.Histogram(
    "mixer_runtime_report_batch_size",
    "coalesced report record batch sizes (records from concurrent "
    "Report RPCs share one packed device trip)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048),
    registry=REGISTRY)
# gRPC serving-path counters (grpcServer.go's monitoring role): a
# failed perf run must be diagnosable from these alone — how many
# requests were decoded vs answered, and how batch formation went.
CHECK_REQUESTS = prometheus_client.Counter(
    "mixer_grpc_check_requests", "Check RPCs decoded", registry=REGISTRY)
CHECK_RESPONSES = prometheus_client.Counter(
    "mixer_grpc_check_responses", "Check responses sent",
    registry=REGISTRY)


def serving_counters() -> dict:
    """Snapshot of the serving-path counters as a plain dict (emitted
    into bench artifacts on success AND failure)."""
    hist: dict[str, int] = {}
    for i, b in enumerate(CHECK_BATCH_SIZE._upper_bounds):
        # prometheus_client stores per-bucket (non-cumulative) counts
        cur = int(CHECK_BATCH_SIZE._buckets[i].get())
        label = "inf" if b == float("inf") else str(int(b))
        if cur:
            hist[label] = cur
    decoded = int(CHECK_REQUESTS._value.get())
    sent = int(CHECK_RESPONSES._value.get())
    return {
        "requests_decoded": decoded,
        "responses_sent": sent,
        "in_flight": decoded - sent,
        "batches_formed": sum(hist.values()),
        "batch_rows": int(CHECK_BATCH_SIZE._sum.get()),
        "batch_size_hist": hist,
        "report_batch_rows": int(REPORT_BATCH_SIZE._sum.get()),
        "report_batches_formed": int(
            REPORT_BATCH_SIZE._buckets and sum(
                int(b.get()) for b in REPORT_BATCH_SIZE._buckets)),
    }


@contextlib.contextmanager
def resolve_timer():
    RESOLVE_COUNT.inc()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        RESOLVE_DURATION.observe(time.perf_counter() - t0)


@contextlib.contextmanager
def dispatch_timer():
    DISPATCH_COUNT.inc()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        DISPATCH_DURATION.observe(time.perf_counter() - t0)
