"""Fused per-plane SLO scorecard (the /debug/slo view).

Each serving plane already publishes its own latency/lag families;
this module folds their CURRENT readings into one verdict table so a
single scrape answers "is the mesh meeting its targets, and which
plane is missing". Verdict vocabulary per plane:

  ok       the plane's reading is inside its target
  miss     the reading exists and is outside the target
  no_data  the plane has served nothing in its window (a fresh boot
           or an unused plane — distinct from a miss on purpose)

`overall` is the worst plane verdict (miss > ok > no_data). Pure
reads: gauges, ledgers and the event ring — never the hot path.
"""
from __future__ import annotations

import time
from typing import Any

# targets for planes that don't carry their own (the check plane's
# 1ms target lives in monitor.CHECK_P99_TARGET_MS)
REPORT_DISPATCH_TARGET_MS = 250.0
DISCOVERY_PUSH_P99_TARGET_MS = 50.0


def _worst(verdicts: list[str]) -> str:
    if "miss" in verdicts:
        return "miss"
    if "ok" in verdicts:
        return "ok"
    return "no_data"


def scorecard(monitor: Any, forensics: Any, *,
              audit: dict | None = None,
              discovery: Any = None) -> dict:
    planes: dict[str, dict] = {}

    # check wire p99 vs the latency plane's target
    lat = monitor.refresh_latency_gauges()
    if lat.get("n_window", 0) <= 0:
        planes["check_wire"] = {"verdict": "no_data", **lat}
    else:
        planes["check_wire"] = {
            "verdict": "ok" if lat.get("under_target") else "miss",
            **lat}

    # report export: dispatch wall of the slowest exporter + the
    # conservation ledger's in-flight volume
    cons = monitor.report_conservation()
    lag = monitor.REPORT_EXPORTER_LAG_MS
    with lag._lock:
        lags = {",".join(f"{k}={v}" for k, v in labels) or "_": val
                for labels, val in lag._values.items()}
    worst_lag = max(lags.values(), default=0.0)
    if cons["accepted"] == 0:
        verdict = "no_data"
    else:
        verdict = "ok" if worst_lag <= REPORT_DISPATCH_TARGET_MS \
            else "miss"
    planes["report_export"] = {
        "verdict": verdict,
        "worst_dispatch_ms": round(worst_lag, 3),
        "target_ms": REPORT_DISPATCH_TARGET_MS,
        "accepted": cons["accepted"], "exported": cons["exported"],
        "in_flight": cons["in_flight"]}

    # discovery push fan-out p99
    try:
        push = monitor.discovery_latency_snapshot()["push"]
    except Exception:
        push = {"count": 0}
    if not push.get("count"):
        planes["discovery_push"] = {"verdict": "no_data", **push}
    else:
        p99 = push.get("p99_ms", 0.0)
        planes["discovery_push"] = {
            "verdict": "ok" if p99 <= DISCOVERY_PUSH_P99_TARGET_MS
            else "miss",
            "target_ms": DISCOVERY_PUSH_P99_TARGET_MS, **push}
    if discovery is not None:
        try:
            planes["discovery_push"]["generation"] = \
                discovery.version()
        except Exception:
            pass

    # quota flush age: informational freshness — an idle pool has no
    # target to miss, but a quota-bearing incident wants "when did
    # counters last flush" one scrape away
    flushes = forensics.EVENTS.snapshot(kind="quota_flush", limit=1)
    if not flushes:
        planes["quota_flush"] = {"verdict": "no_data"}
    else:
        planes["quota_flush"] = {
            "verdict": "ok",
            "age_s": round(time.time() - flushes[0]["wall"], 3),
            "items": flushes[0].get("detail", {}).get("items")}

    # the audit plane's own verdicts: invariants + explainability
    if audit is None:
        planes["audit"] = {"verdict": "no_data"}
    else:
        rate = audit.get("explainability", {}).get("rate", 1.0)
        healthy = bool(audit.get("healthy", True))
        planes["audit"] = {
            "verdict": "ok" if healthy and rate >= 1.0 else "miss",
            "healthy": healthy,
            "explainability_rate": rate,
            "violated": [c["name"] for c in audit.get("checks", ())
                         if c["status"] == "violated"]}

    return {"overall": _worst([p["verdict"] for p in planes.values()]),
            "planes": planes}
